"""Generate the committed SentencePiece fixture tests/data/sp/tiny.model.

Deterministic (no RNG): a small unigram vocab with control pieces, word
and subword pieces, single letters, and the full <0x00>..<0xFF> byte
table (byte_fallback=True — the llama tokenizer.model shape). Scores make
longer pieces win Viterbi where available. The bytes follow the public
sentencepiece_model.proto field numbers (llm/sp_model.py
write_model_proto), so a real `sentencepiece` install loads this file
unchanged — the parity test in tests/test_sp_tokenizer.py runs wherever
that package exists.

Run: python tools/make_sp_fixture.py  (rewrites the fixture in place)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.llm.sp_model import (BYTE, CONTROL, NORMAL, UNKNOWN,
                                     write_model_proto)

WORDS = ["▁the", "▁quick", "▁brown", "▁fox", "▁jumps", "▁over", "▁lazy",
         "▁dog", "▁hello", "▁wor", "ld", "▁t", "he", "ll", "o", "er",
         "ing", "▁a", "▁of", "un", "re"]
LETTERS = [chr(c) for c in range(ord("a"), ord("z") + 1)] + ["▁"]


def build() -> bytes:
    pieces = [("<unk>", 0.0, UNKNOWN),
              ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(w, -2.0 - 0.01 * i, NORMAL) for i, w in enumerate(WORDS)]
    pieces += [(c, -8.0, NORMAL) for c in LETTERS]
    pieces += [(f"<0x{b:02X}>", -20.0, BYTE) for b in range(256)]
    return write_model_proto(pieces, unk_id=0, bos_id=1, eos_id=2,
                             pad_id=-1, byte_fallback=True,
                             add_dummy_prefix=True)


def main() -> None:
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "data", "sp", "tiny.model")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "wb") as f:
        f.write(build())
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")


if __name__ == "__main__":
    main()
