"""Prefill-interference measurement: the physical quantity behind the
reference's "+30% throughput/GPU from disaggregation" claim
(reference docs/architecture.md:57), measured for THIS hardware.

On a TPU core, programs serialize — a prefill dispatch time-slices the
decode stream rather than contending for execution units the way
co-resident CUDA kernels do. So the disagg win on TPU decomposes into
measurable terms, and this tool measures them all on-chip with the
chained-dispatch slope protocol (the only trusted meter over the
tunnel, KNOWN_ISSUES.md):

  1. t_step(B): decode step time at the serving batch.
  2. t_pf(ISL): one prompt's prefill program time, swept over ISL.
  3. The interleave check: a chain alternating [prefill, K-step decode]
     must cost t_pf + K*t_step (serialization additivity; if it costs
     MORE, there is real cross-dispatch interference — cache/HBM
     residency effects — and the excess is reported).

From these, steady state (every slot serves ISL prefill + GEN decode):
  mixed chip decode tok/s  = B*GEN / (B*t_pf + GEN*t_step)
  split decode chip tok/s  = B / t_step      (prefill moved off-chip)
and the decode-slot STALL a co-located prefill injects (the ITL spike a
user sees) is t_pf itself.

Usage: python tools/interference_bench.py [isl ...]   (default 512 2048 4096)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import EngineConfig, bench_model_config
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.sampling import make_slot_keys
    from dynamo_tpu.utils.timing import slope_per_unit

    isls = [int(a) for a in sys.argv[1:]] or [512, 2048, 4096]
    B = int(os.environ.get("IB_BATCH", "32"))
    GEN = int(os.environ.get("IB_GEN", "256"))
    mcfg = bench_model_config(os.environ.get("IB_MODEL", "1b"))
    max_isl = max(isls)
    bs = 16
    max_len = max_isl + GEN + 64
    bps = (max_len + bs - 1) // bs
    ecfg = EngineConfig(
        max_model_len=max_len, kv_block_size=bs,
        num_kv_blocks=B * bps + (max_isl + bs - 1) // bs + 4,
        max_num_seqs=B,
        prefill_buckets=sorted(set(isls)), decode_steps_per_dispatch=16,
        quantization="int8")
    core = EngineCore(mcfg, ecfg, attn_impl="auto",
                      param_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    K = ecfg.decode_steps_per_dispatch

    # occupy all B slots mid-decode at seq ~= 512 (KV-realistic)
    for i in range(B):
        blocks = core.kv_manager.pool.alloc_uninit(bps)
        table = np.zeros((core.M,), np.int32)
        table[:len(blocks)] = blocks
        core._block_tables[i, :] = table
        core._tokens[i] = 7
        core._positions[i] = 512
    temp = jnp.asarray(np.full((B,), 0.7, np.float32))
    topk = jnp.asarray(np.zeros((B,), np.int32))
    topp = jnp.asarray(np.ones((B,), np.float32))
    seeds = jnp.asarray(np.zeros((B,), np.int64))
    planned, pmask = core._planned_zero
    key = make_slot_keys(0, jnp.asarray([0]), jnp.asarray(0))[0]

    def decode_dispatch(toks_in):
        steps0 = jnp.asarray(np.full((B,), 512, np.int64))
        toks, _lp, core.kv = core._decode_k_jit(
            core.params, core.kv, toks_in,
            jnp.asarray(np.full((B,), 512, np.int32)),
            jnp.array(core._block_tables), seeds, steps0,
            temp, topk, topp, planned, pmask)
        return toks[-1]

    def prefill_dispatch(isl, prompt, table):
        tok, _lp, core.kv = core._prefill_jit(
            core.params, core.kv, prompt, table,
            jnp.asarray(0, jnp.int32), jnp.asarray(isl, jnp.int32),
            key, jnp.asarray(0.7, jnp.float32), jnp.asarray(0, jnp.int32),
            jnp.asarray(1.0, jnp.float32))
        return tok

    t0h = jnp.asarray(core._tokens.copy())

    def chain_decode(m):
        toks = t0h
        t0 = time.monotonic()
        for _ in range(m):
            toks = decode_dispatch(toks)
        np.asarray(toks)
        return time.monotonic() - t0

    # warm + measure decode
    chain_decode(2)
    t_dispatch = slope_per_unit(chain_decode, 4, 16, reps=3)
    t_step = t_dispatch / K

    out = {"B": B, "GEN": GEN, "K": K,
           "t_step_ms": round(t_step * 1e3, 3),
           "decode_only_tok_per_s": round(B / t_step, 1),
           "isl": {}}
    # ONE scratch block run reused by every ISL's prefill probe
    blocks = core.kv_manager.pool.alloc_uninit((max_isl + bs - 1) // bs)
    assert blocks is not None, "scratch blocks"
    table = np.zeros((core.M,), np.int32)
    table[:len(blocks)] = blocks
    table_j = jnp.asarray(table)
    for isl in isls:
        prompt = jnp.asarray(
            rng.integers(1, mcfg.vocab_size, isl).astype(np.int32))

        def chain_pf(m, prompt=prompt, table_j=table_j, isl=isl):
            t0 = time.monotonic()
            tok = None
            for _ in range(m):
                tok = prefill_dispatch(isl, prompt, table_j)
            np.asarray(tok)
            return time.monotonic() - t0

        chain_pf(2)
        t_pf = slope_per_unit(chain_pf, 2, 8, reps=3)

        def chain_mixed(m, prompt=prompt, table_j=table_j, isl=isl):
            toks = t0h
            t0 = time.monotonic()
            for _ in range(m):
                prefill_dispatch(isl, prompt, table_j)
                toks = decode_dispatch(toks)
            np.asarray(toks)
            return time.monotonic() - t0

        chain_mixed(2)
        t_mixed = slope_per_unit(chain_mixed, 2, 8, reps=3)
        excess = t_mixed - (t_pf + t_dispatch)

        mixed_rate = B * GEN / (B * t_pf + GEN * t_step)
        out["isl"][isl] = {
            "t_pf_ms": round(t_pf * 1e3, 2),
            "itl_spike_ms": round(t_pf * 1e3, 2),
            "interleave_excess_ms": round(excess * 1e3, 2),
            "interleave_excess_pct": round(
                100 * excess / (t_pf + t_dispatch), 1),
            "mixed_decode_tok_per_s": round(mixed_rate, 1),
            "split_decode_gain": round((B / t_step) / mixed_rate, 2),
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
