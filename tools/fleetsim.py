#!/usr/bin/env python
"""fleetsim: run fleet-scale co-simulation scenarios (docs/fleet_sim.md).

The discrete-event fleet simulator (dynamo_tpu/sim/) drives the REAL
control plane — SLA planner, KV router, disagg-threshold retune, fabric
admission gate — against hundreds of simulated replicas on a virtual
clock. This CLI runs one named scenario and prints its report.

Examples:

    python tools/fleetsim.py --list
    python tools/fleetsim.py --scenario scale_storm --seed 3
    python tools/fleetsim.py --scenario baseline_hour --replicas 300 \\
        --duration 7200 --report out.json --event-log events.jsonl
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "export-trace":
        # ROADMAP fleet-sim extension (b): collected production traces →
        # a Workload.load_jsonl-compatible trace the simulator replays
        return export_trace_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="fleetsim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--scenario", default=None,
                   help="scenario name (see --list)")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and exit")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=None,
                   help="override the scenario's replica count")
    p.add_argument("--duration", type=float, default=None,
                   help="override the scenario's duration (virtual s)")
    p.add_argument("--report", default=None,
                   help="write the full report JSON here")
    p.add_argument("--event-log", default=None,
                   help="write the deterministic event log (JSONL) here")
    p.add_argument("--json", action="store_true",
                   help="print the report as one JSON line (tooling mode)")
    args = p.parse_args(argv)

    from dynamo_tpu.sim.scenarios import SCENARIOS

    if args.list or args.scenario is None:
        print("scenarios:")
        for name, sc in SCENARIOS.items():
            print(f"  {name:16s} {sc.description}")
        return 0
    if args.scenario not in SCENARIOS:
        print(f"unknown scenario {args.scenario!r}; --list shows them",
              file=sys.stderr)
        return 2

    overrides = {}
    if args.replicas is not None:
        overrides["replicas"] = args.replicas
    if args.duration is not None:
        overrides["duration_s"] = args.duration

    report = _run(args.scenario, args.seed, overrides, args.event_log)

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        _print_report(report)
    return 1 if report["violations"] else 0


def _run(name: str, seed: int, overrides: dict, event_log_path):
    """run_scenario, optionally capturing the event log to a file (the
    capture rides the same deterministic JSONL serialization the digest
    is computed over)."""
    from dynamo_tpu.sim import scenarios as _sc
    from dynamo_tpu.sim.clock import REAL_PERF_COUNTER, run_simulation
    from dynamo_tpu.sim.fleet import SimFleet

    sc = _sc.SCENARIOS[name]
    cfg, wl, faults, run_s = sc.build(seed, **overrides)

    async def main_coro():
        fleet = await SimFleet(cfg, seed=seed).start()
        t0 = REAL_PERF_COUNTER()
        await fleet.run(wl, faults=faults, duration_s=run_s)
        report = fleet.report(wall_s=REAL_PERF_COUNTER() - t0)
        report["scenario"] = name
        report["slo"]["late_attainment"] = round(
            _sc._late_attainment(fleet, cfg.slo), 4)
        report["violations"] = sc.check(fleet, report)
        log_bytes = fleet.log.to_jsonl_bytes() if event_log_path else None
        await fleet.stop()
        return report, log_bytes

    report, log_bytes = run_simulation(main_coro)
    if event_log_path:
        with open(event_log_path, "wb") as f:
            f.write(log_bytes)
    return report


def _print_report(r: dict) -> None:
    req = r["requests"]
    lat = r["latency_ms"]
    print(f"scenario {r['scenario']} seed={r['seed']}  "
          f"virtual {r['virtual_s']:.0f}s  wall {r.get('wall_s', 0):.1f}s")
    print(f"  replicas  start={r['replicas']['start']} "
          f"peak={r['replicas']['peak']} end={r['replicas']['end']}")
    print(f"  requests  arrived={req['arrived']} "
          f"completed={req['completed']} dropped={req['dropped']} "
          f"retried={req['retried']} remote_prefill={req['remote_prefills']}")
    p50 = lat["ttft_p50"]
    p90 = lat["ttft_p90"]
    p99 = lat["ttft_p99"]
    print(f"  ttft_ms   p50={p50 and round(p50)} p90={p90 and round(p90)} "
          f"p99={p99 and round(p99)}  attainment="
          f"{r['slo']['ttft_attainment']} (late "
          f"{r['slo'].get('late_attainment')})")
    print(f"  router    hit_rate={r['router']['hit_rate_blocks']} "
          f"kv_events={r['router']['kv_events']} "
          f"fabric_fetch_blocks={r['router']['fabric_fetch_blocks']}")
    if "planner" in r:
        c = {k: v for k, v in r["planner"]["counters"].items() if v}
        print(f"  planner   {c} disagg_threshold="
              f"{r['planner']['disagg_threshold']}")
    print(f"  events    {r['events']}  digest "
          f"{r['event_log_digest'][:16]}…")
    if r["violations"]:
        print("  VIOLATIONS:")
        for v in r["violations"]:
            print(f"    - {v}")
    else:
        print("  checks    all expectations held")


def traces_to_workload(trace_dicts, *, default_osl: int = 16,
                       tenant: str = "t00"):
    """Collected trace dicts (runtime/tracing.py ``Trace.to_dict``
    shape — what workers publish on the ``trace_events`` subject and
    the collector stores per tree member) → a sim Workload.

    Per trace tree (grouped on ``trace_id``): arrival ``at`` is the
    origin wall clock relative to the earliest trace in the set; ``rid``
    the request id; ``isl``/``osl``/``tenant``/``session`` come from
    the worker trace's ``engine.finish`` marker attrs
    (llm/engines/jax_engine.py stamps them — tenant/session from
    nvext.tenant/nvext.session_id via PreprocessedRequest), with
    ``engine.prefill``'s suffix+hit as the isl fallback. Traces with no
    token counts at all are skipped (returned count). Session turns are
    reconstructed per session in arrival order, so exported workloads
    PRESERVE tenant and prefix-reuse structure (ROADMAP sim item (d));
    traces predating the tenant/session attrs fall back to the CLI
    ``tenant`` label with one session per request."""
    from dynamo_tpu.sim.workload import RequestSpec, Workload

    trees = {}
    for d in trace_dicts:
        tid = d.get("trace_id")
        if tid:
            trees.setdefault(tid, []).append(d)
    specs, skipped = [], 0
    origin0 = min((min(m.get("origin_ts", 0.0) or 0.0 for m in ms)
                   for ms in trees.values()), default=0.0)
    rows = []
    for tid, members in sorted(trees.items()):
        isl = osl = None
        rid = None
        at = None
        r_tenant = r_session = None
        for m in sorted(members, key=lambda x: x.get("origin_offset_ms",
                                                     0.0)):
            rid = rid or m.get("request_id")
            if at is None and m.get("origin_ts"):
                at = float(m["origin_ts"]) - origin0
            spans = {s["name"]: s for s in m.get("spans", ())}
            fin = spans.get("engine.finish", {}).get("attrs", {})
            if isl is None and fin.get("isl") is not None:
                isl = int(fin["isl"])
            if osl is None and fin.get("osl") is not None:
                osl = int(fin["osl"])
            if r_tenant is None and fin.get("tenant"):
                r_tenant = str(fin["tenant"])
            if r_session is None and fin.get("session"):
                r_session = str(fin["session"])
            pf = spans.get("engine.prefill", {}).get("attrs", {})
            if isl is None and pf.get("suffix") is not None:
                isl = int(pf.get("suffix", 0)) + int(pf.get("hit", 0))
        if isl is None or not rid:
            skipped += 1
            continue
        rows.append((max(at or 0.0, 0.0), str(rid), r_tenant, r_session,
                     max(int(isl), 1),
                     max(int(osl if osl is not None else default_osl), 1)))
    # session turns in arrival order (the prefix-reuse structure the
    # sim's HashCatalog chains on)
    turn_of: dict = {}
    for at, rid, r_tenant, r_session, isl, osl in sorted(rows):
        t = r_tenant or tenant
        session = r_session or f"{t}-{rid}"
        turn = turn_of.get(session, -1) + 1
        turn_of[session] = turn
        specs.append(RequestSpec(
            at=round(at, 6), rid=rid, tenant=t, session=session,
            turn=turn, isl=isl, osl=osl))
    return Workload(specs), skipped


def export_trace_main(argv) -> int:
    """``fleetsim export-trace``: trace-collector dumps → a replayable
    workload JSONL (sim/workload.py Workload.load_jsonl format).

    Input: a JSON file holding a LIST of trace dicts (or {"traces":
    [...]}): e.g. the members of ``GET /traces/{id}`` trees, or traces
    captured straight off the ``trace_events`` subject. Output rides
    Workload.save_jsonl, so load_jsonl round-trips it verbatim."""
    p = argparse.ArgumentParser(
        prog="fleetsim export-trace",
        description=export_trace_main.__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--traces", required=True,
                   help="JSON file: list of collected trace dicts")
    p.add_argument("--out", required=True,
                   help="workload JSONL to write "
                        "(Workload.load_jsonl-compatible)")
    p.add_argument("--tenant", default="t00",
                   help="tenant label stamped on every request")
    p.add_argument("--default-osl", type=int, default=16,
                   help="osl for traces whose finish marker predates "
                        "the isl/osl attrs")
    args = p.parse_args(argv)
    with open(args.traces) as f:
        raw = json.load(f)
    if isinstance(raw, dict):
        raw = raw.get("traces", [])
    wl, skipped = traces_to_workload(raw, default_osl=args.default_osl,
                                     tenant=args.tenant)
    wl.save_jsonl(args.out)
    print(f"exported {len(wl)} request(s) to {args.out}"
          + (f" ({skipped} trace(s) skipped: no token counts)"
             if skipped else ""))
    return 0 if len(wl) else 2


if __name__ == "__main__":
    sys.exit(main())
