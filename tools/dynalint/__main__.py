"""CLI: ``python -m tools.dynalint`` from the repo root.

Exit codes: 0 = clean (or suppressed-only), 1 = unbaselined findings,
2 = usage/config error.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

from .engine import (DEFAULT_SCAN_ROOTS, changed_closure, load_context,
                     run_lint, write_baseline)


def _git_changed_files(root: str, base: str = None) -> set:
    """Repo-relative changed files: worktree + staged diffs (vs ``base``
    when given) plus untracked files. Raises on a non-git tree."""
    def lines(*args):
        out = subprocess.run(["git", *args], cwd=root, text=True,
                             capture_output=True, check=True)
        return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]

    changed = set()
    diff_base = [base] if base else []
    changed.update(lines("diff", "--name-only", *diff_base))
    if not base:
        changed.update(lines("diff", "--name-only", "--cached"))
    changed.update(lines("ls-files", "--others", "--exclude-standard"))
    return changed


def _run_ruff(root: str) -> int:
    """Optional satellite pass: `ruff check` under the curated ruff.toml
    when the binary exists (the container may not ship it — report and
    skip cleanly, never fail the lint on a missing tool)."""
    ruff = shutil.which("ruff")
    if ruff is None:
        print("ruff: not installed — skipping the optional ruff pass "
              "(pip install ruff to enable)")
        return 0
    cmd = [ruff, "check", "--config", os.path.join(root, "ruff.toml"),
           *(os.path.join(root, r) for r in DEFAULT_SCAN_ROOTS
             if os.path.exists(os.path.join(root, r)))]
    print(f"ruff: {' '.join(cmd)}")
    proc = subprocess.run(cmd)
    return proc.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="repo-native static analysis (rule catalog: "
                    "docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="roots to scan (default: dynamo_tpu tools "
                         "bench.py)")
    ap.add_argument("--root", default=".",
                    help="repo root (default: cwd)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "tools/dynalint/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current unsuppressed findings as the "
                         "new baseline (deferral ritual — every entry "
                         "needs a KNOWN_ISSUES.md pointer)")
    ap.add_argument("--update-schemas", action="store_true",
                    help="regenerate tools/dynalint/schemas.lock.json "
                         "from the current wire dataclasses")
    ap.add_argument("--changed-only", action="store_true",
                    help="incremental mode: scan only git-changed files "
                         "plus the call graph's reverse closure "
                         "(pre-commit speed); closure rules run only "
                         "when their input files changed")
    ap.add_argument("--base", default=None,
                    help="with --changed-only: diff against this ref "
                         "instead of the worktree (e.g. origin/main)")
    ap.add_argument("--with-ruff", action="store_true",
                    help="also run `ruff check` under the repo "
                         "ruff.toml when ruff is installed")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"error: not a directory: {root}", file=sys.stderr)
        return 2

    if args.update_schemas:
        from .rules.dl004_schema import update_lock
        ctx = load_context(root)
        path = update_lock(ctx)
        print(f"wire-schema lock regenerated: {path}")
        print("review the diff — it IS the protocol change record")
        return 0

    scan_roots = tuple(args.paths) if args.paths else DEFAULT_SCAN_ROOTS
    rules = args.rules.split(",") if args.rules else None
    only_paths = None
    if args.changed_only:
        try:
            changed = _git_changed_files(root, args.base)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"error: --changed-only needs a git worktree: {e}",
                  file=sys.stderr)
            return 2
        if not changed:
            print("dynalint: --changed-only with a clean diff — "
                  "nothing to scan")
            return 0
        ctx = load_context(root, scan_roots=scan_roots)
        # python closure over the call/import graph; non-py changes
        # (csrc, the Grafana JSON, the chaos tests) ride along verbatim
        # so the closure rules keyed on them still trigger
        only_paths = changed_closure(
            ctx.graph, {c for c in changed if c in ctx.graph.modules})
        only_paths |= changed
        findings, suppressed, stats = run_lint(
            root, rules=rules, baseline_path=args.baseline,
            scan_roots=scan_roots, ctx=ctx, only_paths=only_paths)
    else:
        findings, suppressed, stats = run_lint(
            root, rules=rules, baseline_path=args.baseline,
            scan_roots=scan_roots)

    if args.write_baseline:
        path = args.baseline or os.path.join(
            root, "tools/dynalint/baseline.json")
        write_baseline(path, findings)
        print(f"baseline written: {path} ({len(findings)} entries) — "
              f"fill in the reasons and add KNOWN_ISSUES.md pointers")
        return 0

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "suppressed": [f.__dict__ for f in suppressed],
            "stats": stats}, indent=1))
    else:
        for f in findings:
            print(f.render())
        scoped = (f" [changed-only: {stats['scoped_files']} files in "
                  f"closure]" if stats.get("scoped_files") is not None
                  else "")
        print(f"dynalint: {len(findings)} finding(s), "
              f"{len(suppressed)} suppressed "
              f"(waiver/baseline), {stats['files']} files, "
              f"{stats['functions']} functions, "
              f"{stats['elapsed_s']}s{scoped}")

    rc = 1 if findings else 0
    if args.with_ruff:
        ruff_rc = _run_ruff(root)
        rc = rc or (1 if ruff_rc else 0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
