"""dynalint driver: repo loading, inline waivers, baseline, rule running.

Silencing a finding (docs/static_analysis.md "baseline etiquette"):

1. Fix it. The default, and the only option for new code.
2. Inline waiver — a comment ``# dynalint: ok DL001 <reason>`` on the
   flagged line or the line directly above. For DELIBERATE design choices
   (e.g. the WAL's fsync-per-commit durability trade) where blocking the
   loop IS the contract. The reason is mandatory by convention.
3. Baseline — ``tools/dynalint/baseline.json`` entries keyed
   (rule, path, symbol), for found-but-deferred debt. Every baseline
   entry needs a KNOWN_ISSUES.md pointer; the repo-wide tier-1 gate
   fails on any finding that is neither waived nor baselined.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import re
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import RepoGraph

_WAIVER_RE = re.compile(r"#\s*dynalint:\s*ok\s+([A-Z0-9,\s]+?)(?:\s+\S.*)?$")

DEFAULT_SCAN_ROOTS = ("dynamo_tpu", "tools", "bench.py")
EXCLUDE_PATTERNS = ("*/__pycache__/*", "tools/dynalint/fixtures/*")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str           # repo-relative
    line: int
    message: str
    hint: str = ""
    # line-stable identity for baselining: the enclosing function/class
    # qualname (or a rule-chosen token). Baselines match (rule, path,
    # symbol) so findings survive unrelated line drift.
    symbol: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol or str(self.line))

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclasses.dataclass
class RepoContext:
    """Everything a rule needs. Built once per run; rules are pure
    functions ``rule(ctx) -> List[Finding]``."""

    root: str
    graph: RepoGraph
    # waivers[path] = {lineno: set(rule_ids) or {"*"}}
    waivers: Dict[str, Dict[int, Set[str]]]
    # rule-specific configuration (overridable by fixture tests)
    schema_paths: Sequence[str] = (
        "dynamo_tpu/runtime/codec.py",
        "dynamo_tpu/llm/protocols/common.py",
        "dynamo_tpu/llm/protocols/disagg.py",
        "dynamo_tpu/llm/protocols/openai.py",
        "dynamo_tpu/llm/protocols/sse.py",
        "dynamo_tpu/llm/protocols/annotated.py",
        "dynamo_tpu/llm/kv_router/protocols.py",
        "dynamo_tpu/llm/kv/stream.py",
    )
    schema_lock_path: str = "tools/dynalint/schemas.lock.json"
    # (cpp path, wrapper .py path, symbol prefixes) — the mirrored ABIs
    mirror_pairs: Sequence[Tuple[str, str, Tuple[str, ...]]] = (
        ("csrc/kv_reuse_pool.cpp", "dynamo_tpu/llm/kv/native_pool.py",
         ("kvpool_",)),
        ("csrc/kv_radix_index.cpp", "dynamo_tpu/llm/kv_router/indexer.py",
         ("dyn_kv_index_",)),
        ("csrc/data_plane.cpp", "dynamo_tpu/runtime/native_tcp.py",
         ("dpsend_", "dprecv_")),
        ("csrc/kv_event_abi.cpp", "dynamo_tpu/llm/kv_router/c_abi.py",
         ("dynamo_llm_", "dynamo_kv_event_", "dyn_kv_event_",
          "dyn_kv_abi_")),
    )
    # ---- DL009 event↔replay closure: where the recorder emits, where
    # the offline replayer + multihost follower classify, and the chaos
    # failpoint registry the static coverage gate reads
    recorder_emit_paths: Sequence[str] = ("dynamo_tpu/engine/core.py",)
    replay_module: str = "dynamo_tpu/engine/replay.py"
    multihost_module: str = "dynamo_tpu/engine/multihost.py"
    wire_events_name: str = "WIRE_EVENTS"
    host_events_name: str = "HOST_EVENTS"
    faults_module: str = "dynamo_tpu/runtime/faults.py"
    faults_sites_name: str = "SITES"
    chaos_test_path: str = "tests/test_chaos.py"
    # ---- DL010 metrics-plane closure
    metrics_module: str = "dynamo_tpu/components/metrics.py"
    metrics_protocol_module: str = "dynamo_tpu/llm/kv_router/protocols.py"
    metrics_dataclass: str = "ForwardPassMetrics"
    mock_worker_module: str = "dynamo_tpu/components/mock_worker.py"
    grafana_dashboard_path: str = "deploy/metrics/grafana-dashboard.json"
    # ---- DL011 control-key closure
    llmctl_module: str = "dynamo_tpu/launch/llmctl.py"
    # ---- DL012 sim/event-log determinism
    determinism_paths: Sequence[str] = ("dynamo_tpu/sim/",
                                        "dynamo_tpu/engine/replay.py")
    # ---- --changed-only incremental mode: when set, per-function rules
    # scan only these files (the git-diff set plus the call graph's
    # reverse closure) and cross-file closure rules run only when one of
    # their input files is in the set. None = full repo.
    only_paths: Optional[Set[str]] = None

    def in_scope(self, path: str) -> bool:
        return self.only_paths is None or path in self.only_paths

    def closure_relevant(self, *paths: str) -> bool:
        """Should a cross-file closure rule run? True on full scans, or
        when any of the rule's input files is in the changed closure."""
        if self.only_paths is None:
            return True
        return any(p in self.only_paths for p in paths)

    def iter_funcs(self):
        for f in self.graph.funcs.values():
            if self.in_scope(f.path):
                yield f

    def iter_modules(self):
        for rel in sorted(self.graph.modules):
            if self.in_scope(rel):
                yield self.graph.modules[rel]

    def read_file(self, relpath: str) -> Optional[str]:
        p = os.path.join(self.root, relpath)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


def _collect_waivers(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _WAIVER_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[i] = rules or {"*"}
    return out


def _excluded(relpath: str) -> bool:
    return any(fnmatch.fnmatch(relpath, pat) for pat in EXCLUDE_PATTERNS)


def load_context(root: str,
                 scan_roots: Sequence[str] = DEFAULT_SCAN_ROOTS,
                 **overrides) -> RepoContext:
    import gc

    # parsing 160+ modules allocates millions of AST nodes that all
    # survive — generational GC runs repeatedly over a graph with no
    # garbage in it. Pausing collection for the load is worth ~25% of
    # total gate time; the try/finally keeps caller GC state intact.
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        return _load_context_inner(root, scan_roots, **overrides)
    finally:
        if gc_was_enabled:
            gc.enable()


def _load_context_inner(root: str, scan_roots: Sequence[str],
                        **overrides) -> RepoContext:
    graph = RepoGraph(root)
    waivers: Dict[str, Dict[int, Set[str]]] = {}
    for entry in scan_roots:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            paths = [entry]
        else:
            paths = []
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        paths.append(os.path.relpath(
                            os.path.join(dirpath, fn), root))
        for rel in paths:
            rel = rel.replace(os.sep, "/")
            if _excluded(rel):
                continue
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                continue
            if graph.add_source(rel, src) is not None:
                w = _collect_waivers(src)
                if w:
                    waivers[rel] = w
    return RepoContext(root=root, graph=graph, waivers=waivers, **overrides)


def is_waived(ctx: RepoContext, finding: Finding) -> bool:
    file_waivers = ctx.waivers.get(finding.path)
    if not file_waivers:
        return False
    for ln in (finding.line, finding.line - 1):
        rules = file_waivers.get(ln)
        if rules and (finding.rule in rules or "*" in rules):
            return True
    return False


# ------------------------------------------------------------------ baseline

def load_baseline(path: str) -> List[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    return list(data.get("suppressions", []))


def baseline_matches(entry: dict, finding: Finding) -> bool:
    return (entry.get("rule") == finding.rule
            and entry.get("path") == finding.path
            and entry.get("symbol", "") == (finding.symbol or ""))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    sup = [{"rule": f.rule, "path": f.path, "symbol": f.symbol or "",
            "reason": "TODO: justify or fix (see docs/static_analysis.md)"}
           for f in findings]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "suppressions": sup}, f, indent=2)
        f.write("\n")


# ----------------------------------------------------- changed-only scope

def changed_closure(graph: RepoGraph, changed: Set[str]) -> Set[str]:
    """The changed file set plus its REVERSE dependency closure: every
    file that imports a changed module or whose calls resolve into one.
    A diff in f() can only introduce findings in files that can reach
    f — this is the set a pre-commit ``--changed-only`` run must scan."""
    from .callgraph import resolve_call

    rev: Dict[str, Set[str]] = {}
    for rel, mod in graph.modules.items():
        deps: Set[str] = set()
        for dotted in mod.imports.values():
            target = graph.by_dotted.get(dotted)
            if target is not None:
                deps.add(target.path)
        for dotted, _orig in mod.from_imports.values():
            target = graph.by_dotted.get(dotted)
            if target is not None:
                deps.add(target.path)
        for dep in deps:
            rev.setdefault(dep, set()).add(rel)
    for func in graph.funcs.values():
        for call in func.calls:
            for target in resolve_call(graph, func, call):
                if target.path != func.path:
                    rev.setdefault(target.path, set()).add(func.path)

    out = set(changed)
    work = list(changed)
    while work:
        cur = work.pop()
        for caller in rev.get(cur, ()):
            if caller not in out:
                out.add(caller)
                work.append(caller)
    return out


# --------------------------------------------------------------------- run

def run_lint(root: str,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             scan_roots: Sequence[str] = DEFAULT_SCAN_ROOTS,
             ctx: Optional[RepoContext] = None,
             only_paths: Optional[Set[str]] = None,
             ) -> Tuple[List[Finding], List[Finding], dict]:
    """Run the suite. Returns (unsuppressed, suppressed, stats).

    ``only_paths`` (the --changed-only closure) restricts per-function
    rules to those files and skips closure rules whose inputs are
    untouched; stats carry per-rule wall time AND finding counts so new
    rules can be budgeted against the tier-1 gate."""
    from .rules import ALL_RULES

    t0 = time.monotonic()
    if ctx is None:
        ctx = load_context(root, scan_roots=scan_roots)
    if only_paths is not None:
        ctx.only_paths = set(only_paths)
    selected = {r.upper() for r in rules} if rules else None
    findings: List[Finding] = []
    per_rule: Dict[str, float] = {}
    per_rule_n: Dict[str, int] = {}
    for rule_id, rule_fn in ALL_RULES.items():
        if selected is not None and rule_id not in selected:
            continue
        t = time.monotonic()
        got = rule_fn(ctx)
        findings.extend(got)
        per_rule[rule_id] = round(time.monotonic() - t, 3)
        per_rule_n[rule_id] = len(got)

    baseline = load_baseline(
        baseline_path if baseline_path is not None
        else os.path.join(root, "tools/dynalint/baseline.json"))
    unsuppressed: List[Finding] = []
    suppressed: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if is_waived(ctx, f) or any(baseline_matches(e, f)
                                    for e in baseline):
            suppressed.append(f)
        else:
            unsuppressed.append(f)
    stats = {"files": len(ctx.graph.modules),
             "functions": len(ctx.graph.funcs),
             "elapsed_s": round(time.monotonic() - t0, 3),
             "per_rule_s": per_rule,
             "per_rule_findings": per_rule_n,
             "scoped_files": (len(ctx.only_paths)
                              if ctx.only_paths is not None else None),
             "suppressed": len(suppressed)}
    return unsuppressed, suppressed, stats
