"""DL001: blocking call reachable from an ``async def`` without an
off-loop hop.

The invariant this repo polices by hand ("file I/O via to_thread, never
on the engine loop" — diskstore.py, offload.py) and only *observes* at
runtime via the flight recorder's loop-lag probe. A blocking primitive —
file open, fsync, np.savez/np.load, time.sleep, subprocess, a device
sync — executed on the event loop stalls every in-flight request for its
duration; at fleet QPS that is a tail-latency incident.

Mechanics: every ``async def`` is a root; call edges (tools/dynalint/
callgraph.py, conservative resolution) extend reachability through SYNC
functions only. Functions *referenced* into ``asyncio.to_thread`` /
``run_in_executor`` / ``Thread(target=…)`` get no edge — that is the
sanctioned escape hatch. A finding is reported at the blocking call
site, with one example async→…→call chain.
"""

from __future__ import annotations

from typing import List, Optional

from ..callgraph import CallSite, FuncInfo, async_reachable
from ..engine import Finding, RepoContext

RULE_ID = "DL001"

# dotted-name blocking primitives, keyed by canonical module
_BLOCKING_BY_MODULE = {
    "time": {"sleep"},
    "os": {"fsync"},
    "subprocess": {"run", "call", "check_call", "check_output", "Popen"},
    "numpy": {"savez", "savez_compressed", "load", "save"},
    "jax": {"block_until_ready", "device_get"},
    "socket": {"create_connection"},
}
# attribute-call tails that block regardless of receiver (device syncs)
_BLOCKING_METHOD_TAILS = {"block_until_ready"}

_HINT = ("run it off-loop: `await asyncio.to_thread(fn, ...)` (or "
         "loop.run_in_executor), or move the work to a sync context; "
         "waive deliberate blocking with `# dynalint: ok DL001 <reason>`")


def _blocking_desc(func: FuncInfo, call: CallSite) -> Optional[str]:
    """Human name of the blocking primitive, or None."""
    text = call.text
    parts = text.split(".")
    mod = func.module
    if len(parts) == 1:
        if parts[0] == "open" and parts[0] not in mod.from_imports \
                and parts[0] not in mod.functions:
            return "open()"
        # from-imported primitive, e.g. `from time import sleep`
        if parts[0] in mod.from_imports:
            src, orig = mod.from_imports[parts[0]]
            if orig in _BLOCKING_BY_MODULE.get(src, ()):
                return f"{src}.{orig}"
        return None
    head, tail = parts[0], parts[-1]
    if len(parts) == 2:
        canonical = mod.imports.get(head, head)
        if tail in _BLOCKING_BY_MODULE.get(canonical, ()):
            return f"{canonical}.{tail}"
    if tail in _BLOCKING_METHOD_TAILS:
        return f".{tail}()"
    return None


def check(ctx: RepoContext) -> List[Finding]:
    graph = ctx.graph
    chains = async_reachable(graph)
    findings: List[Finding] = []
    seen: set = set()
    for fid, chain in chains.items():
        func = graph.funcs[fid]
        if not ctx.in_scope(func.path):
            continue        # --changed-only: report only in the closure
        for call in func.calls:
            desc = _blocking_desc(func, call)
            if desc is None:
                continue
            key = (func.path, call.lineno, desc)
            if key in seen:
                continue
            seen.add(key)
            via = " -> ".join(
                graph.funcs[f].qualname for f in chain)
            root = graph.funcs[chain[0]]
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=call.lineno,
                symbol=f"{func.qualname}:{desc}",
                message=(f"blocking call {desc} runs on the event loop "
                         f"(reachable from async "
                         f"`{root.path}::{root.qualname}` via {via})"),
                hint=_HINT))
    return findings
