"""DL003: pin/hold balance — every pin acquisition reaches a release on
all paths, including exception edges.

PR 5's ``prepare_prefill`` loud assert made static. Acquisition
primitives and their matching releases:

    <recv>.hold(blocks)               ->  <recv>.release(...)
    <recv>.pin(slots|hashes)          ->  <recv>.unpin(...)
    <recv>.match_prefix(..., pin=True)->  <recv>.unpin(...)

Per-function analysis (the pin receivers are actor-local state, so
cross-function lifetimes are always ownership transfers):

- OWNERSHIP TRANSFER: the acquisition's bound name (or its argument's
  root name) escapes — appears in a `return`/`yield` expression, is
  stored on an attribute, or is handed to another call (e.g. packed
  into a PrefillPlan / OffloadJob whose consumer releases). Transferred
  pins are the caller's problem; no local release required.
- LOCAL LIFETIME: releases exist in this function. Then the exception
  edge must be covered: if any statement between the acquisition and
  the first matching release contains a call (= can raise), some
  matching release must sit in a `finally` or `except` handler —
  otherwise a raise leaks the pin (the engine slot then holds a
  spill-pump victim forever).
- LEAK: no release and no escape — flagged outright.

Tier-wrapper primitives (functions literally named pin/unpin/hold/
release/match_prefix, which forward to an inner store) are exempt: they
ARE the primitive, the balance obligation sits with their callers.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional

from ..callgraph import FuncInfo, dotted_text, shallow_walk
from ..engine import Finding, RepoContext

RULE_ID = "DL003"

_ACQ_RELEASE = {"hold": "release", "pin": "unpin", "match_prefix": "unpin"}
_WRAPPER_NAMES = {"pin", "unpin", "hold", "release", "match_prefix",
                  "abort_plan"}
# read-only builtins: passing the pinned collection through these does
# NOT transfer ownership (len(pins) is bookkeeping, OffloadJob(pins) is
# a handoff)
_PURE_BUILTINS = {"len", "min", "max", "sum", "sorted", "enumerate",
                  "range", "print", "repr", "str", "int", "bool", "any",
                  "all", "zip", "iter", "next", "id", "isinstance"}


@dataclasses.dataclass
class _Acq:
    node: ast.Call
    lineno: int
    recv: str                 # receiver text, e.g. "self.disk_store"
    kind: str                 # hold | pin | match_prefix
    bound_name: Optional[str]  # x = recv.match_prefix(...)
    arg_root: Optional[str]    # recv.hold(ids) -> "ids"


def _root_name(node: ast.expr) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Call):      # list(pinned) etc.
        if node.args:
            return _root_name(node.args[0])
        return None
    return node.id if isinstance(node, ast.Name) else None


def _find_acquisitions(func: FuncInfo) -> List[_Acq]:
    out: List[_Acq] = []
    assigns: Dict[int, str] = {}       # id(call node) -> bound name
    for n in shallow_walk(func.node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            assigns[id(n.value)] = n.targets[0].id
    for n in shallow_walk(func.node):
        if not isinstance(n, ast.Call):
            continue
        text = dotted_text(n.func)
        if text is None or "." not in text:
            continue
        recv, meth = text.rsplit(".", 1)
        if meth not in _ACQ_RELEASE:
            continue
        if meth == "match_prefix":
            pin_kw = next((kw for kw in n.keywords if kw.arg == "pin"),
                          None)
            if pin_kw is None or not (
                    isinstance(pin_kw.value, ast.Constant)
                    and pin_kw.value.value is True):
                continue
        arg_root = _root_name(n.args[0]) if n.args else None
        out.append(_Acq(node=n, lineno=n.lineno, recv=recv, kind=meth,
                        bound_name=assigns.get(id(n)), arg_root=arg_root))
    return out


def _release_calls(func: FuncInfo, recv: str, kind: str) -> List[ast.Call]:
    want = _ACQ_RELEASE[kind]
    out = []
    for n in shallow_walk(func.node):
        if isinstance(n, ast.Call):
            text = dotted_text(n.func)
            if text == f"{recv}.{want}":
                out.append(n)
    return out


def _in_handler_or_finally(func: FuncInfo, call: ast.Call) -> bool:
    for n in shallow_walk(func.node):
        if isinstance(n, ast.Try):
            for region in (n.finalbody,
                           [s for h in n.handlers for s in h.body]):
                for stmt in region:
                    if any(sub is call for sub in ast.walk(stmt)):
                        return True
    return False


def _escapes(func: FuncInfo, acq: _Acq) -> bool:
    names = {n for n in (acq.bound_name, acq.arg_root) if n}
    if not names:
        return False
    release_calls = {id(c) for kind in _ACQ_RELEASE
                     for c in _release_calls(func, acq.recv, kind)}
    for n in shallow_walk(func.node):
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and n.value is not None:
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Name) and sub.id in names:
                    return True
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute):
                    for sub in ast.walk(n.value):
                        if isinstance(sub, ast.Name) and sub.id in names:
                            return True
        if isinstance(n, ast.Call) and n is not acq.node \
                and id(n) not in release_calls \
                and not (isinstance(n.func, ast.Name)
                         and n.func.id in _PURE_BUILTINS):
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name) and sub.id in names \
                        and sub is not n.func:
                    return True
    # a nested function closing over the name also transfers ownership
    # (e.g. the off-thread onboard prep closure)
    graph_names = names
    for sub in ast.walk(func.node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not func.node:
            for inner in ast.walk(sub):
                if isinstance(inner, ast.Name) and inner.id in graph_names:
                    return True
    return False


def _calls_between(func: FuncInfo, start_line: int,
                   end_line: int) -> bool:
    """Any call strictly between the two lines (shallow scope) — the
    can-raise approximation."""
    for n in shallow_walk(func.node):
        if isinstance(n, ast.Call) and start_line < n.lineno < end_line:
            return True
    return False


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for func in ctx.iter_funcs():
        if func.name in _WRAPPER_NAMES:
            continue
        acqs = _find_acquisitions(func)
        for acq in acqs:
            releases = _release_calls(func, acq.recv, acq.kind)
            escapes = _escapes(func, acq)
            if not releases:
                if escapes:
                    continue            # ownership transferred
                findings.append(Finding(
                    rule=RULE_ID, path=func.path, line=acq.lineno,
                    symbol=f"{func.qualname}:{acq.recv}.{acq.kind}",
                    message=(f"`{acq.recv}.{acq.kind}(...)` acquires a "
                             f"pin that is never released and never "
                             f"escapes `{func.qualname}` — the entry "
                             f"stays pinned forever"),
                    hint=(f"release with `{acq.recv}."
                          f"{_ACQ_RELEASE[acq.kind]}(...)` on every "
                          f"path, or hand the pins to an owner that "
                          f"does")))
                continue
            # local lifetime: exception edge must be covered
            covered = any(_in_handler_or_finally(func, r)
                          for r in releases)
            first_rel = min(r.lineno for r in releases)
            if not covered and _calls_between(func, acq.lineno,
                                              first_rel):
                findings.append(Finding(
                    rule=RULE_ID, path=func.path, line=acq.lineno,
                    symbol=f"{func.qualname}:{acq.recv}.{acq.kind}:exc",
                    message=(f"`{acq.recv}.{acq.kind}(...)` is released "
                             f"on the normal path but a call between "
                             f"acquisition (line {acq.lineno}) and the "
                             f"first release (line {first_rel}) can "
                             f"raise — the exception edge leaks the "
                             f"pin"),
                    hint=(f"move the release into a finally/except so "
                          f"`{acq.recv}.{_ACQ_RELEASE[acq.kind]}` also "
                          f"runs on the raise path")))
    return findings
