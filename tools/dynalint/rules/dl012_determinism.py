"""DL012: sim/event-log determinism — DL005's purity discipline for the
co-simulator and replay-affecting paths.

The fleet simulator's whole value rests on one property: the SAME
scenario + seed produces a byte-identical EventLog (the tier-1
determinism gate diffs the bytes). DL005 protects jit-traced bodies;
nothing protected the sim itself, where the same three leak classes
break the byte-identity promise instead of follower lockstep:

- **wall clock** — ``time.time/monotonic/perf_counter/time_ns`` or
  ``datetime.now/utcnow`` read inside the determinism roots. The sim
  runs on a VIRTUAL clock (sim/clock.py); a real-clock read smuggles
  wall time into event ordering or payloads. (sim/clock.py itself — the
  patcher — references the real functions without calling them and
  stays clean by construction.)
- **ambient randomness** — module-function stdlib ``random.*`` /
  ``np.random.*`` / ``secrets`` / ``uuid``. Seeded instances
  (``random.Random(seed)`` held on a local/attr and called as a method)
  are the sanctioned source and are NOT flagged: the receiver
  distinguishes them statically.
- **unordered-set iteration** — ``for x in <set>`` / comprehensions /
  ``"".join(<set>)``-style consumption where the iterable is provably a
  set (a set literal, a ``set(...)`` call, a name/attr annotated or
  assigned as a set in the same scope) and not wrapped in ``sorted()``.
  Python sets iterate in hash order, which varies per process — exactly
  the nondeterminism the EventLog gate exists to catch. Membership
  tests and ``len()`` are fine; only iteration orders leak.

Scope: ``RepoContext.determinism_paths`` (dynamo_tpu/sim/ and
engine/replay.py by default). Deliberate escapes (e.g. a wall-clock
timestamp in a REPORT footer that never enters the log) waive inline.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..callgraph import FuncInfo, dotted_text, shallow_walk
from ..engine import Finding, RepoContext

RULE_ID = "DL012"

_WALL_CLOCK = {"time", "monotonic", "perf_counter", "time_ns",
               "process_time"}
_DT_CLOCK = {"now", "utcnow", "today"}
_RANDOM_MODULES = {"random", "secrets", "uuid"}

_HINT = ("route time through the sim's virtual clock and randomness "
         "through a seeded random.Random(seed); iterate sets as "
         "sorted(...) — hash order varies per process and breaks the "
         "byte-identical EventLog gate; waive a provably log-invisible "
         "escape with `# dynalint: ok DL012 <reason>`")


def _in_scope(ctx: RepoContext, path: str) -> bool:
    return any(path.startswith(root) if root.endswith("/")
               else path == root
               for root in ctx.determinism_paths)


def _impure_call(func: FuncInfo, text: str) -> Optional[str]:
    parts = text.split(".")
    mod = func.module
    if len(parts) == 1:
        entry = mod.from_imports.get(parts[0])
        if entry and entry[0] == "time" and entry[1] in _WALL_CLOCK:
            return f"time.{entry[1]}"
        if entry and entry[0] in _RANDOM_MODULES:
            return f"{entry[0]}.{entry[1]}"
        return None
    head = mod.imports.get(parts[0], parts[0])
    tail = parts[-1]
    if head == "time" and tail in _WALL_CLOCK:
        return f"time.{tail}"
    if head == "datetime" and tail in _DT_CLOCK:
        return text
    if head in _RANDOM_MODULES and len(parts) == 2:
        # module-FUNCTION randomness (ambient global RNG). A call on a
        # seeded instance has a non-module receiver and lands elsewhere.
        if head == "random" and tail in ("Random", "SystemRandom"):
            return None      # constructing a seeded instance is the fix
        return text
    if head in ("numpy", "np") and len(parts) >= 3 and parts[1] == "random":
        if tail in ("default_rng", "Generator", "RandomState"):
            return None
        return text
    return None


# ------------------------------------------------------- set iteration


class _SetEnv:
    """Names/attrs provably set-typed within one function (assignments
    from set literals / ``set(...)`` / set-typed annotations)."""

    def __init__(self, func: FuncInfo):
        self.names: Set[str] = set()
        self.attrs: Set[str] = set()
        for node in shallow_walk(func.node):
            value = None
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
                ann = node.annotation
                ann_txt = (dotted_text(ann) or "").rsplit(".", 1)[-1]
                if isinstance(ann, ast.Subscript):
                    ann_txt = (dotted_text(ann.value) or "").rsplit(
                        ".", 1)[-1]
                if ann_txt in ("set", "Set", "frozenset", "FrozenSet"):
                    self._add(targets)
                value = node.value
            if value is not None and self._is_set_expr(value):
                self._add(targets)

    def _add(self, targets: List[ast.expr]) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                self.names.add(t.id)
            elif (isinstance(t, ast.Attribute)
                  and isinstance(t.value, ast.Name)
                  and t.value.id == "self"):
                self.attrs.add(t.attr)

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
            return True
        if isinstance(node, ast.Call):
            name = (dotted_text(node.func) or "").rsplit(".", 1)[-1]
            return name in ("set", "frozenset")
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            return (_SetEnv._is_set_expr(node.left)
                    or _SetEnv._is_set_expr(node.right))
        return False

    def is_set(self, node: ast.AST) -> bool:
        if self._is_set_expr(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.names
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr in self.attrs
        return False


_CLASS_ATTR_CACHE: Dict[str, Set[str]] = {}


def _class_set_attrs(ctx: RepoContext, func: FuncInfo) -> Set[str]:
    """self attributes assigned/annotated as sets anywhere in the class
    (cached per class — every method shares the answer)."""
    if func.cls_name is None:
        return set()
    key = f"{func.path}::{func.cls_name}"
    hit = _CLASS_ATTR_CACHE.get(key)
    if hit is not None:
        return hit
    mod = func.module
    ci = mod.classes.get(func.cls_name)
    attrs: Set[str] = set()
    if ci is not None:
        for m in ci.methods.values():
            attrs |= _SetEnv(m).attrs
    _CLASS_ATTR_CACHE[key] = attrs
    return attrs


def _iter_exprs(func: FuncInfo):
    """(expr, lineno) iterated by for-loops and comprehensions."""
    for node in shallow_walk(func.node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield node.iter, node.lineno
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                yield gen.iter, node.lineno


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    _CLASS_ATTR_CACHE.clear()   # per-run cache (fixture trees may reuse
    # the same relpath::Class key across different roots)
    for func in ctx.iter_funcs():
        if not _in_scope(ctx, func.path):
            continue
        for call in func.calls:
            desc = _impure_call(func, call.text)
            if desc:
                findings.append(Finding(
                    rule=RULE_ID, path=func.path, line=call.lineno,
                    symbol=f"{func.qualname}:{desc}",
                    message=(f"determinism leak: `{desc}` in "
                             f"`{func.qualname}` feeds the byte-"
                             f"identical EventLog path with per-process "
                             f"state (wall clock / ambient RNG)"),
                    hint=_HINT))
        env = _SetEnv(func)
        env.attrs |= _class_set_attrs(ctx, func)
        for it, lineno in _iter_exprs(func):
            # sorted(...) / list(sorted(...)) wrapping is the fix
            if isinstance(it, ast.Call):
                name = (dotted_text(it.func) or "").rsplit(".", 1)[-1]
                if name == "sorted":
                    continue
                if name in ("list", "tuple") and it.args and isinstance(
                        it.args[0], ast.Call) and (dotted_text(
                            it.args[0].func) or "").endswith("sorted"):
                    continue
            if env.is_set(it):
                findings.append(Finding(
                    rule=RULE_ID, path=func.path, line=lineno,
                    symbol=f"{func.qualname}:set-iteration",
                    message=(f"determinism leak: `{func.qualname}` "
                             f"iterates a set in hash order — two "
                             f"identical runs may order these events "
                             f"differently (wrap in sorted(...))"),
                    hint=_HINT))
    return findings
