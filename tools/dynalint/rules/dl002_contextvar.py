"""DL002: contextvar leaks around the ambient trace.

Two sub-checks, both grounded in the bug PR 7 fixed in the engine loop
(runtime/tracing.py `detach_trace` docstring):

(a) token discipline — a ``.set(value)`` on a module-level
    ``contextvars.ContextVar`` must either set ``None`` (a detach) or
    capture the token and ``.reset(token)`` it in the same function,
    with the reset on a ``finally`` edge. An unpaired set leaks the
    binding into every later task created from that context.

(b) long-lived task detach — an ``async def`` that (1) is spawned via
    ``create_task`` / ``ensure_future``, (2) loops (``while``/``async
    for``: it outlives the request whose context spawned it), and
    (3) transitively reaches an ambient-trace READER
    (``current_trace`` / ``current_wire_context`` / ``tracing.span`` /
    ``use_trace``) must call ``detach_trace()`` in its body. Otherwise
    the FIRST request's trace parents every span the task ever records
    — the exact mis-attachment the engine loop shipped. Reachability
    here uses union (recall-mode) method resolution: over-approximating
    "might read the ambient trace" is the safe side, and the fix — one
    ``detach_trace()`` at task entry — is always correct for a task
    that owns no request.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..callgraph import (FuncInfo, dotted_text, resolve_call,
                         shallow_walk)
from ..engine import Finding, RepoContext

RULE_ID = "DL002"

_READER_NAMES = {"current_trace", "current_wire_context", "use_trace",
                 "span"}
_SPAWNER_TAILS = {"create_task", "ensure_future"}
_MAX_DEPTH = 5


def _module_contextvars(mod) -> Set[str]:
    """Names bound at module level to ``contextvars.ContextVar(...)``."""
    out: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            callee = dotted_text(value.func) or ""
            if callee.split(".")[-1] == "ContextVar":
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _check_token_discipline(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.graph.modules.values():
        cvars = _module_contextvars(mod)
        if not cvars:
            continue
        for func in ctx.graph.funcs.values():
            if func.module is not mod:
                continue
            sets: List[ast.Call] = []
            resets: List[ast.Call] = []
            resets_in_finally: bool = False
            for n in shallow_walk(func.node):
                if not isinstance(n, ast.Call):
                    continue
                t = dotted_text(n.func)
                if t is None or "." not in t:
                    continue
                recv, meth = t.rsplit(".", 1)
                if recv not in cvars:
                    continue
                if meth == "set":
                    sets.append(n)
                elif meth == "reset":
                    resets.append(n)
            if not sets:
                continue
            # is any reset on a finally edge?
            for try_node in shallow_walk(func.node):
                if isinstance(try_node, ast.Try):
                    for stmt in try_node.finalbody:
                        for n in ast.walk(stmt):
                            if (isinstance(n, ast.Call)
                                    and (dotted_text(n.func) or "")
                                    .endswith(".reset")):
                                resets_in_finally = True
            for s in sets:
                if (s.args and isinstance(s.args[0], ast.Constant)
                        and s.args[0].value is None):
                    continue        # detach — the cure, not the disease
                if resets and resets_in_finally:
                    continue
                detail = ("no `.reset(token)` in this function"
                          if not resets else
                          "`.reset(token)` is not on a finally edge — "
                          "an exception leaks the binding")
                findings.append(Finding(
                    rule=RULE_ID, path=func.path, line=s.lineno,
                    symbol=f"{func.qualname}:set",
                    message=(f"contextvar `.set()` without a paired "
                             f"reset ({detail}); the binding leaks into "
                             f"every task created from this context"),
                    hint=("capture `token = var.set(...)` and "
                          "`var.reset(token)` in a finally block, or "
                          "use a contextmanager like tracing.use_trace")))
    return findings


def _spawned_funcs(ctx: RepoContext) -> Set[str]:
    """Names of functions that appear as ``create_task(<name>(...))``
    (or ``ensure_future``) anywhere in the repo."""
    spawned: Set[str] = set()
    for func in ctx.graph.funcs.values():
        for call in func.calls:
            if call.text.rsplit(".", 1)[-1] not in _SPAWNER_TAILS:
                continue
            for a in call.node.args:
                if isinstance(a, ast.Call):
                    t = dotted_text(a.func)
                    if t:
                        spawned.add(t.rsplit(".", 1)[-1])
    return spawned


def _is_reader_call(func: FuncInfo, text: str) -> bool:
    """True when ``text`` calls one of runtime/tracing.py's ambient-trace
    readers (resolved through this module's imports, so an arbitrary
    method that happens to be called ``span`` does not count)."""
    mod = func.module
    parts = text.split(".")
    if len(parts) == 1:
        entry = mod.from_imports.get(parts[0])
        return (entry is not None and entry[1] in _READER_NAMES
                and entry[0].endswith("tracing"))
    head, tail = parts[0], parts[-1]
    if tail not in _READER_NAMES:
        return False
    dotted = mod.imports.get(head, "")
    if not dotted and head in mod.from_imports:
        src, orig = mod.from_imports[head]
        dotted = f"{src}.{orig}" if src else orig
    return dotted.endswith("tracing")


def _reaches_ambient_reader(ctx: RepoContext, func: FuncInfo,
                            cache: Dict[str, bool],
                            depth: int = 0) -> bool:
    if func.fid in cache:
        return cache[func.fid]
    cache[func.fid] = False           # cycle guard
    if depth > _MAX_DEPTH:
        return False
    for call in func.calls:
        if _is_reader_call(func, call.text):
            cache[func.fid] = True
            return True
        for target in resolve_call(ctx.graph, func, call, union=True):
            if _reaches_ambient_reader(ctx, target, cache, depth + 1):
                cache[func.fid] = True
                return True
    return False


def _has_loop(func: FuncInfo) -> bool:
    return any(isinstance(n, (ast.While, ast.AsyncFor))
               for n in shallow_walk(func.node))


def _calls_detach(func: FuncInfo) -> bool:
    return any(c.text.rsplit(".", 1)[-1] == "detach_trace"
               for c in func.calls)


def _check_task_detach(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    spawned = _spawned_funcs(ctx)
    cache: Dict[str, bool] = {}
    for func in ctx.graph.funcs.values():
        if not func.is_async or func.name not in spawned:
            continue
        if func.path.endswith("runtime/tracing.py"):
            continue                  # the machinery itself
        if not _has_loop(func) or _calls_detach(func):
            continue
        if not _reaches_ambient_reader(ctx, func, cache):
            continue
        findings.append(Finding(
            rule=RULE_ID, path=func.path, line=func.lineno,
            symbol=f"{func.qualname}:detach",
            message=(f"long-lived task `{func.qualname}` loops and "
                     f"(transitively) reads the ambient trace but never "
                     f"detaches — it inherits the spawning request's "
                     f"trace forever and mis-attaches every span"),
            hint=("call runtime.tracing.detach_trace() at task entry; "
                  "per-request identity must travel by value "
                  "(EngineRequest.trace, trace_ctx parameters)")))
    return findings


def check(ctx: RepoContext) -> List[Finding]:
    return _check_token_discipline(ctx) + _check_task_detach(ctx)
