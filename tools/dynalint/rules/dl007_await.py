"""DL007: unbounded cross-process await.

The chaos-hardening invariant (docs/chaos.md): every ``await`` that
blocks on ANOTHER process — a worker's dial-back stream, a work-queue
pop, a response frame — must carry an explicit timeout, because the
other side can be partitioned, browning out, or dead-but-connected. An
unbounded receive turns a peer failure into a local hang: the engine
loop keeps serving but THIS request (or this pump) waits forever, which
is exactly the failure shape the failpoint suite injects.

What counts as a cross-process receive site (curated, like DL001's
blocking-primitive table):

- ``.next_frame(...)``      — runtime/tcp.StreamReceiver (response frames)
- ``.wait_connected(...)``  — runtime/tcp.StreamReceiver (dial-back)
- ``.dequeue(...)``         — runtime/bus work queues (cross-process pop)
- ``.out_queue.get()``      — engine→stream handoff queue; unbounded
  means a dead engine loop hangs the client stream forever

A call is compliant when it passes a ``timeout=`` keyword (any value —
``timeout=None`` is an EXPLICIT opt-out and is flagged), or when it is
not directly awaited (e.g. wrapped in ``asyncio.wait_for(...)``).
Deliberately-unbounded pumps waive with
``# dynalint: ok DL007 <reason>``.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, RepoContext

RULE_ID = "DL007"

_RECEIVE_TAILS = {"next_frame", "wait_connected", "dequeue"}

_HINT = ("pass an explicit timeout= (or wrap in asyncio.wait_for); a "
         "partitioned peer must fail this await in bounded time — waive "
         "a deliberately-unbounded pump with `# dynalint: ok DL007 "
         "<reason>`")


def _is_out_queue_get(call: ast.Call) -> bool:
    """``<expr>.out_queue.get(...)`` — the engine's per-request stream
    handoff queue."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "get"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "out_queue")


def _receive_desc(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _RECEIVE_TAILS:
        return f".{f.attr}()"
    if _is_out_queue_get(call):
        return ".out_queue.get()"
    return ""


def _has_bounded_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    # positional timeout: next_frame(t) / wait_connected(t) /
    # dequeue(t, ...) all take timeout first
    if call.args and not _is_out_queue_get(call):
        first = call.args[0]
        return not (isinstance(first, ast.Constant)
                    and first.value is None)
    return False


class _AwaitVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[tuple] = []   # (lineno, desc)
        self._func_stack: List[str] = []

    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Await(self, node: ast.Await) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            desc = _receive_desc(call)
            if desc and not _has_bounded_timeout(call):
                qual = ".".join(self._func_stack) or "<module>"
                self.findings.append((node.lineno, desc, qual))
        self.generic_visit(node)


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.iter_modules():
        rel = mod.path
        v = _AwaitVisitor()
        v.visit(mod.tree)
        for lineno, desc, qual in v.findings:
            findings.append(Finding(
                rule=RULE_ID, path=rel, line=lineno,
                symbol=f"{qual}:{desc}",
                message=(f"unbounded cross-process await {desc} — a "
                         f"partitioned peer hangs this caller forever"),
                hint=_HINT))
    return findings
