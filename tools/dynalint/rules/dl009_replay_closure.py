"""DL009: event↔replay closure + static failpoint coverage.

The recorded-replay / multihost-follower machinery is a four-party
contract that until now only runtime tests policed:

- every event type the recorder emits (``recorder.rec("<name>", …)`` in
  the engine) must have a **home in engine/replay.py** — either a
  replayed kind (an ``ev["ev"] == …`` / ``kind == …`` comparison) or an
  explicit entry in the leader-side ``HOST_EVENTS`` classification.
  An emitted event replay has never heard of silently falls through the
  replayer's if/elif chain — recorded runs stop being re-executable;
- every event kind the **follower** handles (engine/multihost.py
  ``run_follower``) must be in ``WIRE_EVENTS`` — otherwise the leader's
  ``DispatchStreamLeader.rec`` DROPS it on the floor and follower device
  state silently diverges (this rule's first catch on the real tree:
  ``ragged`` and ``verify`` were handled but never forwarded);
- every ``WIRE_EVENTS`` member must be handled by ``run_follower`` —
  a forwarded-but-unhandled kind is the same divergence from the other
  side — and must also be offline-replayable (or explicitly refused,
  which is a comparison too);
- ``HOST_EVENTS`` and ``WIRE_EVENTS`` must be disjoint: an event cannot
  be both leader-side bookkeeping and device-state lockstep.

Plus the chaos half (the runtime coverage gate of tests/test_chaos.py
made static): every failpoint site registered in ``faults.SITES`` must
be referenced from tests/test_chaos.py AND actually hit somewhere in
the tree (``faults.hit/hit_async/mangle`` with that literal); every hit
must name a registered site.

All sets are READ FROM THE CODE via the dataflow constant pass — there
is no curated copy of the event list inside the rule to drift.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from ..engine import Finding, RepoContext

RULE_ID = "DL009"

_HINT_EVENT = ("classify the event: add an exec_* handler (device-state "
               "events) or a HOST_EVENTS entry (leader-side bookkeeping) "
               "in engine/replay.py, and keep WIRE_EVENTS in lockstep "
               "with run_follower's handled kinds")
_HINT_FAULT = ("every registered failpoint needs a chaos test that arms "
               "it and a hit() at the real failure site "
               "(docs/chaos.md); remove dead registry entries")


def _emitted_events(ctx: RepoContext) -> dict:
    """{event: first lineno} for every ``*.rec("<lit>", …)`` emission in
    the configured emit paths."""
    out: dict = {}
    for rel in ctx.recorder_emit_paths:
        mod = ctx.graph.modules.get(rel)
        if mod is None:
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "rec" and node.args):
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                out.setdefault(a0.value, (rel, node.lineno))
    return out


def _compared_kinds(ctx: RepoContext, rel: str,
                    func_name: Optional[str] = None) -> Set[str]:
    """String literals compared against an event-kind expression
    (``kind == "x"``, ``kind in ("x", …)``, ``ev["ev"] == "x"``) in one
    module (optionally scoped to one function)."""
    mod = ctx.graph.modules.get(rel)
    if mod is None:
        return set()
    scope: ast.AST = mod.tree
    if func_name is not None:
        for f in ctx.graph.funcs.values():
            if f.path == rel and f.name == func_name:
                scope = f.node
                break
    out: Set[str] = set()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Compare):
            continue
        for comp in node.comparators:
            if isinstance(comp, ast.Constant) and isinstance(
                    comp.value, str):
                out.add(comp.value)
            elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                out.update(el.value for el in comp.elts
                           if isinstance(el, ast.Constant)
                           and isinstance(el.value, str))
    return out


def _const_set(ctx: RepoContext, rel: str, name: str) -> Optional[Set[str]]:
    mod = ctx.graph.modules.get(rel)
    if mod is None:
        return None
    return ctx.graph.consts.str_set(mod, name)


def _module_finding(ctx: RepoContext, rel: str, symbol: str, msg: str,
                    hint: str, line: int = 1) -> Finding:
    return Finding(rule=RULE_ID, path=rel, line=line, symbol=symbol,
                   message=msg, hint=hint)


def _check_events(ctx: RepoContext, findings: List[Finding]) -> None:
    emitted = _emitted_events(ctx)
    if not emitted:
        return            # fixture tree without a recorder — nothing on
    replay_rel = ctx.replay_module
    mh_rel = ctx.multihost_module
    offline = _compared_kinds(ctx, replay_rel)
    follower = _compared_kinds(ctx, mh_rel, func_name="run_follower")
    follower.discard("__shutdown__")
    wire = _const_set(ctx, mh_rel, ctx.wire_events_name)
    host = _const_set(ctx, replay_rel, ctx.host_events_name)

    if wire is None:
        findings.append(_module_finding(
            ctx, mh_rel, f"{ctx.wire_events_name}:missing",
            f"no statically-resolvable `{ctx.wire_events_name}` set — "
            f"the leader cannot prove its forwarding closure",
            _HINT_EVENT))
        wire = set()
    if host is None:
        findings.append(_module_finding(
            ctx, replay_rel, f"{ctx.host_events_name}:missing",
            f"no statically-resolvable `{ctx.host_events_name}` "
            f"classification in the replay module — leader-side "
            f"bookkeeping events must be declared, not implied",
            _HINT_EVENT))
        host = set()

    for ev, (rel, line) in sorted(emitted.items()):
        if ev not in offline and ev not in host:
            findings.append(Finding(
                rule=RULE_ID, path=rel, line=line, symbol=f"{ev}:no-home",
                message=(f"recorded event `{ev}` has no home in "
                         f"{replay_rel}: neither replayed nor classified "
                         f"as leader-side bookkeeping (HOST_EVENTS) — "
                         f"recorded runs with it are silently "
                         f"un-replayable"),
                hint=_HINT_EVENT))
    for ev in sorted(follower - wire):
        findings.append(_module_finding(
            ctx, mh_rel, f"{ev}:dropped-on-wire",
            f"follower handles event `{ev}` but {ctx.wire_events_name} "
            f"omits it — DispatchStreamLeader.rec drops it and follower "
            f"device state silently diverges", _HINT_EVENT))
    for ev in sorted(wire - follower):
        findings.append(_module_finding(
            ctx, mh_rel, f"{ev}:unhandled-on-follower",
            f"`{ev}` rides the dispatch stream ({ctx.wire_events_name}) "
            f"but run_follower has no handler for it — it falls through "
            f"the if/elif chain silently", _HINT_EVENT))
    for ev in sorted(wire - offline):
        findings.append(_module_finding(
            ctx, replay_rel, f"{ev}:not-offline-replayable",
            f"wire event `{ev}` is not handled (or explicitly refused) "
            f"by the offline replayer in {replay_rel}", _HINT_EVENT))
    for ev in sorted(host & wire):
        findings.append(_module_finding(
            ctx, replay_rel, f"{ev}:host-and-wire",
            f"`{ev}` is classified host-side bookkeeping AND forwarded "
            f"on the wire — pick one", _HINT_EVENT))


def _fault_hits(ctx: RepoContext) -> Set[str]:
    """Site literals passed to faults.hit / hit_async / mangle anywhere
    in the scanned tree."""
    out: Set[str] = set()
    for func in ctx.graph.funcs.values():
        if func.path == ctx.faults_module:
            continue        # the registry's own plumbing
        for call in func.calls:
            # aliasing idiom included: `from .faults import hit as _fault`
            base = call.text.rsplit(".", 1)[-1].lstrip("_")
            if base not in ("hit", "hit_async", "mangle", "fault",
                            "fault_async"):
                continue
            if not call.node.args:
                continue
            a0 = call.node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                out.add(a0.value)
    return out


def _check_faults(ctx: RepoContext, findings: List[Finding]) -> None:
    mod = ctx.graph.modules.get(ctx.faults_module)
    if mod is None:
        return
    sites = ctx.graph.consts.str_dict(mod, ctx.faults_sites_name)
    if sites is None:
        findings.append(_module_finding(
            ctx, ctx.faults_module, f"{ctx.faults_sites_name}:missing",
            f"failpoint registry `{ctx.faults_sites_name}` is not a "
            f"statically-resolvable literal dict", _HINT_FAULT))
        return
    chaos_src = ctx.read_file(ctx.chaos_test_path) or ""
    hits = _fault_hits(ctx)
    for site in sorted(sites):
        if not re.search(rf'"{re.escape(site)}"', chaos_src):
            findings.append(_module_finding(
                ctx, ctx.faults_module, f"{site}:untested",
                f"failpoint site `{site}` is registered but never "
                f"referenced from {ctx.chaos_test_path} — the runtime "
                f"coverage gate would fail; this is it, before merge",
                _HINT_FAULT))
        if site not in hits:
            findings.append(_module_finding(
                ctx, ctx.faults_module, f"{site}:never-hit",
                f"failpoint site `{site}` is registered but no "
                f"faults.hit/hit_async/mangle call names it — a dead "
                f"registry entry arms nothing", _HINT_FAULT))
    for site in sorted(hits - set(sites)):
        findings.append(_module_finding(
            ctx, ctx.faults_module, f"{site}:unregistered",
            f"faults.hit(\"{site}\") names a site missing from "
            f"{ctx.faults_sites_name} — it would raise KeyError at the "
            f"first disarmed hit", _HINT_FAULT))


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    if ctx.closure_relevant(*ctx.recorder_emit_paths, ctx.replay_module,
                            ctx.multihost_module):
        _check_events(ctx, findings)
    if ctx.closure_relevant(ctx.faults_module, ctx.chaos_test_path):
        _check_faults(ctx, findings)
    return findings
