"""DL006: Python<->C++ mirror drift across the csrc ABI boundary.

The C++ pools/indexes are declared "mirrored EXACTLY" by their Python
twins (pool.py <-> kv_reuse_pool.cpp) and today only the differential
fuzzer notices drift — at runtime, after the drift shipped. This rule
checks the boundary statically for every csrc library:

- every ABI symbol the ctypes wrapper references (``lib.kvpool_x``,
  ``getattr(lib, "kvpool_x")``) must be exported from the cpp's
  ``extern "C"`` block (a missing symbol is an AttributeError at
  runtime, on the serving path);
- every exported symbol must be referenced by its wrapper (an orphan
  export is drift in the making: one side added an op the other never
  learned);
- declared ``argtypes`` arity must equal the C parameter count (ctypes
  happily under/over-marshals and corrupts the stack silently);
- a non-void C return REQUIRES ``restype`` on the wrapper (ctypes
  defaults to c_int — a truncated pointer on 64-bit is a crash that
  only reproduces under memory pressure);
- out-buffer contracts: ``kvpool_layout_stats`` writes ``out[0..N]``;
  the wrapper's scratch buffer must be exactly N+1 wide (the PR-5
  stats-mirror contract).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..callgraph import dotted_text
from ..engine import Finding, RepoContext

RULE_ID = "DL006"

_EXTERN_START_RE = re.compile(r'extern\s+"C"\s*\{')
_FUNC_RE = re.compile(
    r'^\s*((?:unsigned\s+)?[A-Za-z_][\w:]*\s*\*?)\s+'   # return type
    r'([A-Za-z_]\w*)\s*\(([^)]*)\)\s*\{',               # name(params) {
    re.MULTILINE | re.DOTALL)


def parse_cpp_exports(source: str,
                      prefixes: Tuple[str, ...]) -> Dict[str, dict]:
    """{symbol: {"params": int, "returns_void": bool, "line": int,
    "out_writes": {param_name: max_index}}}"""
    out: Dict[str, dict] = {}
    for extern in _EXTERN_START_RE.finditer(source):
        # balanced-brace scan from the opening brace of the extern block
        start = source.index("{", extern.start())
        depth, i = 0, start
        while i < len(source):
            if source[i] == "{":
                depth += 1
            elif source[i] == "}":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        body = source[start + 1: i]
        base_line = source[: start].count("\n") + 1
        for m in _FUNC_RE.finditer(body):
            ret, name, params = m.group(1).strip(), m.group(2), m.group(3)
            if not name.startswith(prefixes):
                continue
            params = params.strip()
            n_params = 0 if params in ("", "void") else params.count(",") + 1
            # find the function body (balanced braces from the def)
            start = m.end() - 1
            depth, i = 0, start
            while i < len(body):
                if body[i] == "{":
                    depth += 1
                elif body[i] == "}":
                    depth -= 1
                    if depth == 0:
                        break
                i += 1
            fn_body = body[start:i + 1]
            writes: Dict[str, int] = {}
            for w in re.finditer(r"([A-Za-z_]\w*)\[(\d+)\]\s*=", fn_body):
                pname, idx = w.group(1), int(w.group(2))
                writes[pname] = max(writes.get(pname, -1), idx)
            out[name] = {
                "params": n_params,
                "returns_void": ret == "void",
                "line": base_line + body[: m.start()].count("\n"),
                "out_writes": writes,
            }
    return out


def parse_wrapper_refs(mod) -> Dict[str, dict]:
    """{symbol: {"argtypes": Optional[int], "restype": bool, "line"}}
    from ``lib.<sym>.argtypes = [...]`` / ``.restype = ...`` assignments,
    ``getattr(lib, "<sym>")`` and ``lib.<sym>(...)`` references."""
    refs: Dict[str, dict] = {}

    def entry(sym: str, line: int) -> dict:
        return refs.setdefault(sym, {"argtypes": None, "restype": False,
                                     "line": line})

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):             # noqa: N802
            for t in node.targets:
                text = dotted_text(t)
                if text is None:
                    continue
                parts = text.split(".")
                if len(parts) >= 3 and parts[-1] in ("argtypes",
                                                     "restype"):
                    sym = parts[-2]
                    e = entry(sym, node.lineno)
                    if parts[-1] == "argtypes":
                        if isinstance(node.value, (ast.List, ast.Tuple)):
                            e["argtypes"] = len(node.value.elts)
                    else:
                        e["restype"] = True
            self.generic_visit(node)

        def visit_Call(self, node):               # noqa: N802
            text = dotted_text(node.func)
            if text == "getattr" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                entry(node.args[1].value, node.lineno)
            elif text is not None and "." in text:
                entry(text.rsplit(".", 1)[-1], node.lineno)
            self.generic_visit(node)

        def visit_Constant(self, node):           # noqa: N802
            # string-iterated registration loops:
            # for fn in ("kvpool_a", "kvpool_b"): getattr(lib, fn)...
            if isinstance(node.value, str) \
                    and re.fullmatch(r"[A-Za-z_]\w*", node.value):
                entry(node.value, getattr(node, "lineno", 1))

    V().visit(mod.tree)
    return refs


def _scratch_buffer_sizes(mod) -> Dict[str, int]:
    """Sizes of ctypes scratch buffers built as ``(_I64 * N)()`` in
    functions that call an out-buffer ABI (keyed by enclosing function
    name)."""
    sizes: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call) and not sub.args
                        and isinstance(sub.func, ast.BinOp)
                        and isinstance(sub.func.op, ast.Mult)
                        and isinstance(sub.func.right, ast.Constant)
                        and isinstance(sub.func.right.value, int)):
                    sizes[node.name] = sub.func.right.value
    return sizes


def check(ctx: RepoContext) -> List[Finding]:
    if not ctx.closure_relevant(*(p for pair in ctx.mirror_pairs
                                  for p in pair[:2])):
        return []      # --changed-only: no mirrored ABI touched
    findings: List[Finding] = []
    for cpp_path, py_path, prefixes in ctx.mirror_pairs:
        cpp_src = ctx.read_file(cpp_path)
        mod = ctx.graph.modules.get(py_path)
        if cpp_src is None or mod is None:
            continue
        exports = parse_cpp_exports(cpp_src, tuple(prefixes))
        refs = {sym: info for sym, info in parse_wrapper_refs(mod).items()
                if sym.startswith(tuple(prefixes))}

        for sym, info in refs.items():
            exp = exports.get(sym)
            if exp is None:
                findings.append(Finding(
                    rule=RULE_ID, path=py_path, line=info["line"],
                    symbol=f"{sym}:missing-export",
                    message=(f"wrapper references ABI symbol `{sym}` "
                             f"that {cpp_path} does not export — "
                             f"AttributeError on the serving path"),
                    hint=f"export it from {cpp_path} extern \"C\" or "
                         f"drop the reference"))
                continue
            if info["argtypes"] is not None \
                    and info["argtypes"] != exp["params"]:
                findings.append(Finding(
                    rule=RULE_ID, path=py_path, line=info["line"],
                    symbol=f"{sym}:arity",
                    message=(f"`{sym}` argtypes arity "
                             f"{info['argtypes']} != C parameter count "
                             f"{exp['params']} ({cpp_path}:"
                             f"{exp['line']}) — ctypes will silently "
                             f"mis-marshal the call"),
                    hint="make the argtypes list match the C signature "
                         "exactly"))
            if not exp["returns_void"] and info["argtypes"] is not None \
                    and not info["restype"]:
                findings.append(Finding(
                    rule=RULE_ID, path=py_path, line=info["line"],
                    symbol=f"{sym}:restype",
                    message=(f"`{sym}` returns non-void in {cpp_path} "
                             f"but the wrapper sets no restype — "
                             f"ctypes truncates to c_int"),
                    hint="declare lib.{}.restype".format(sym)))

        for sym, exp in exports.items():
            if sym not in refs:
                findings.append(Finding(
                    rule=RULE_ID, path=cpp_path, line=exp["line"],
                    symbol=f"{sym}:orphan-export",
                    message=(f"{cpp_path} exports `{sym}` but "
                             f"{py_path} never references it — the "
                             f"mirror halves have drifted"),
                    hint=f"wrap it in {py_path} or remove the export"))

        # out-buffer width contracts (the PR-5 stats mirror):
        # kvpool_layout_stats writes out[0..N]; the wrapper's scratch
        # buffer in the calling function must be exactly N+1 wide
        sizes = _scratch_buffer_sizes(mod)
        for sym, exp in exports.items():
            writes = exp["out_writes"].get("out", -1)
            if writes < 0 or sym not in refs:
                continue
            # find wrapper functions whose body calls this symbol
            for fname, size in sizes.items():
                caller = None
                for fid, fi in ctx.graph.funcs.items():
                    if fi.module is mod and fi.name == fname and any(
                            c.text.rsplit(".", 1)[-1] == sym
                            for c in fi.calls):
                        caller = fi
                        break
                if caller is None:
                    continue
                if size != writes + 1:
                    findings.append(Finding(
                        rule=RULE_ID, path=py_path, line=caller.lineno,
                        symbol=f"{sym}:out-buffer",
                        message=(f"`{fname}` passes a {size}-wide "
                                 f"scratch buffer to `{sym}` but the C "
                                 f"side writes out[0..{writes}] — "
                                 f"buffer overrun or dropped stats"),
                        hint="size the buffer to the C contract and "
                             "keep both sides in one commit"))
    return findings
