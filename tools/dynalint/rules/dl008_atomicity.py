"""DL008: async-atomicity — stale shared-state snapshots across awaits.

The engine loop is single-tasked by design, but every OTHER async
function in the serving plane interleaves with it at each ``await``: a
``self.``-state read taken BEFORE a suspension point describes a world
that may no longer exist AFTER it. The bug class this rule rejects is
the check-then-act race (the stale-slot / ``_sweep_cancelled`` vs
harvest interleaving shape): a guard or index derived from shared state,
an ``await``, then an action that trusts the pre-await value without
re-reading.

Two detected shapes, both built on the dataflow layer's await-point
segmentation (``dataflow.await_epochs``):

1. **stale snapshot acting on shared state** — a local bound from a
   ``self.X`` read at epoch *b* is used at epoch *u* > *b* (an await
   intervened) as the INDEX of a shared-state subscript store/delete
   (``self.Y[v] = …`` / ``del self.Y[v]``) or as an argument to a
   mutating method on shared state (``self.Y.pop(v)``, ``.remove``,
   ``.release``, ``.unpin``, ``.discard``, ``.vacate``), without being
   rebound after the last await before the use.

2. **check-then-act guard** — an ``if``/``while`` test reads ``self.X``
   at epoch *g*; the governed body crosses an await and then mutates the
   SAME ``self.X`` root (subscript store/delete, mutating method, or
   plain reassignment) at a later epoch, with no re-read of that root
   between the last intervening await and the mutation.

Suppressions that keep the repo-wide gate honest rather than noisy:
``self.cfg`` / ``self.config`` / ``self.model_cfg``-rooted reads
(immutable engine config), ALL-CAPS attribute constants, and any re-read
of the root between the await and the act (re-validation is exactly the
fix the rule asks for). Deliberate single-writer pumps waive with
``# dynalint: ok DL008 <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import FuncInfo
from ..dataflow import await_epochs, iter_assign_names
from ..engine import Finding, RepoContext

RULE_ID = "DL008"

# receiver-method tails that MUTATE the receiver (curated like DL001's
# blocking table; add here when a new shared-state mutator appears)
_MUTATOR_TAILS = {"pop", "remove", "discard", "release", "unpin",
                  "vacate", "popitem", "clear"}

# self attributes that are configuration, not shared mutable state
_CONFIG_ATTRS = {"cfg", "config", "model_cfg", "_cfg"}

_HINT = ("re-read the shared state after the await (the world moved "
         "while you were suspended), or hoist the await out of the "
         "check-then-act window; waive a deliberately single-writer "
         "pump with `# dynalint: ok DL008 <reason>`")


def _self_roots(node: ast.AST, taint: bool = False) -> Set[str]:
    """Attr names X for every ``self.X`` LOAD inside ``node``, excluding
    config attrs, constants, and ``self.X(...)`` method positions.

    ``taint=True`` is the stricter snapshot-source form: reads inside a
    ``Call`` (constructor/helper arguments) don't taint the bound value
    — the value is the callee's product, not a raw state snapshot — and
    a value that contains an ``Await`` is POST-suspension data, which is
    as fresh as it gets."""
    roots: Set[str] = set()
    if taint and any(isinstance(n, ast.Await) for n in ast.walk(node)):
        return roots
    skip: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if taint:
                # anything inside a call — receiver, args — feeds the
                # CALLEE; the bound value is the callee's product
                skip.update(id(d) for d in ast.walk(n))
                skip.discard(id(n))
            else:
                skip.add(id(n.func))
    for n in ast.walk(node):
        if (isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and isinstance(n.ctx, ast.Load)
                and id(n) not in skip
                and n.attr not in _CONFIG_ATTRS
                and not n.attr.isupper()):
            roots.add(n.attr)
    return roots


def _mutated_self_root(node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """(attr, index/arg expr) when ``node`` is a shared-state mutation:
    ``self.X[i] = / del self.X[i]`` or ``self.X.<mutator>(arg)`` (incl.
    one attribute hop: ``self.X.Y.pop(arg)`` roots at X)."""
    if isinstance(node, (ast.Assign, ast.Delete)):
        targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript):
                recv = t.value
                while isinstance(recv, (ast.Attribute, ast.Subscript)):
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"):
                        return recv.attr, t.slice
                    recv = recv.value
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        f = call.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATOR_TAILS):
            recv = f.value
            while isinstance(recv, (ast.Attribute, ast.Subscript)):
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    arg = call.args[0] if call.args else None
                    return recv.attr, arg
                recv = recv.value
    return None


class _FuncState:
    """Epoch-indexed dataflow facts for one async function body."""

    def __init__(self, func: FuncInfo):
        self.func = func
        self.seq = await_epochs(func.node)
        # evaluation order position per node id (for "between" queries)
        self.order: Dict[int, int] = {id(n): i
                                      for i, (n, _) in enumerate(self.seq)}
        self.epoch: Dict[int, int] = {id(n): e for n, e in self.seq}
        # node ids inside an ``async with self.<lock>`` region: the
        # sanctioned double-checked-lock discipline serializes its
        # guards with its mutations, so they are exempt
        self.locked: Set[int] = set()
        for n in ast.walk(func.node):
            if not isinstance(n, ast.AsyncWith):
                continue
            for item in n.items:
                t = item.context_expr
                tail = t.attr if isinstance(t, ast.Attribute) else (
                    t.id if isinstance(t, ast.Name) else "")
                if "lock" in tail.lower() or "sem" in tail.lower():
                    self.locked.update(id(d) for s in n.body
                                       for d in ast.walk(s))
                    break

    def epoch_of(self, node: ast.AST) -> Optional[int]:
        return self.epoch.get(id(node))

    def reads_between(self, root: str, lo_pos: int, hi_pos: int) -> bool:
        """Any ``self.<root>`` load strictly between two positions?"""
        for i in range(lo_pos + 1, hi_pos):
            n, _ = self.seq[i]
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and n.attr == root
                    and isinstance(n.ctx, ast.Load)):
                return True
        return False

    def last_await_before(self, pos: int) -> Optional[int]:
        for i in range(pos - 1, -1, -1):
            n, _ = self.seq[i]
            if isinstance(n, ast.Await):
                return i
        return None


def _check_snapshots(st: _FuncState, findings: List[Finding]) -> None:
    func = st.func
    # bindings: name -> list of (position, epoch, snapshot_roots)
    binds: Dict[str, List[Tuple[int, int, Set[str]]]] = {}
    # keys this function itself STORED under (``self.X[rid] = …``): a
    # later pop/del keyed by the same local is the owner cleaning up its
    # own entry (the netstore rid/wid discipline), not check-then-act
    owned_keys: Set[str] = set()
    for pos, (node, epoch) in enumerate(st.seq):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            roots = (_self_roots(value, taint=True)
                     if value is not None else set())
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                        t.slice, ast.Name):
                    owned_keys.add(t.slice.id)
                for name in iter_assign_names(t):
                    binds.setdefault(name, []).append((pos, epoch, roots))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            roots = _self_roots(node.iter, taint=True)
            for name in iter_assign_names(node.target):
                binds.setdefault(name, []).append(
                    (st.order[id(node)], epoch, roots))

    def latest_bind(name: str, pos: int):
        cand = None
        for b in binds.get(name, []):
            if b[0] < pos:
                cand = b
        return cand

    seen: Set[Tuple[int, str]] = set()
    for pos, (node, epoch) in enumerate(st.seq):
        if id(node) in st.locked:
            continue
        mut = _mutated_self_root(node)
        if mut is None or mut[1] is None:
            continue
        root, arg = mut
        for n in ast.walk(arg):
            if not (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)):
                continue
            if n.id in owned_keys:
                continue
            b = latest_bind(n.id, pos)
            if b is None:
                continue
            b_pos, b_epoch, b_roots = b
            if not b_roots or b_epoch >= epoch:
                continue  # not a shared snapshot, or no await crossed
            # re-validation: the snapshot's source root re-read after the
            # last await before the act
            la = st.last_await_before(pos)
            if la is not None and any(
                    st.reads_between(r, la, pos) for r in b_roots):
                continue
            key = (node.lineno, n.id)
            if key in seen:
                continue
            seen.add(key)
            src = ", ".join(f"self.{r}" for r in sorted(b_roots))
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=node.lineno,
                symbol=f"{func.qualname}:{n.id}@self.{root}",
                message=(f"async-atomicity: `{n.id}` (snapshot of {src}, "
                         f"epoch {b_epoch}) drives a mutation of "
                         f"`self.{root}` after an intervening await "
                         f"(epoch {epoch}) without re-validation — the "
                         f"stale-slot check-then-act race"),
                hint=_HINT))


def _check_guards(st: _FuncState, findings: List[Finding]) -> None:
    func = st.func
    for pos, (node, epoch) in enumerate(st.seq):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        guard_roots = _self_roots(node.test)
        if not guard_roots:
            continue
        body_nodes = {id(n) for n in ast.walk(node)} - {id(node)}
        for pos2 in range(pos + 1, len(st.seq)):
            n2, e2 = st.seq[pos2]
            if id(n2) not in body_nodes or id(n2) in st.locked:
                continue
            if e2 <= epoch:
                continue                 # no await crossed yet
            mut = _mutated_self_root(n2)
            root: Optional[str] = None
            if mut is not None and mut[0] in guard_roots:
                root = mut[0]
            elif (isinstance(n2, ast.Assign)
                  and len(n2.targets) == 1
                  and isinstance(n2.targets[0], ast.Attribute)
                  and isinstance(n2.targets[0].value, ast.Name)
                  and n2.targets[0].value.id == "self"
                  and n2.targets[0].attr in guard_roots):
                root = n2.targets[0].attr
            if root is None:
                continue
            la = st.last_await_before(pos2)
            if la is not None and la > pos and st.reads_between(
                    root, la, pos2):
                continue                 # re-validated after the await
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=n2.lineno,
                symbol=f"{func.qualname}:guard@self.{root}",
                message=(f"async-atomicity: guard on `self.{root}` "
                         f"(line {node.lineno}) and the act on it "
                         f"straddle an await — the guarded condition "
                         f"may no longer hold when the mutation runs"),
                hint=_HINT))
            break    # one finding per guard is enough signal


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    for func in ctx.iter_funcs():
        if not func.is_async or func.cls_name is None:
            continue
        st = _FuncState(func)
        _check_snapshots(st, findings)
        _check_guards(st, findings)
    return findings
