"""DL010: metrics-plane closure — field → gauge → dashboard → mock feed.

The metrics plane is a four-hop producer→consumer chain that grew one
hand-policed hop per PR: an engine counter becomes a
``ForwardPassMetrics`` field, the field feeds a gauge table in
``components/metrics.py``, the exported ``nv_llm_*`` series appears on
the Grafana dashboard, and ``mock_worker`` feeds it synthetically so the
whole stack runs with zero TPUs. A hop someone forgets is a silent gap:
a counter nobody scrapes, a gauge nobody plots, a panel the no-GPU
fixture never lights up.

Three checks, all against sets READ FROM THE CODE (dataflow constant
pass — no curated copy inside the rule):

1. every ``ForwardPassMetrics`` dataclass field appears as a key in one
   of the metrics module's gauge tables (``_GAUGE_FIELDS`` or any
   module-level ``*_GAUGES`` dict; dict-valued fields like
   ``tenant_stats`` are covered by a labeled table whose name carries
   the field's family);
2. every exported gauge/counter NAME — gauge-table values, the derived
   ``{PREFIX}_{field}`` family, and any ``Gauge("literal", …)``
   registration — appears in the Grafana dashboard JSON;
3. every gauge-table FIELD is fed by mock_worker (referenced as a
   string key, attribute, or constructor kwarg in its source) — the
   zero-TPU fixture must light every panel.

Waive a deliberately-unplotted internal gauge at the table entry line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..engine import Finding, RepoContext

RULE_ID = "DL010"

_HINT = ("wire the full plane: ForwardPassMetrics field → a *_GAUGES "
         "table (components/metrics.py) → the Grafana dashboard JSON → "
         "a mock_worker synthetic feed (docs/static_analysis.md "
         "'adding a plane')")


def _dataclass_fields(ctx: RepoContext) -> Dict[str, int]:
    """{field: lineno} of the metrics dataclass."""
    mod = ctx.graph.modules.get(ctx.metrics_protocol_module)
    if mod is None:
        return {}
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and \
                node.name == ctx.metrics_dataclass:
            out: Dict[str, int] = {}
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    out[item.target.id] = item.lineno
            return out
    return {}


def _gauge_tables(ctx: RepoContext):
    """(field → exported name, field → table lineno, plain-field set,
    labeled-table names) from the metrics module."""
    mod = ctx.graph.modules.get(ctx.metrics_module)
    if mod is None:
        return {}, {}, set(), set()
    consts = ctx.graph.consts
    field_to_name: Dict[str, str] = {}
    field_line: Dict[str, int] = {}
    labeled_tables: Set[str] = set()
    plain_fields: Set[str] = set()
    prefix = consts.const_str(mod, "PREFIX") or "nv_llm"
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tname = node.targets[0].id
        if tname == "_GAUGE_FIELDS":
            fields = consts.str_set(mod, tname) or set()
            for f in fields:
                plain_fields.add(f)
                field_to_name[f] = f"{prefix}_{f}"
                field_line[f] = node.lineno
        elif tname.endswith("_GAUGES"):
            table = consts.str_dict(mod, tname)
            if table is None:
                continue
            labeled_tables.add(tname)
            for f, name in table.items():
                field_to_name.setdefault(f, name)
                field_line.setdefault(f, node.lineno)
    return field_to_name, field_line, plain_fields, labeled_tables


def _registered_names(ctx: RepoContext) -> Set[str]:
    """Metric names passed literally (or PREFIX-resolvably) to
    Gauge()/Counter()/Histogram() registrations in the metrics module."""
    mod = ctx.graph.modules.get(ctx.metrics_module)
    if mod is None:
        return set()
    consts = ctx.graph.consts
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        callee = node.func
        tail = (callee.attr if isinstance(callee, ast.Attribute)
                else callee.id if isinstance(callee, ast.Name) else "")
        if tail not in ("Gauge", "Counter", "Histogram", "Summary"):
            continue
        name = consts.resolve_str_expr(mod, node.args[0])
        if name and "\x00" not in name:
            out.add(name)
    return out


def _mock_worker_tokens(ctx: RepoContext) -> Set[str]:
    mod = ctx.graph.modules.get(ctx.mock_worker_module)
    if mod is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.keyword) and node.arg:
            out.add(node.arg)
    return out


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    if not ctx.closure_relevant(ctx.metrics_module,
                                ctx.metrics_protocol_module,
                                ctx.mock_worker_module,
                                ctx.grafana_dashboard_path):
        return []      # --changed-only: metrics plane untouched
    fields = _dataclass_fields(ctx)
    if not fields:
        return findings        # fixture tree without the protocol module
    field_to_name, field_line, _plain, labeled = _gauge_tables(ctx)
    metrics_rel = ctx.metrics_module
    proto_rel = ctx.metrics_protocol_module

    # dict-valued fields (per-tenant stats) are covered by a labeled
    # table named after their family: tenant_stats ↔ _TENANT_GAUGES
    def _labeled_covers(field: str) -> bool:
        stem = field.split("_")[0].upper()
        return any(t.strip("_").startswith(stem) for t in labeled)

    for f, line in sorted(fields.items()):
        if f in field_to_name or _labeled_covers(f):
            continue
        findings.append(Finding(
            rule=RULE_ID, path=proto_rel, line=line,
            symbol=f"{ctx.metrics_dataclass}.{f}:unscraped",
            message=(f"ForwardPassMetrics.{f} is published by every "
                     f"worker but no gauge table in {metrics_rel} "
                     f"consumes it — a counter nobody scrapes"),
            hint=_HINT))

    dashboard = ctx.read_file(ctx.grafana_dashboard_path)
    exported = set(field_to_name.values()) | _registered_names(ctx)
    if dashboard is None:
        findings.append(Finding(
            rule=RULE_ID, path=ctx.grafana_dashboard_path, line=1,
            symbol="dashboard:missing",
            message=f"Grafana dashboard {ctx.grafana_dashboard_path} "
                    f"not found — the gauge allowlist has no home",
            hint=_HINT))
    else:
        for name in sorted(exported):
            if name not in dashboard:
                line = 1
                for f, n in field_to_name.items():
                    if n == name:
                        line = field_line.get(f, 1)
                        break
                findings.append(Finding(
                    rule=RULE_ID, path=metrics_rel, line=line,
                    symbol=f"{name}:unplotted",
                    message=(f"exported metric `{name}` is missing from "
                             f"{ctx.grafana_dashboard_path} — a gauge "
                             f"nobody plots (or a stale export)"),
                    hint=_HINT))

    mock_tokens = _mock_worker_tokens(ctx)
    if mock_tokens:
        for f in sorted(field_to_name):
            if f not in mock_tokens:
                findings.append(Finding(
                    rule=RULE_ID, path=ctx.mock_worker_module, line=1,
                    symbol=f"{f}:unfed",
                    message=(f"gauge-table field `{f}` is never fed by "
                             f"{ctx.mock_worker_module} — the zero-TPU "
                             f"fixture leaves its panel dark"),
                    hint=_HINT))
    return findings
