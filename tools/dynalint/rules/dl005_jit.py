"""DL005: jit-boundary purity — functions handed to ``jax.jit`` /
``shard_map`` / ``pl.pallas_call`` must be deterministic pure traces.

The recorded-replay and multihost-follower machinery depend on every
compiled program being a pure function of its arguments: a follower
replays the leader's dispatch stream and must produce bit-identical
device state. A jitted function that reads wall-clock or stdlib random
bakes a trace-time value into the compiled program (different per
process — followers diverge); one that MUTATES engine attributes runs
the mutation once at trace time and never again (silent state skew).

Flagged inside a jit-target body (and its nested defs):

- wall-clock reads: ``time.time/monotonic/perf_counter/time_ns``,
  ``datetime.now/utcnow``
- non-JAX randomness: stdlib ``random.*``, ``np.random.*``,
  ``secrets.*``, ``uuid.*`` (``jax.random`` with explicit keys is the
  sanctioned source)
- environment reads: ``os.environ`` / ``os.getenv`` (trace-time
  constants that differ across hosts)
- attribute mutation: assignment/augassign to ``self.X`` or to a
  ``global`` — trace-time side effects
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..callgraph import FuncInfo, dotted_text, shallow_walk
from ..engine import Finding, RepoContext

RULE_ID = "DL005"

_JIT_ENTRYPOINTS = {"jit", "shard_map", "pallas_call", "named_call",
                    "checkpoint", "custom_vjp"}
_IMPURE_CALLS = {
    "time": {"time", "monotonic", "perf_counter", "time_ns",
             "process_time"},
    "datetime": {"now", "utcnow", "today"},
    "os": {"getenv"},
    "secrets": {"token_hex", "token_bytes", "randbits", "choice"},
    "uuid": {"uuid1", "uuid4"},
}
_IMPURE_MODULES = {"random", "secrets", "uuid"}


def _jit_targets(ctx: RepoContext) -> List[FuncInfo]:
    """FuncInfos referenced as the function argument of a jit-like
    entrypoint: decorators (@jax.jit, @partial(jax.jit, ...)) and direct
    wrapping calls (jax.jit(f), shard_map(f, ...), pl.pallas_call(k,...))."""
    out: List[FuncInfo] = []
    seen: Set[str] = set()

    def add(func: Optional[FuncInfo]) -> None:
        if func is not None and func.fid not in seen:
            seen.add(func.fid)
            out.append(func)

    def resolve_name(enclosing: Optional[FuncInfo], mod, name: str
                     ) -> Optional[FuncInfo]:
        cur = enclosing
        while cur is not None:
            if name in cur.nested:
                return ctx.graph.funcs[cur.nested[name]]
            cur = (ctx.graph.funcs.get(cur.parent_fid)
                   if cur.parent_fid else None)
        return mod.functions.get(name)

    for func in ctx.graph.funcs.values():
        # decorators on the function itself
        for dec in getattr(func.node, "decorator_list", []):
            texts = []
            if isinstance(dec, ast.Call):
                texts.append(dotted_text(dec.func) or "")
                texts.extend(dotted_text(a) or "" for a in dec.args)
            else:
                texts.append(dotted_text(dec) or "")
            for t in texts:
                if t.rsplit(".", 1)[-1] in _JIT_ENTRYPOINTS:
                    add(func)
        # wrapping calls inside function bodies
        for call in func.calls:
            if call.text.rsplit(".", 1)[-1] not in _JIT_ENTRYPOINTS:
                continue
            args = list(call.node.args) + [kw.value
                                           for kw in call.node.keywords
                                           if kw.arg in ("f", "fun",
                                                         "kernel")]
            for a in args:
                if isinstance(a, ast.Name):
                    add(resolve_name(func, func.module, a.id))
    # module-level wrapping: f_jit = jax.jit(f)
    for mod in ctx.graph.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                callee = dotted_text(node.value.func) or ""
                if callee.rsplit(".", 1)[-1] in _JIT_ENTRYPOINTS:
                    for a in node.value.args:
                        if isinstance(a, ast.Name):
                            add(mod.functions.get(a.id))
    return out


def _impure_call_desc(func: FuncInfo, text: str) -> Optional[str]:
    parts = text.split(".")
    mod = func.module
    if len(parts) == 1:
        entry = mod.from_imports.get(parts[0])
        if entry and entry[1] in _IMPURE_CALLS.get(entry[0], ()):
            return f"{entry[0]}.{entry[1]}"
        return None
    head = mod.imports.get(parts[0], parts[0])
    if head in _IMPURE_MODULES:
        return text
    if head == "numpy" and len(parts) >= 2 and parts[1] == "random":
        return text
    tail = parts[-1]
    if tail in _IMPURE_CALLS.get(head, ()):
        return f"{head}.{tail}"
    # datetime.datetime.now()
    if head == "datetime" and tail in _IMPURE_CALLS["datetime"]:
        return text
    return None


def _check_body(ctx: RepoContext, func: FuncInfo,
                findings: List[Finding]) -> None:
    for call in func.calls:
        desc = _impure_call_desc(func, call.text)
        if desc:
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=call.lineno,
                symbol=f"{func.qualname}:{desc}",
                message=(f"jit-boundary impurity: `{desc}` inside "
                         f"jitted `{func.qualname}` bakes a trace-time "
                         f"value into the compiled program (followers/"
                         f"replay diverge)"),
                hint=("pass the value in as an argument, or use "
                      "jax.random with an explicit threaded key")))
        if call.text == "os.environ.get" or call.text.startswith(
                "os.environ"):
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=call.lineno,
                symbol=f"{func.qualname}:environ",
                message=(f"jit-boundary impurity: environment read "
                         f"inside jitted `{func.qualname}` is a "
                         f"trace-time constant that differs across "
                         f"hosts"),
                hint="thread it through as a static argument"))
    for n in shallow_walk(func.node):
        if isinstance(n, ast.Global):
            findings.append(Finding(
                rule=RULE_ID, path=func.path, line=n.lineno,
                symbol=f"{func.qualname}:global",
                message=(f"jit-boundary impurity: `global` mutation in "
                         f"jitted `{func.qualname}` runs once at trace "
                         f"time, never per step"),
                hint="return the value instead of mutating state"))
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, ast.AugAssign):
            targets = [n.target]
        for t in targets:
            if isinstance(t, ast.Attribute):
                root = t
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "self":
                    findings.append(Finding(
                        rule=RULE_ID, path=func.path, line=n.lineno,
                        symbol=f"{func.qualname}:self-mutation",
                        message=(f"jit-boundary impurity: `self` "
                                 f"attribute mutation in jitted "
                                 f"`{func.qualname}` happens at trace "
                                 f"time only — silent state skew"),
                        hint="hoist the mutation out of the traced "
                             "function"))


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    visited: Set[str] = set()
    for target in _jit_targets(ctx):
        stack = [target]
        while stack:
            f = stack.pop()
            if f.fid in visited:
                continue
            visited.add(f.fid)
            _check_body(ctx, f, findings)
            # nested defs trace as part of the parent
            for fid in f.nested.values():
                stack.append(ctx.graph.funcs[fid])
    return findings
