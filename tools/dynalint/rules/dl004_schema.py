"""DL004: wire-schema lock for the request/event-plane dataclasses.

The frontend, workers, and multihost followers exchange JSON payloads
shaped by the dataclasses in runtime/codec.py, llm/protocols/ and
llm/kv_router/protocols.py. Those classes all decode defensively
("defaults keep old payloads decoding", "absent on old senders; ignored
by old receivers") — but nothing ENFORCES that discipline, so a careless
edit silently drifts the fleet until a mixed-version deploy starts
dropping fields. This rule locks the schemas into a committed file
(tools/dynalint/schemas.lock.json) and fails the lint on:

- a removed class or removed field (old peers still send/expect it);
- a changed field type (old payloads decode into the wrong shape);
- a reordered committed-field prefix (positional construction breaks);
- a NEW field without a default (old payloads stop constructing) —
  append-only evolution, the same rule the reference enforces with
  serde defaults;
- a field type outside the JSON-serializable grammar (primitives,
  Optional/List/Dict/Union/... over them, schema-set classes, enums).
  Binary-plane classes (length-prefixed codec frames, device KV
  payloads) may additionally use ``bytes`` / ``np.ndarray``.

Intentional protocol changes are a one-command ritual:
``python -m tools.dynalint --update-schemas`` regenerates the lock;
the diff then documents the protocol change in review.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set

from ..engine import Finding, RepoContext

RULE_ID = "DL004"

# classes carried by a binary transport (not the JSON request plane):
# codec frames are length-prefixed byte containers; KV payloads ship
# device arrays over the dedicated KV stream
_BINARY_PLANE_EXTRA = {
    "Frame": {"bytes"},
    "KvPayload": {"np.ndarray", "ndarray"},
    "DeviceKvPayload": {"np.ndarray", "ndarray"},
    # LayeredHarvest never serializes: it is the producer's HOST-LOCAL
    # handle over one dispatched device gather (llm/kv/stream.py) — it
    # lives in a schema-watched module only because the wire manifest
    # (LayerStreamManifest) does
    "LayeredHarvest": {"Callable[[int], Dict[str, np.ndarray]]",
                       "Callable[[], Dict[str, np.ndarray]]"},
}

_ALLOWED_ATOMS = {"str", "int", "float", "bool", "dict", "list", "None",
                  "Any", "object", "Dict", "List", "Tuple", "Sequence"}
_ALLOWED_WRAPPERS = {"Optional", "Union", "List", "Dict", "Tuple",
                     "Sequence", "Annotated", "ClassVar"}


def _is_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        text = ast.unparse(dec)
        if text.split("(")[0].rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


def _class_fields(node: ast.ClassDef) -> List[dict]:
    out = []
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            ann = ast.unparse(stmt.annotation)
            if ann.startswith("ClassVar"):
                continue
            out.append({"name": stmt.target.id, "type": ann,
                        "has_default": stmt.value is not None})
    return out


def extract_schemas(ctx: RepoContext) -> Dict[str, dict]:
    """{ClassName: {"path", "fields"}} over the schema files. Also
    returns enum names via the '__enums__' pseudo-entry consumed by the
    type checker."""
    schemas: Dict[str, dict] = {}
    enums: Set[str] = set()
    typevars: Set[str] = set()
    for rel in ctx.schema_paths:
        mod = ctx.graph.modules.get(rel)
        if mod is None:
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                bases = {ast.unparse(b).rsplit(".", 1)[-1]
                         for b in node.bases}
                if bases & {"Enum", "IntEnum", "StrEnum", "Flag"}:
                    enums.add(node.name)
                elif _is_dataclass(node):
                    schemas[node.name] = {"path": rel,
                                          "fields": _class_fields(node)}
            elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                             ast.Call):
                callee = ast.unparse(node.value.func).rsplit(".", 1)[-1]
                if callee == "TypeVar":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            typevars.add(t.id)
    schemas["__enums__"] = {"path": "", "fields": sorted(enums)}
    schemas["__typevars__"] = {"path": "", "fields": sorted(typevars)}
    return schemas


def _type_leaves(ann: str) -> Optional[List[str]]:
    """Leaf type names of an annotation, or None when unparseable."""
    try:
        tree = ast.parse(ann, mode="eval")
    except SyntaxError:
        return None
    leaves: List[str] = []

    def walk(node: ast.expr) -> None:
        if isinstance(node, ast.Subscript):
            head = ast.unparse(node.value).rsplit(".", 1)[-1]
            if head in _ALLOWED_WRAPPERS or head in _ALLOWED_ATOMS:
                walk(node.slice)
            else:
                leaves.append(ast.unparse(node))
        elif isinstance(node, ast.Tuple):
            for e in node.elts:
                walk(e)
        elif isinstance(node, (ast.Name, ast.Attribute)):
            leaves.append(ast.unparse(node))
        elif isinstance(node, ast.Constant):
            leaves.append(repr(node.value) if node.value is not None
                          else "None")
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.BitOr):
            walk(node.left)
            walk(node.right)
        else:
            leaves.append(ast.unparse(node))

    walk(tree.body)
    return leaves


def _check_types(schemas: Dict[str, dict]) -> List[Finding]:
    findings: List[Finding] = []
    known = set(schemas) | set(schemas["__enums__"]["fields"]) \
        | set(schemas["__typevars__"]["fields"])
    for cls, info in schemas.items():
        if cls.startswith("__"):
            continue
        extra = _BINARY_PLANE_EXTRA.get(cls, set())
        for field in info["fields"]:
            leaves = _type_leaves(field["type"])
            if leaves is None:
                continue
            for leaf in leaves:
                short = leaf.rsplit(".", 1)[-1]
                if (leaf in _ALLOWED_ATOMS or short in _ALLOWED_ATOMS
                        or leaf in known or short in known
                        or leaf in extra or short in extra):
                    continue
                findings.append(Finding(
                    rule=RULE_ID, path=info["path"], line=1,
                    symbol=f"{cls}.{field['name']}:type",
                    message=(f"wire dataclass field `{cls}."
                             f"{field['name']}: {field['type']}` uses "
                             f"non-JSON-serializable type `{leaf}` on "
                             f"the request/event plane"),
                    hint=("use JSON-able primitives/containers or a "
                          "schema-set dataclass; binary-plane classes "
                          "are whitelisted in dl004_schema.py")))
    return findings


def _diff_against_lock(schemas: Dict[str, dict],
                       lock: Dict[str, dict]) -> List[Finding]:
    findings: List[Finding] = []
    for cls, linfo in lock.items():
        if cls.startswith("__"):
            continue
        cur = schemas.get(cls)
        if cur is None:
            findings.append(Finding(
                rule=RULE_ID, path=linfo.get("path", "?"), line=1,
                symbol=f"{cls}:removed",
                message=(f"wire dataclass `{cls}` was removed but is "
                         f"committed in the schema lock — old peers "
                         f"still speak it"),
                hint="restore it, or run --update-schemas and document "
                     "the protocol break"))
            continue
        cur_fields = {f["name"]: f for f in cur["fields"]}
        cur_order = [f["name"] for f in cur["fields"]]
        lock_order = [f["name"] for f in linfo["fields"]]
        for lf in linfo["fields"]:
            cf = cur_fields.get(lf["name"])
            if cf is None:
                findings.append(Finding(
                    rule=RULE_ID, path=cur["path"], line=1,
                    symbol=f"{cls}.{lf['name']}:removed",
                    message=(f"field `{cls}.{lf['name']}` was removed "
                             f"from the wire schema — old payloads "
                             f"still carry it / old peers still expect "
                             f"it"),
                    hint="deprecate in place (keep the field, default "
                         "it) or --update-schemas with a fleet-upgrade "
                         "plan"))
            elif cf["type"] != lf["type"]:
                findings.append(Finding(
                    rule=RULE_ID, path=cur["path"], line=1,
                    symbol=f"{cls}.{lf['name']}:type-changed",
                    message=(f"field `{cls}.{lf['name']}` changed type "
                             f"`{lf['type']}` -> `{cf['type']}` — old "
                             f"payloads decode into the wrong shape"),
                    hint="add a NEW defaulted field instead of mutating "
                         "the committed one (append-only evolution)"))
        # committed fields that survive must keep their relative order
        # (positional construction across the fleet)
        surviving = [n for n in lock_order if n in cur_fields]
        cur_positions = {n: i for i, n in enumerate(cur_order)}
        if surviving != sorted(surviving, key=lambda n: cur_positions[n]):
            findings.append(Finding(
                rule=RULE_ID, path=cur["path"], line=1,
                symbol=f"{cls}:reordered",
                message=(f"committed fields of `{cls}` were reordered — "
                         f"positional construction across fleet "
                         f"versions breaks"),
                hint="append new fields AFTER the committed prefix"))
        # new fields must default (old payloads lack them)
        committed = set(lock_order)
        for f in cur["fields"]:
            if f["name"] not in committed and not f["has_default"]:
                findings.append(Finding(
                    rule=RULE_ID, path=cur["path"], line=1,
                    symbol=f"{cls}.{f['name']}:no-default",
                    message=(f"new wire field `{cls}.{f['name']}` has "
                             f"no default — payloads from old senders "
                             f"stop constructing"),
                    hint="give it a default (the 'zeros on old "
                         "payloads' convention) then --update-schemas"))
    return findings


def update_lock(ctx: RepoContext) -> str:
    schemas = extract_schemas(ctx)
    path = os.path.join(ctx.root, ctx.schema_lock_path)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(schemas, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def check(ctx: RepoContext) -> List[Finding]:
    if not ctx.closure_relevant(*ctx.schema_paths, ctx.schema_lock_path):
        return []      # --changed-only: no wire dataclass touched
    schemas = extract_schemas(ctx)
    findings = _check_types(schemas)
    lock_raw = ctx.read_file(ctx.schema_lock_path)
    if lock_raw is None:
        findings.append(Finding(
            rule=RULE_ID, path=ctx.schema_lock_path, line=1,
            symbol="lockfile:missing",
            message="wire-schema lockfile is missing",
            hint="generate it: python -m tools.dynalint --update-schemas"))
        return findings
    try:
        lock = json.loads(lock_raw)
    except ValueError:
        findings.append(Finding(
            rule=RULE_ID, path=ctx.schema_lock_path, line=1,
            symbol="lockfile:corrupt",
            message="wire-schema lockfile is not valid JSON",
            hint="regenerate: python -m tools.dynalint --update-schemas"))
        return findings
    findings.extend(_diff_against_lock(schemas, lock))
    return findings
