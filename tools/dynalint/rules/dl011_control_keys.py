"""DL011: control-key closure — every llmctl write has a live consumer.

``llmctl`` is the fleet's control surface: every subcommand that works
does so because the kvstore key it writes has a watcher loop (or a
poll-read) wired into a running process. Nothing enforces that pairing —
a new ``llmctl foo set`` that writes ``foo/control/{ns}`` with no
``watch_foo_loop`` anywhere ships a knob connected to nothing, and an
unreferenced ``watch_*_loop`` is the same bug from the other side.

Mechanics (all on the dataflow layer — no curated key list in the rule):

- **writes**: ``kv_put`` / ``kv_create`` call sites in the llmctl module
  and in repo functions it directly calls (one resolved hop — the model
  registry's ``add_model`` shape). The key argument resolves to a *key
  ref*: the ``*_key()`` helper it calls (directly or through a local
  ``key = helper(...)`` alias, or a ``<local>.key()`` method on a
  constructed repo object), plus — via the string-constant pass — the
  helper's static return prefix (f-string holes become wildcards);
- **reads**: ``kv_get`` / ``kv_get_prefix`` / ``watch_prefix`` call
  sites in every OTHER module, resolved the same way;
- a write is closed when some read shares its helper or its static
  prefix. Findings land on the unconsumed ``kv_put`` line;
- **orphan watchers**: a module-level ``watch_*_loop`` function that no
  other module references (``create_task(watch_x_loop(...))`` in
  launch/run.py or components/processor.py is the canonical wiring) is
  flagged at its def line.

A deliberately write-only key (an audit trail) waives at the kv_put.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import FuncInfo, dotted_text, resolve_call
from ..engine import Finding, RepoContext

RULE_ID = "DL011"

_WRITE_TAILS = {"kv_put", "kv_create", "kv_create_or_validate"}
_READ_TAILS = {"kv_get", "kv_get_prefix", "watch_prefix"}

_HINT = ("wire the consumer: a watch_*_loop spawned from launch/run.py "
         "or components/processor.py (or a poll-read in the owning "
         "component); a deliberately write-only audit key waives with "
         "`# dynalint: ok DL011 <reason>`")


def _helper_prefix(ctx: RepoContext, helper: FuncInfo) -> Optional[str]:
    """Static return-string prefix of a ``*_key()`` helper (holes →
    wildcard marker, prefix = text before the first hole)."""
    for node in ast.walk(helper.node):
        if isinstance(node, ast.Return) and node.value is not None:
            s = ctx.graph.consts.resolve_str_expr(helper.module, node.value)
            if s is not None:
                return s.split("\x00", 1)[0]
    return None


def _constructed_method(ctx: RepoContext, func: FuncInfo, local: str,
                        meth: str) -> Optional[FuncInfo]:
    """Method ``meth`` on the class a local var was constructed from
    (``spec = DeploymentSpec(...); spec.key()``)."""
    from ..callgraph import _resolve_method_in_class
    for node in ast.walk(func.node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == local
                and isinstance(node.value, ast.Call)):
            callee = dotted_text(node.value.func) or ""
            cname = callee.rsplit(".", 1)[-1]
            if cname[:1].isupper():
                ci, ci_mod = ctx.graph.attr_types._find_class(
                    func.module, cname)
                return _resolve_method_in_class(ctx.graph, ci, ci_mod,
                                                meth)
    return None


class _KeyRef:
    __slots__ = ("helper", "prefix", "line")

    def __init__(self, helper: Optional[str], prefix: Optional[str],
                 line: int):
        self.helper = helper
        self.prefix = prefix
        self.line = line

    def matches(self, other: "_KeyRef") -> bool:
        if self.helper and other.helper and self.helper == other.helper:
            return True
        if self.prefix and other.prefix:
            a, b = self.prefix, other.prefix
            return bool(a) and bool(b) and (a.startswith(b)
                                            or b.startswith(a))
        return False

    def describe(self) -> str:
        if self.helper:
            return f"{self.helper}(…)"
        return f"\"{self.prefix}…\""


def _resolve_key_expr(ctx: RepoContext, func: FuncInfo,
                      expr: ast.AST,
                      local_aliases: Dict[str, Tuple[str, Optional[str]]],
                      line: int) -> Optional[_KeyRef]:
    """Key expression → _KeyRef, or None when unresolvable."""
    mod = func.module
    # helper call:  tenant_control_key(ns)  /  spec.key()
    if isinstance(expr, ast.Call):
        text = dotted_text(expr.func)
        if text is None:
            return None
        name = text.rsplit(".", 1)[-1]
        targets = resolve_call(
            ctx.graph, func,
            type("C", (), {"node": expr, "lineno": line, "text": text})())
        if not targets:
            # <local>.key() where the local was constructed in this
            # function: resolve the method in the constructed class
            parts = text.split(".")
            if len(parts) == 2:
                t = _constructed_method(ctx, func, parts[0], name)
                if t is not None:
                    targets = [t]
        prefix = None
        for t in targets:
            prefix = _helper_prefix(ctx, t)
            if prefix:
                break
        return _KeyRef(name, prefix, line)
    # local alias:  key = helper(...);  kv_put(key, …)
    if isinstance(expr, ast.Name) and expr.id in local_aliases:
        helper, prefix = local_aliases[expr.id]
        return _KeyRef(helper, prefix, line)
    # resolvable string/f-string
    s = ctx.graph.consts.resolve_str_expr(mod, expr)
    if s is not None:
        p = s.split("\x00", 1)[0]
        if p:
            return _KeyRef(None, p, line)
    return None


def _collect_refs(ctx: RepoContext, func: FuncInfo,
                  tails: Set[str]) -> List[_KeyRef]:
    out: List[_KeyRef] = []
    # pre-pass: local ``name = helper(...)`` aliases
    aliases: Dict[str, Tuple[str, Optional[str]]] = {}
    for node in ast.walk(func.node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            text = dotted_text(node.value.func)
            if text is None:
                continue
            name = text.rsplit(".", 1)[-1]
            if not (name.endswith("_key") or name == "key"):
                continue
            targets = resolve_call(
                ctx.graph, func,
                type("C", (), {"node": node.value,
                               "lineno": node.lineno, "text": text})())
            prefix = None
            for t in targets:
                prefix = _helper_prefix(ctx, t)
                if prefix:
                    break
            aliases[node.targets[0].id] = (name, prefix)
    for call in func.calls:
        tail = call.text.rsplit(".", 1)[-1]
        if tail not in tails or not call.node.args:
            continue
        ref = _resolve_key_expr(ctx, func, call.node.args[0], aliases,
                                call.lineno)
        if ref is not None:
            out.append(ref)
    return out


def check(ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    llmctl = ctx.graph.modules.get(ctx.llmctl_module)
    if llmctl is None:
        return findings
    # the write-closure half keys on llmctl itself (--changed-only: a
    # key-helper edit pulls llmctl into the reverse closure via imports)
    if ctx.in_scope(ctx.llmctl_module):
        # writer functions: llmctl's own + one resolved call hop out
        writer_funcs: Dict[str, FuncInfo] = {
            f.fid: f for f in ctx.graph.funcs.values()
            if f.path == ctx.llmctl_module}
        for f in list(writer_funcs.values()):
            for call in f.calls:
                for t in resolve_call(ctx.graph, f, call):
                    writer_funcs.setdefault(t.fid, t)

        writes: List[Tuple[FuncInfo, _KeyRef]] = []
        for f in writer_funcs.values():
            for ref in _collect_refs(ctx, f, _WRITE_TAILS):
                writes.append((f, ref))

        reads: List[_KeyRef] = []
        for f in ctx.graph.funcs.values():
            if f.fid in writer_funcs:
                continue
            reads.extend(_collect_refs(ctx, f, _READ_TAILS))

        for f, w in writes:
            if any(w.matches(r) for r in reads):
                continue
            findings.append(Finding(
                rule=RULE_ID, path=f.path, line=w.line,
                symbol=f"{f.qualname}:{w.helper or w.prefix}",
                message=(f"llmctl writes control key {w.describe()} but "
                         f"no watcher/reader outside the control surface "
                         f"consumes it — a knob wired to nothing"),
                hint=_HINT))

    # orphan watcher loops: defined (in scope), never referenced
    # cross-module. One referenced-name pass over each module instead of
    # a walk per watcher.
    watchers = [f for f in ctx.iter_funcs()
                if f.name.startswith("watch_")
                and f.name.endswith("_loop")
                and f.cls_name is None and f.parent_fid is None]
    if not watchers:
        return findings
    # reference index off the already-collected call sites (a watcher is
    # wired as `create_task(watch_x_loop(...))` — an inner call — or
    # offloaded by reference); no tree re-walks
    referenced_by_module: Dict[str, Set[str]] = {}
    wanted = {f.name for f in watchers}
    for fn in ctx.graph.funcs.values():
        hits = {c.text.rsplit(".", 1)[-1] for c in fn.calls} & wanted
        hits |= {r.rsplit(".", 1)[-1] for r in fn.offloaded_refs} & wanted
        if hits:
            referenced_by_module.setdefault(fn.path, set()).update(hits)
    for f in watchers:
        referenced = any(f.name in names
                         for path, names in referenced_by_module.items()
                         if path != f.path)
        if not referenced:
            findings.append(Finding(
                rule=RULE_ID, path=f.path, line=f.lineno,
                symbol=f"{f.qualname}:orphan-watcher",
                message=(f"`{f.qualname}` is a watcher loop no other "
                         f"module spawns — the control key it watches "
                         f"converges nowhere"),
                hint=_HINT))
    return findings
