"""Rule registry. Each rule module exposes ``RULE_ID`` and
``check(ctx) -> List[Finding]``; adding a rule = adding a module here
(docs/static_analysis.md "adding a rule")."""

from . import (dl001_blocking, dl002_contextvar, dl003_pins, dl004_schema,
               dl005_jit, dl006_mirror, dl007_await, dl008_atomicity,
               dl009_replay_closure, dl010_metrics_closure,
               dl011_control_keys, dl012_determinism)

ALL_RULES = {
    m.RULE_ID: m.check
    for m in (dl001_blocking, dl002_contextvar, dl003_pins, dl004_schema,
              dl005_jit, dl006_mirror, dl007_await, dl008_atomicity,
              dl009_replay_closure, dl010_metrics_closure,
              dl011_control_keys, dl012_determinism)
}
