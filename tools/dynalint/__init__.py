"""dynalint: repo-native static analysis for the invariants this codebase
polices by hand.

Dynamo's Rust core gets its engine-loop, ownership, and wire-contract
invariants checked at compile time; the Python reproduction re-states the
same rules in comments and catches violations at runtime (the flight
recorder's loop-lag probe, prepare_prefill's loud assert, the differential
fuzzer). dynalint rejects those bug classes before merge:

- DL001  blocking call reachable from an ``async def`` without a
         ``to_thread``/executor hop (the engine-loop stall class)
- DL002  contextvar leak: ambient-trace ``.set()`` without a paired
         reset, and long-lived tasks that read the ambient trace
         without detaching at entry (the PR-7 engine-loop bug)
- DL003  pin/hold balance: every pin acquisition reaches a release on
         all paths including exception edges (PR-5's runtime assert,
         made static)
- DL004  wire-schema lock: request/event-plane dataclasses checked
         against a committed lockfile (append-only evolution,
         JSON-serializable field types)
- DL005  jit-boundary purity: functions handed to jax.jit/shard_map/
         pallas_call must not read wall-clock, stdlib random, or
         mutate engine state (the recorded-replay determinism contract)
- DL006  Python<->C++ mirror drift: csrc exported ABI symbols and
         arities vs their ctypes wrappers (the "mirrored EXACTLY"
         contract behind the fuzz-locked pools)

Run ``python -m tools.dynalint`` from the repo root. See
docs/static_analysis.md for the rule catalog and baseline etiquette.
"""

from .engine import Finding, RepoContext, run_lint  # noqa: F401

__all__ = ["Finding", "RepoContext", "run_lint"]
