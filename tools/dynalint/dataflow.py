"""Lightweight interprocedural dataflow over the RepoGraph.

Three passes, each deliberately shallow (stdlib ``ast``, no fixpoints):

1. **String-constant propagation** (:class:`ModuleConsts`) — module-level
   assignments of string literals and of string collections
   (tuple/list/set/frozenset/dict-of-strings, including ``frozenset({..})``
   wrapping and ``A | B`` unions of resolvable parts) become a per-module
   environment, followed across ``from X import NAME``. This is what lets
   the closure rules (DL009-DL011) read ``WIRE_EVENTS``, gauge tables, and
   key-prefix constants without executing the modules.

2. **Attribute-type resolution** (:class:`AttrTypes`) — ``self.X`` is
   resolved to a repo class via, in confidence order: a class-body or
   ``__init__`` annotated assignment (``self.wal: Optional[Wal] = ...``),
   a direct constructor call (``self.pool = KvBlockPool(...)``), or an
   annotated ``__init__`` parameter aliased onto the attribute
   (``def __init__(self, server: "DiscoveryServer"): self.server =
   server``). ``Optional[T]`` unwraps to ``T``. The call-graph resolver
   uses this to connect ``self.pool.release(...)``-style chains that the
   PR-8 resolver dropped as ambiguous — the documented DL001 blind spot
   (the discovery daemon's WAL fsync behind sync session glue) closes
   through exactly this pass.

3. **Await-point segmentation** (:func:`await_epochs`) — a source-order
   walk of an async function body yielding ``(node, epoch)`` where the
   epoch increments after every ``await`` (including ``async for`` /
   ``async with`` headers). DL008's stale-read detection is a comparison
   of binding epochs against use epochs on this numbering.

Everything here follows the PR-8 precision contract: ambiguity yields
*nothing* (no constant, no type, no edge) — a tier-1 zero-findings gate
cannot afford optimistic guesses.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .callgraph import ModuleInfo, RepoGraph, dotted_text

# ---------------------------------------------------------------- constants


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class ModuleConsts:
    """Module-level string/str-collection constant environment."""

    def __init__(self, graph: RepoGraph):
        self.graph = graph
        self._strs: Dict[str, Dict[str, str]] = {}
        self._sets: Dict[str, Dict[str, Set[str]]] = {}
        self._dicts: Dict[str, Dict[str, Dict[str, str]]] = {}
        for mod in graph.modules.values():
            self._collect(mod)

    def _collect(self, mod: ModuleInfo) -> None:
        strs: Dict[str, str] = {}
        sets: Dict[str, Set[str]] = {}
        dicts: Dict[str, Dict[str, str]] = {}
        for node in mod.tree.body:
            targets: List[ast.expr] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                s = _literal_str(value)
                if s is not None:
                    strs[t.id] = s
                    continue
                ss = self._eval_str_set(mod, value, strs, sets)
                if ss is not None:
                    sets[t.id] = ss
                    continue
                d = self._eval_str_dict(value)
                if d is not None:
                    dicts[t.id] = d
        self._strs[mod.path] = strs
        self._sets[mod.path] = sets
        self._dicts[mod.path] = dicts

    def _eval_str_set(self, mod: ModuleInfo, node: ast.AST,
                      strs: Dict[str, str],
                      sets: Dict[str, Set[str]]) -> Optional[Set[str]]:
        """String-collection literal → set of strings, or None."""
        if isinstance(node, ast.Call):
            callee = dotted_text(node.func) or ""
            if callee.rsplit(".", 1)[-1] in ("frozenset", "set", "tuple",
                                             "list", "sorted"):
                if len(node.args) == 1:
                    return self._eval_str_set(mod, node.args[0], strs, sets)
                if not node.args:
                    return set()
            return None
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: Set[str] = set()
            for el in node.elts:
                s = _literal_str(el)
                if s is None:
                    return None
                out.add(s)
            return out
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
            left = self._eval_str_set(mod, node.left, strs, sets)
            right = self._eval_str_set(mod, node.right, strs, sets)
            if left is not None and right is not None:
                return left | right
            return None
        if isinstance(node, ast.Name):
            if node.id in sets:
                return set(sets[node.id])
            return self.str_set(mod, node.id)
        return None

    def _eval_str_dict(self, node: ast.AST) -> Optional[Dict[str, str]]:
        if not isinstance(node, ast.Dict):
            return None
        out: Dict[str, str] = {}
        for k, v in zip(node.keys, node.values):
            ks = _literal_str(k) if k is not None else None
            vs = _literal_str(v) if v is not None else None
            if ks is None or vs is None:
                return None
            out[ks] = vs
        return out

    # -------------------------------------------------------------- queries
    def _follow_import(self, mod: ModuleInfo,
                       name: str) -> Optional[Tuple[ModuleInfo, str]]:
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.graph.by_dotted.get(src)
            if target is not None:
                return target, orig
        return None

    def const_str(self, mod: ModuleInfo, name: str) -> Optional[str]:
        v = self._strs.get(mod.path, {}).get(name)
        if v is not None:
            return v
        hop = self._follow_import(mod, name)
        if hop is not None:
            return self.const_str(*hop)
        return None

    def str_set(self, mod: ModuleInfo, name: str) -> Optional[Set[str]]:
        v = self._sets.get(mod.path, {}).get(name)
        if v is not None:
            return v
        hop = self._follow_import(mod, name)
        if hop is not None:
            return self.str_set(*hop)
        return None

    def str_dict(self, mod: ModuleInfo,
                 name: str) -> Optional[Dict[str, str]]:
        v = self._dicts.get(mod.path, {}).get(name)
        if v is not None:
            return v
        hop = self._follow_import(mod, name)
        if hop is not None:
            return self.str_dict(*hop)
        return None

    def resolve_str_expr(self, mod: ModuleInfo,
                         node: ast.AST) -> Optional[str]:
        """Literal, module constant, or an f-string/concat whose parts
        all resolve — used to resolve key expressions like
        ``f"{PREFIX}control/{ns}"`` down to a match PREFIX."""
        s = _literal_str(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name):
            return self.const_str(mod, node.id)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self.resolve_str_expr(mod, node.left)
            right = self.resolve_str_expr(mod, node.right)
            if left is None:
                return None
            # an unresolvable tail (a runtime name) is a wildcard hole,
            # same as an f-string's formatted value
            return left + (right if right is not None else "\x00")
        if isinstance(node, ast.JoinedStr):
            parts: List[str] = []
            for v in node.values:
                s = _literal_str(v)
                if s is not None:
                    parts.append(s)
                elif isinstance(v, ast.FormattedValue):
                    inner = self.resolve_str_expr(mod, v.value)
                    # an unresolvable hole (a runtime argument like the
                    # namespace) resolves as a wildcard marker — callers
                    # match on the static PREFIX before the first hole
                    parts.append(inner if inner is not None else "\x00")
            return "".join(parts)
        return None


# -------------------------------------------------------------- attr types


def _annotation_class_name(node: ast.AST) -> Optional[str]:
    """``Wal`` / ``"Wal"`` / ``Optional[Wal]`` / ``mod.Wal`` → "Wal"."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # string forward reference, possibly "Optional[X]"
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        base = dotted_text(node.value) or ""
        if base.rsplit(".", 1)[-1] in ("Optional",):
            return _annotation_class_name(node.slice)
        return None
    text = dotted_text(node)
    if text is None:
        return None
    return text.rsplit(".", 1)[-1]


class AttrTypes:
    """(module path, class name, attr) → class name, high-confidence only.

    Conflicting evidence (two inits assigning different classes) removes
    the entry — precision over recall, as everywhere in dynalint.
    """

    _CONFLICT = "\x00conflict"

    def __init__(self, graph: RepoGraph):
        self.graph = graph
        # (path, cls, attr) -> class name
        self._types: Dict[Tuple[str, str, str], str] = {}
        for mod in graph.modules.values():
            for ci in mod.classes.values():
                self._collect_class(mod, ci)

    def _note(self, key: Tuple[str, str, str], cls_name: str) -> None:
        cur = self._types.get(key)
        if cur is None:
            self._types[key] = cls_name
        elif cur != cls_name:
            self._types[key] = self._CONFLICT

    def _collect_class(self, mod: ModuleInfo, ci) -> None:
        # class-body annotations:  pool: KvBlockPool
        cls_node = None
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == ci.name:
                cls_node = node
                break
        if cls_node is not None:
            for item in cls_node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    cn = _annotation_class_name(item.annotation)
                    if cn and self._is_repo_class(mod, cn):
                        self._note((mod.path, ci.name, item.target.id), cn)
        init = ci.methods.get("__init__")
        if init is None:
            return
        params: Dict[str, str] = {}
        args = init.node.args
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                cn = _annotation_class_name(a.annotation)
                if cn and self._is_repo_class(mod, cn):
                    params[a.arg] = cn
        for stmt in ast.walk(init.node):
            target = None
            value = None
            if isinstance(stmt, ast.AnnAssign):
                target, value = stmt.target, stmt.value
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    cn = _annotation_class_name(stmt.annotation)
                    if cn and self._is_repo_class(mod, cn):
                        self._note((mod.path, ci.name, target.attr), cn)
                    continue
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self") or value is None:
                continue
            key = (mod.path, ci.name, target.attr)
            # self.pool = KvBlockPool(...)
            if isinstance(value, ast.Call):
                callee = dotted_text(value.func) or ""
                cn = callee.rsplit(".", 1)[-1]
                if cn and cn[:1].isupper() and self._is_repo_class(mod, cn):
                    self._note(key, cn)
                continue
            # self.server = server   (annotated __init__ param)
            if isinstance(value, ast.Name) and value.id in params:
                self._note(key, params[value.id])

    def _is_repo_class(self, mod: ModuleInfo, name: str) -> bool:
        if name in mod.classes:
            return True
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.graph.by_dotted.get(src)
            return target is not None and orig in target.classes
        return False  # no global fallback: module-scoped visibility only

    def _find_class(self, mod: ModuleInfo, name: str):
        """ClassInfo + its module for a name visible from ``mod``."""
        if name in mod.classes:
            return mod.classes[name], mod
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.graph.by_dotted.get(src)
            if target is not None and orig in target.classes:
                return target.classes[orig], target
        return None, None

    def attr_class(self, mod: ModuleInfo, cls_name: str, attr: str):
        """ClassInfo (and its module) for ``self.<attr>`` inside
        ``cls_name``, following single-module base classes; None when
        unknown or conflicting."""
        seen: Set[str] = set()
        cur, cur_mod = mod.classes.get(cls_name), mod
        while cur is not None and cur.name not in seen:
            seen.add(cur.name)
            cn = self._types.get((cur_mod.path, cur.name, attr))
            if cn == self._CONFLICT:
                return None, None
            if cn is not None:
                return self._find_class(cur_mod, cn)
            nxt, nxt_mod = None, None
            for b in cur.bases:
                bname = b.split(".")[-1]
                cand, cand_mod = self._find_class(cur_mod, bname)
                if cand is not None and cand.name not in seen:
                    nxt, nxt_mod = cand, cand_mod
                    break
            cur, cur_mod = nxt, nxt_mod
        return None, None


# ----------------------------------------------------- await segmentation


class _EpochWalker:
    """Source-order walk with an epoch that bumps after every await."""

    def __init__(self) -> None:
        self.epoch = 0
        self.out: List[Tuple[ast.AST, int]] = []

    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _expr(self, node: ast.AST) -> None:
        """Post-order over an expression: an Await's operand evaluates
        BEFORE the suspension, uses after it see the next epoch."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Await):
            self._expr(node.value)
            self.out.append((node, self.epoch))
            self.epoch += 1
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child)
        self.out.append((node, self.epoch))

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test)
            self.out.append((stmt, self.epoch))
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            if isinstance(stmt, ast.AsyncFor):
                self.epoch += 1      # each item crosses a suspension
            self._expr(stmt.target)
            self.out.append((stmt, self.epoch))
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._expr(item.optional_vars)
            if isinstance(stmt, ast.AsyncWith):
                self.epoch += 1      # __aenter__ suspends
            self.out.append((stmt, self.epoch))
            self.walk(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.out.append((stmt, self.epoch))
            self.walk(stmt.body)
            for h in stmt.handlers:
                self.walk(h.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        # plain statement: post-order its expressions, THEN the statement
        # itself — so an ``x = await f()`` binding carries the POST-await
        # epoch (the bound value is as fresh as the suspension it crossed)
        for child in ast.iter_child_nodes(stmt):
            self._expr(child)
        self.out.append((stmt, self.epoch))


def await_epochs(func_node: ast.AST) -> List[Tuple[ast.AST, int]]:
    """``[(node, epoch)]`` in evaluation order for an (async) function
    body; the epoch increments at every suspension point. Nested function
    bodies are excluded (they run in their own context)."""
    w = _EpochWalker()
    w.walk(func_node.body)
    return w.out


def iter_assign_names(node: ast.AST) -> Iterator[str]:
    """Names bound by an assignment target (flattening tuples)."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from iter_assign_names(el)
