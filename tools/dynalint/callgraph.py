"""Module index + lightweight call graph over the repo's Python sources.

Design goals (shared by every dynalint rule):

- stdlib ``ast`` only — no new dependencies;
- conservative edges: an edge exists only when the target is resolvable
  with high confidence (same-scope nested function, same-module function,
  ``self.method`` in the defining class, a repo-internal ``from X import
  f`` / ``mod.f`` call, or a method name defined by exactly ONE class in
  the repo). Ambiguity yields NO edge — precision over recall, because a
  tier-1 gate must hold zero false positives;
- offload-aware: a function referenced (not called) as an argument to
  ``asyncio.to_thread`` / ``loop.run_in_executor`` / ``Thread(target=…)``
  / ``executor.submit`` runs OFF the event loop, so no call edge is
  created from the enclosing (async) function;
- constructor calls (``SomeClass(…)``) create no edges: ``__init__``
  chains are overwhelmingly startup-time and would drown the async
  reachability analysis in engine-construction noise.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

# callables whose function-valued argument runs off the event loop
_OFFLOADERS = {"to_thread", "run_in_executor", "submit", "Thread",
               "start_new_thread", "run_sync_in_worker_thread"}

# module aliases that never resolve to repo code (pruned before the
# unique-method fallback can mistake e.g. ``np.load`` for a repo method)
_EXTERNAL_MODULES = {
    "os", "sys", "io", "json", "time", "math", "struct", "socket",
    "asyncio", "subprocess", "threading", "logging", "contextlib",
    "dataclasses", "functools", "itertools", "collections", "typing",
    "numpy", "np", "jax", "jnp", "ctypes", "base64", "random", "secrets",
    "heapq", "bisect", "shutil", "tempfile", "signal", "uuid", "enum",
    "re", "pickle", "hashlib", "urllib", "http", "gzip", "pathlib",
    "inspect", "traceback", "warnings", "errno", "stat", "string",
    "textwrap", "argparse", "xxhash", "ml_dtypes",
}


@dataclasses.dataclass
class CallSite:
    node: ast.Call
    lineno: int
    # dotted text of the callee, e.g. "self.pool.release", "np.load"
    text: str


@dataclasses.dataclass
class FuncInfo:
    fid: str                       # "relpath::Class.name" / "relpath::name"
    path: str                      # repo-relative source path
    module: "ModuleInfo"
    name: str
    qualname: str                  # Class.name or outer.<locals>.name
    node: ast.AST
    is_async: bool
    cls_name: Optional[str] = None
    parent_fid: Optional[str] = None
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    # bare-Name references handed to an offloader (run off-loop)
    offloaded_refs: Set[str] = dataclasses.field(default_factory=set)
    # nested function names defined directly in this function's body
    nested: Dict[str, str] = dataclasses.field(default_factory=dict)
    # bound-method aliases: ``log = self.server.wal_append`` makes a
    # later bare ``log(...)`` resolvable as the dotted chain
    aliases: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def lineno(self) -> int:
        return self.node.lineno


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    bases: List[str]
    methods: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleInfo:
    path: str                      # repo-relative path
    dotted: Optional[str]          # "dynamo_tpu.llm.kv.pool" when a package
    tree: ast.Module
    source: str
    lines: List[str]
    # import alias -> dotted module ("np" -> "numpy")
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    # from-import: local name -> (dotted module, original name)
    from_imports: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    functions: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)


def dotted_text(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, None for anything fancier."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FuncCollector:
    """Collects direct calls + offloaded references for ONE function body,
    without descending into nested function/lambda bodies (those become
    their own FuncInfo nodes). Iterative — NodeVisitor dispatch overhead
    was a third of graph construction on the full tree."""

    _SKIP = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def __init__(self, info: FuncInfo):
        self.info = info
        self._root = info.node

    def _collect(self) -> None:
        info = self.info
        stack: List[ast.AST] = list(self._root.body)
        while stack:
            node = stack.pop()
            if isinstance(node, self._SKIP):
                continue
            if isinstance(node, ast.Call):
                text = dotted_text(node.func)
                if text is not None:
                    info.calls.append(CallSite(node, node.lineno, text))
                    tail = text.rsplit(".", 1)[-1]
                    if tail in _OFFLOADERS:
                        args = list(node.args)
                        for kw in node.keywords:
                            args.append(kw.value)
                        for a in args:
                            if isinstance(a, ast.Name):
                                info.offloaded_refs.add(a.id)
                            elif isinstance(a, ast.Attribute):
                                t = dotted_text(a)
                                if t:
                                    info.offloaded_refs.add(t)
            elif isinstance(node, ast.Assign):
                # bound-method alias: name = <dotted chain> (no call)
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Attribute)):
                    text = dotted_text(node.value)
                    if text is not None:
                        info.aliases[node.targets[0].id] = text
            stack.extend(ast.iter_child_nodes(node))


class RepoGraph:
    """Index of every module/class/function plus on-demand call edges."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}        # relpath -> module
        self.by_dotted: Dict[str, ModuleInfo] = {}      # dotted -> module
        self.funcs: Dict[str, FuncInfo] = {}            # fid -> info
        self.method_index: Dict[str, List[FuncInfo]] = {}  # name -> methods
        self.func_index: Dict[str, List[FuncInfo]] = {}    # name -> module fns
        self._attr_types = None
        self._consts = None

    @property
    def attr_types(self):
        """Lazy dataflow pass: self-attribute → class resolution
        (dataflow.AttrTypes). Built on first use so graph construction
        stays cheap for callers that never need typed chains."""
        if self._attr_types is None:
            from .dataflow import AttrTypes
            self._attr_types = AttrTypes(self)
        return self._attr_types

    @property
    def consts(self):
        """Lazy dataflow pass: module-level string-constant environment
        (dataflow.ModuleConsts)."""
        if self._consts is None:
            from .dataflow import ModuleConsts
            self._consts = ModuleConsts(self)
        return self._consts

    # ------------------------------------------------------------- loading
    def add_source(self, relpath: str, source: str) -> Optional[ModuleInfo]:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return None
        dotted = None
        if relpath.endswith(".py"):
            dotted = relpath[:-3].replace(os.sep, ".").replace("/", ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[: -len(".__init__")]
        mod = ModuleInfo(path=relpath, dotted=dotted, tree=tree,
                         source=source, lines=source.splitlines())
        self._collect_imports(mod)
        self._collect_defs(mod)
        self.modules[relpath] = mod
        if dotted:
            self.by_dotted[dotted] = mod
        return mod

    def _collect_imports(self, mod: ModuleInfo) -> None:
        # imports are STATEMENTS (module body, class/function bodies,
        # if/try arms) — walk statement lists only, never expressions;
        # this is ~half of graph-construction cost on a large tree
        stack: List[ast.AST] = list(mod.tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = a.name
                continue
            if isinstance(node, ast.ImportFrom):
                base = self._resolve_from(mod, node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.from_imports[a.asname or a.name] = (base, a.name)
                continue
            for attr in ("body", "orelse", "finalbody"):
                stack.extend(getattr(node, attr, ()))
            for h in getattr(node, "handlers", ()):
                stack.extend(h.body)

    def _resolve_from(self, mod: ModuleInfo, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        pkg_parts = (mod.dotted or "").split(".")
        # level 1 = current package; strip the module's own name first
        pkg_parts = pkg_parts[: -node.level]
        if node.module:
            pkg_parts.append(node.module)
        return ".".join(p for p in pkg_parts if p)

    def _collect_defs(self, mod: ModuleInfo) -> None:
        def add_func(node, qualname, cls_name=None, parent_fid=None):
            fid = f"{mod.path}::{qualname}"
            info = FuncInfo(
                fid=fid, path=mod.path, module=mod, name=node.name,
                qualname=qualname, node=node,
                is_async=isinstance(node, ast.AsyncFunctionDef),
                cls_name=cls_name, parent_fid=parent_fid)
            _FuncCollector(info)._collect()
            self.funcs[fid] = info
            if parent_fid and parent_fid in self.funcs:
                self.funcs[parent_fid].nested[node.name] = fid
            # recurse into directly-nested defs (shallow walk stops at
            # nested scopes, so each def is added exactly once)
            for stmt in _shallow_descendants(node):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add_func(stmt, f"{qualname}.<locals>.{stmt.name}",
                             cls_name=cls_name, parent_fid=fid)
            return info

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = add_func(node, node.name)
                mod.functions[node.name] = info
                self.func_index.setdefault(node.name, []).append(info)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, path=mod.path,
                               bases=[dotted_text(b) or "" for b in
                                      node.bases])
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = add_func(item, f"{node.name}.{item.name}",
                                      cls_name=node.name)
                        ci.methods[item.name] = fi
                        self.method_index.setdefault(item.name,
                                                     []).append(fi)
                mod.classes[node.name] = ci

def _shallow_descendants(node: ast.AST) -> Iterable[ast.AST]:
    """All descendants of ``node`` that are not inside a nested function/
    class scope."""
    out = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def shallow_walk(node: ast.AST) -> Iterable[ast.AST]:
    """Public alias: descendants excluding nested scopes."""
    return _shallow_descendants(node)


# --------------------------------------------------------------------------
# edge resolution
# --------------------------------------------------------------------------


def _param_annotation(func: FuncInfo, name: str) -> Optional[str]:
    """Class name annotated on parameter ``name`` of ``func``, or None."""
    from .dataflow import _annotation_class_name
    args = func.node.args
    for a in list(args.args) + list(args.kwonlyargs):
        if a.arg == name and a.annotation is not None:
            return _annotation_class_name(a.annotation)
    return None


def _resolve_method_in_class(graph: RepoGraph, ci, ci_mod,
                             meth: str) -> Optional[FuncInfo]:
    """``meth`` on ClassInfo ``ci`` (base walk within resolvable repo
    classes); None when the class or method is unknown."""
    seen: Set[str] = set()
    while ci is not None and ci.name not in seen:
        if meth in ci.methods:
            return ci.methods[meth]
        seen.add(ci.name)
        nxt, nxt_mod = None, None
        for b in ci.bases:
            bname = b.split(".")[-1]
            cand, cand_mod = graph.attr_types._find_class(ci_mod, bname)
            if cand is not None and cand.name not in seen:
                nxt, nxt_mod = cand, cand_mod
                break
        ci, ci_mod = nxt, nxt_mod
    return None


def resolve_call(graph: RepoGraph, func: FuncInfo, call: CallSite,
                 union: bool = False) -> List[FuncInfo]:
    """Resolve one call site to repo FuncInfos (possibly empty).

    ``union=False`` (default): high-confidence only — ambiguous method
    names resolve to NOTHING. ``union=True``: ambiguous method names
    resolve to EVERY repo method of that name (recall mode, used by
    reachability queries where over-approximation is the safe side).
    """
    text = call.text
    mod = func.module
    parts = text.split(".")

    if len(parts) == 1:
        name = parts[0]
        # nested function in the lexical parent chain
        cur: Optional[FuncInfo] = func
        while cur is not None:
            if name in cur.nested:
                return [graph.funcs[cur.nested[name]]]
            cur = graph.funcs.get(cur.parent_fid) if cur.parent_fid else None
        if name in mod.functions:
            return [mod.functions[name]]
        if name in mod.from_imports:
            src_mod, orig = mod.from_imports[name]
            target = graph.by_dotted.get(src_mod)
            if target and orig in target.functions:
                return [target.functions[orig]]
        # bound-method alias: log = self.server.wal_append; log(...)
        if name in func.aliases:
            alias = CallSite(call.node, call.lineno, func.aliases[name])
            return resolve_call(graph, func, alias, union=union)
        return []

    head, meth = parts[0], parts[-1]
    if head == "self" and len(parts) == 2 and func.cls_name:
        ci = mod.classes.get(func.cls_name)
        seen: Set[str] = set()
        while ci is not None:
            if meth in ci.methods:
                return [ci.methods[meth]]
            seen.add(ci.name)
            nxt = None
            for b in ci.bases:
                bname = b.split(".")[-1]
                if bname in mod.classes and bname not in seen:
                    nxt = mod.classes[bname]
                    break
                # base imported from a repo module
                if bname in mod.from_imports:
                    src_mod, orig = mod.from_imports[bname]
                    tm = graph.by_dotted.get(src_mod)
                    if tm and orig in tm.classes and orig not in seen:
                        nxt = tm.classes[orig]
                        mod = tm  # continue base walk in that module
                        break
            ci = nxt
        mod = func.module  # restore
        # fall through to unique-method resolution

    # typed attribute chain: self.<attr>.<meth>(...) where the class of
    # self.<attr> is known from dataclass/__init__ annotations
    # (dataflow.AttrTypes) — the resolution that connects e.g.
    # ``self.wal.append(...)`` to Wal.append and its fsync
    if head == "self" and len(parts) == 3 and func.cls_name:
        ci, ci_mod = graph.attr_types.attr_class(mod, func.cls_name,
                                                 parts[1])
        hit = _resolve_method_in_class(graph, ci, ci_mod, meth)
        if hit is not None:
            return [hit]

    # annotated-parameter receiver: def f(pool: KvBlockPool): pool.release()
    if len(parts) == 2:
        ann = _param_annotation(func, head)
        if ann is not None:
            ci, ci_mod = graph.attr_types._find_class(mod, ann)
            hit = _resolve_method_in_class(graph, ci, ci_mod, meth)
            if hit is not None:
                return [hit]

    # module-attribute call: alias.f(...) where alias is an import
    if len(parts) == 2 and head in mod.imports:
        dotted = mod.imports[head]
        if dotted.split(".")[0] in _EXTERNAL_MODULES:
            return []
        target = graph.by_dotted.get(dotted)
        if target and meth in target.functions:
            return [target.functions[meth]]
        return []
    if len(parts) == 2 and head in mod.from_imports:
        src_mod, orig = mod.from_imports[head]
        dotted = f"{src_mod}.{orig}" if src_mod else orig
        target = graph.by_dotted.get(dotted)
        if target and meth in target.functions:
            return [target.functions[meth]]
        if dotted.split(".")[0] in _EXTERNAL_MODULES:
            return []

    if head in _EXTERNAL_MODULES:
        return []

    # unique-method fallback over the whole repo
    candidates = graph.method_index.get(meth, [])
    if len(candidates) == 1:
        return [candidates[0]]
    if union and candidates:
        return list(candidates)
    return []


def async_reachable(graph: RepoGraph) -> Dict[str, List[str]]:
    """fid -> example call chain (list of fids, async root first) for every
    SYNC function reachable from an async function without an offload hop.
    Async functions themselves are roots (chain = [root])."""
    chains: Dict[str, List[str]] = {}
    work: List[FuncInfo] = []
    for f in graph.funcs.values():
        if f.is_async:
            chains[f.fid] = [f.fid]
            work.append(f)
    while work:
        cur = work.pop()
        for call in cur.calls:
            # a bare call of an offloaded name from the same function is
            # still on-loop; the offload set only suppresses *references*
            for target in resolve_call(graph, cur, call):
                if target.is_async:
                    continue            # its own root
                if target.fid in chains:
                    continue
                chains[target.fid] = chains[cur.fid] + [target.fid]
                work.append(target)
    return chains
