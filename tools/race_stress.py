"""Randomized churn stress for the pipelined-preemption race: 4 requests
with random budgets/delays contend for 2 slots over a tiny pool, forcing
finish/preempt/re-admission churn against chained dispatches. Every trial's
recorded log runs the stale-read and input-consistency checkers; divergent
or flagged logs are pickled for tools/race_replay.py-style forensics.

Usage: JAX_PLATFORMS=cpu python tools/race_stress.py [n_trials] [out_dir]
"""

import asyncio
import pickle
import sys

sys.path.insert(0, ".")

import numpy as np
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.core import FINISH_SENTINEL, EngineCore, EngineRequest
from dynamo_tpu.engine.replay import Recorder, check_inputs, check_log
from dynamo_tpu.engine.sampling import SlotSampling

TINY = ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                   max_position_embeddings=512)
K = 4
_DONOR = {}


def make_core(blocks, record=True, lanes=0):
    ecfg = EngineConfig(max_model_len=256, kv_block_size=8,
                        num_kv_blocks=blocks, max_num_seqs=2,
                        prefill_buckets=[32, 64, 128],
                        decode_steps_per_dispatch=K,
                        decode_dispatch_pipeline=True,
                        lane_prefill_max_tokens=lanes)
    c = EngineCore(TINY, ecfg, attn_impl="xla", param_dtype=jnp.float32,
                   params=_DONOR.get("params"))
    if not _DONOR:
        _DONOR.update(params=c.params, pf=c._prefill_jit,
                      dk=c._decode_k_jit, mg=c._merge_jit)
    else:   # identical statics/shapes: reuse the compiled programs
        c._prefill_jit, c._decode_k_jit, c._merge_jit = (
            _DONOR["pf"], _DONOR["dk"], _DONOR["mg"])
    if record:
        c.recorder = Recorder()
    return c


async def run_req(core, prompt, rid, max_new, delay=0.0):
    if delay:
        await asyncio.sleep(delay)
    req = EngineRequest(rid=rid, prompt=list(prompt),
                        sampling=SlotSampling(temperature=0.0),
                        max_new_tokens=max_new, eos_ids=frozenset())
    await core.submit(req)
    toks = []
    while True:
        item, _ = await asyncio.wait_for(req.out_queue.get(), 120)
        if item is FINISH_SENTINEL:
            return toks, req
        toks.append(item)


_REF_CACHE = {}


def solo_ref(prompt, max_new):
    key = (tuple(prompt), max_new)
    if key not in _REF_CACHE:
        async def go():
            core = make_core(64, record=False)
            try:
                toks, _req = await run_req(core, prompt, "ref", max_new)
                return toks
            finally:
                await core.stop()
        _REF_CACHE[key] = asyncio.run(go())
    return _REF_CACHE[key]


def trial(seed):
    # odd seeds exercise lane prefill under the same churn
    lanes = 512 if seed % 2 else 0
    rng = np.random.default_rng(seed)
    n_req = 4
    prompts = [rng.integers(1, TINY.vocab_size,
                            size=int(rng.integers(20, 40))).tolist()
               for _ in range(n_req)]
    budgets = [int(rng.integers(20, 50)) for _ in range(n_req)]
    delays = [float(rng.uniform(0, 0.05)) for _ in range(n_req)]
    refs = [solo_ref(p, m) for p, m in zip(prompts, budgets)]

    async def go():
        core = make_core(16, lanes=lanes)
        try:
            outs = await asyncio.gather(*[
                run_req(core, p, f"r{i}", m, d)
                for i, (p, m, d) in enumerate(
                    zip(prompts, budgets, delays))])
        finally:
            await core.stop()
        return core, outs

    core, outs = asyncio.run(go())
    # the exactness contract: bit-exact up to the first recompute boundary
    # (prefill/decode numerics may flip a greedy argmax there — see
    # KNOWN_ISSUES); divergence BEFORE the boundary is a real bug
    bad = []
    for i in range(n_req):
        toks, req = outs[i]
        if toks == refs[i]:
            continue
        boundary = (min(req.numeric_boundaries) if req.numeric_boundaries
                    else len(refs[i]))
        first = next(j for j, (a, b) in enumerate(zip(toks, refs[i]))
                     if a != b)
        if first < boundary:
            bad.append(i)
    stale = check_log(core.recorder.events, block_size=8)
    problems = check_inputs(core.recorder.events)
    return core, bad, stale, problems


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "/tmp"
    n_bad = 0
    for seed in range(n):
        core, bad, stale, problems = trial(seed)
        flag = f"BAD={bad}" if bad else "ok"
        extra = (f" stale={len(stale)}" if stale else "") + \
                (f" input={len(problems)}" if problems else "")
        print(f"seed {seed}: preempt={core.preemptions} {flag}{extra}",
              flush=True)
        if bad or stale or problems:
            n_bad += 1
            path = f"{out_dir}/race_log_{seed}.pkl"
            with open(path, "wb") as f:
                pickle.dump(core.recorder.events, f)
            for s in stale[:8]:
                print("   ", s, flush=True)
            for p in problems[:8]:
                print("   ", p, flush=True)
            print(f"    log -> {path}", flush=True)
    print(f"done: {n_bad}/{n} flagged", flush=True)


if __name__ == "__main__":
    main()
