"""Measured host-peak for a real-scale streaming checkpoint load.

Generates an 8B-CLASS llama checkpoint on disk (llama-3-8B layer
geometry: D=4096, F=14336, 32 q / 8 kv heads, V=128256; layer count
configurable so the bf16 tree fits one chip's HBM), then stream-loads it
onto the live device mesh with load_params_sharded while sampling
/proc/self/status VmRSS from a thread. Reports one JSON line:

    checkpoint_gb, params_gb, rss_before_gb, rss_peak_delta_gb,
    staging_peak_mb (the loader's own accounting), largest_stack_gb,
    load_s

The claim under test (VERDICT r4 item 1): host staging peak is ~one
param-stack shard, NOT the checkpoint — the reference never pays a
full-model host stage because each vLLM rank loads only its own shard
(lib/llm/src/engines/vllm/subprocess.rs:37-41).

Usage:  python tools/measure_streaming_load.py [--layers 8] [--keep]
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _vm_rss_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    return 0


class RssSampler(threading.Thread):
    def __init__(self, interval=0.01):
        super().__init__(daemon=True)
        self.interval = interval
        self.peak = 0
        self._halt = threading.Event()

    def run(self):
        while not self._halt.is_set():
            self.peak = max(self.peak, _vm_rss_bytes())
            time.sleep(self.interval)

    def stop(self):
        self._halt.set()
        self.join()


def write_checkpoint(d: str, L: int) -> int:
    """HF multi-file checkpoint with llama-8B tensor shapes, one file per
    layer (written incrementally — the writer must not be the thing that
    stages the full model either). Returns total bytes on disk."""
    from safetensors.numpy import save_file
    os.makedirs(d, exist_ok=True)
    D, F, H, KV, Dh, V = 4096, 14336, 32, 8, 128, 128256
    rng = np.random.default_rng(0)

    def t(out_dim, in_dim):
        # torch [out, in] orientation; tiny values keep bf16 finite
        a = np.zeros((out_dim, in_dim), np.float32)
        a[0, :8] = rng.standard_normal(8) * 0.01
        return a

    total = 0
    for i in range(L):
        sd = {
            f"model.layers.{i}.input_layernorm.weight": np.ones(D, np.float32),
            f"model.layers.{i}.post_attention_layernorm.weight":
                np.ones(D, np.float32),
            f"model.layers.{i}.self_attn.q_proj.weight": t(H * Dh, D),
            f"model.layers.{i}.self_attn.k_proj.weight": t(KV * Dh, D),
            f"model.layers.{i}.self_attn.v_proj.weight": t(KV * Dh, D),
            f"model.layers.{i}.self_attn.o_proj.weight": t(D, H * Dh),
            f"model.layers.{i}.mlp.gate_proj.weight": t(F, D),
            f"model.layers.{i}.mlp.up_proj.weight": t(F, D),
            f"model.layers.{i}.mlp.down_proj.weight": t(D, F),
        }
        path = os.path.join(d, f"model-layer{i:02d}.safetensors")
        save_file(sd, path)
        total += os.path.getsize(path)
    top = {"model.embed_tokens.weight": t(V, D),
           "model.norm.weight": np.ones(D, np.float32),
           "lm_head.weight": t(V, D)}
    path = os.path.join(d, "model-top.safetensors")
    save_file(top, path)
    total += os.path.getsize(path)
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({
            "model_type": "llama", "vocab_size": V, "hidden_size": D,
            "intermediate_size": F, "num_hidden_layers": L,
            "num_attention_heads": H, "num_key_value_heads": KV,
            "head_dim": Dh, "max_position_embeddings": 8192,
            "rms_norm_eps": 1e-5, "rope_theta": 500000.0,
            "tie_word_embeddings": False, "eos_token_id": 2}, f)
    return total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8,
                    help="llama-8B has 32; 8 keeps the bf16 tree + load "
                         "transients inside one v5e chip's 16 GB HBM")
    ap.add_argument("--dir", default="/tmp/streamload-8bclass")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.weights import load_accounting, load_params_auto
    from dynamo_tpu.parallel.sharding import make_mesh

    cfg_path = os.path.join(args.dir, "config.json")
    have_layers = None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            have_layers = json.load(f).get("num_hidden_layers")
    generated = False
    if have_layers != args.layers:
        if have_layers is not None:
            raise SystemExit(
                f"{args.dir} holds a {have_layers}-layer checkpoint but "
                f"--layers {args.layers} was requested — remove the dir "
                f"or pass the matching --layers")
        if os.path.exists(args.dir) and os.listdir(args.dir):
            raise SystemExit(
                f"{args.dir} exists and is not a checkpoint this tool "
                f"wrote — refusing to reuse (or later delete) it")
        t0 = time.time()
        ckpt_bytes = write_checkpoint(args.dir, args.layers)
        generated = True
        print(f"# wrote {ckpt_bytes/1e9:.2f} GB checkpoint in "
              f"{time.time()-t0:.1f}s", file=sys.stderr)
    ckpt_bytes = sum(
        os.path.getsize(os.path.join(args.dir, f))
        for f in os.listdir(args.dir) if f.endswith(".safetensors"))

    cfg = ModelConfig.from_model_dir(args.dir)
    n = len(jax.devices())
    mesh = make_mesh(dp=1, tp=n)
    rss_before = _vm_rss_bytes()
    sampler = RssSampler()
    sampler.start()
    t0 = time.time()
    with load_accounting() as acct:
        params = load_params_auto(args.dir, cfg, mesh=mesh,
                                  dtype=jnp.bfloat16)
        jax.block_until_ready(list(params.values()))
    load_s = time.time() - t0
    sampler.stop()
    params_bytes = sum(int(v.nbytes) for v in params.values())
    largest_stack = max(int(v.nbytes) for v in params.values())
    out = {
        "checkpoint_gb": round(ckpt_bytes / 1e9, 3),
        "params_gb": round(params_bytes / 1e9, 3),
        "devices": n,
        "rss_before_gb": round(rss_before / 1e9, 3),
        "rss_peak_delta_gb": round((sampler.peak - rss_before) / 1e9, 3),
        "staging_peak_mb": round(acct.peak / 1e6, 1),
        "largest_handoff_gb": round(acct.largest_handoff / 1e9, 3),
        "largest_stack_gb": round(largest_stack / 1e9, 3),
        "load_s": round(load_s, 1),
        "layers": args.layers,
    }
    print(json.dumps(out))
    if not args.keep and generated:
        import shutil
        shutil.rmtree(args.dir, ignore_errors=True)


if __name__ == "__main__":
    main()
