"""Per-component DEVICE timing for the decode step on real hardware.

Times each stage with a chained in-jit `lax.fori_loop` (N-pass slope):
f(N2) - f(N1) wall time with a single value fetch as the barrier cancels
tunnel round-trips and constant dispatch overheads (KNOWN_ISSUES.md).

Components:
  layers      — transformer stack only (embed + _run_layers, no lm head)
  layers+head — plus the logits projection
  full        — plus sampling (the real serving step content)
  attn        — paged attention isolated (the stack with MLP/proj removed
                is not expressible, so this times paged_attention directly
                on pool-shaped inputs)

Usage: python tools/decode_profile.py [batch ...]   (default 16 64 128)
Env: PROF_MODEL (1b|8b — 8b weighs ~8 GB int8, so pass explicit batches
     that keep batch*(seq+256) KV inside the remaining HBM: B<=32 at
     seq 512 with bf16 KV; the 1b default batch list OOMs at 8b),
     PROF_QUANT (int8|none, default int8), PROF_SEQ (kv len, default
     512), PROF_ATTN (auto|pallas|xla), PROF_TABLES (random|contig,
     default random — the historical layout; "contig" gives each slot a
     consecutive block run, the run-tracking allocator's layout, so the
     kernel's wave-coalesced DMA path engages; the header line reports
     the DMA copies/wave either way so the two layouts are comparable).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def slope_time(fn, args, n1=8, n2=40, reps=3):
    """fn(n, *args) -> array; per-iteration seconds via slope (protocol
    home: dynamo_tpu.utils.timing.slope_per_unit)."""
    from dynamo_tpu.utils.timing import slope_per_unit

    np.asarray(fn(n2, *args))            # compile the long variant too

    def once(n):
        t0 = time.monotonic()
        np.asarray(fn(n, *args))
        return time.monotonic() - t0

    return slope_per_unit(once, n1, n2, reps=reps)


def main():
    import jax
    import jax.numpy as jnp
    from functools import partial

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import make_slot_keys, sample_tokens

    batches = [int(a) for a in sys.argv[1:]] or [16, 64, 128]
    quant = os.environ.get("PROF_QUANT", "int8")
    kv_quant = os.environ.get("PROF_KV", "none")   # none|int8 KV pool
    seq = int(os.environ.get("PROF_SEQ", "512"))
    attn_impl = os.environ.get("PROF_ATTN", "auto")
    model = os.environ.get("PROF_MODEL", "1b")
    # long-context sweeps past the geometry's RoPE table: PROF_MAXPOS
    # raises max_position_embeddings (table cost is linear and tiny)
    maxpos = int(os.environ.get("PROF_MAXPOS", "0"))

    # geometry shared with bench.py (ONE home; unknown names raise —
    # no silent 1B fallback under a mislabeled header)
    from dynamo_tpu.engine.config import bench_model_config
    mcfg = bench_model_config(model)
    if maxpos:
        import dataclasses
        mcfg = dataclasses.replace(mcfg, max_position_embeddings=maxpos)
    if seq >= mcfg.max_position_embeddings:
        # positions stay pinned at `seq` throughout the profile chains
        # (the fori body never advances them), so the only alias hazard
        # is the decode position itself falling past the RoPE table
        raise SystemExit(
            f"PROF_SEQ={seq} >= the {model!r} geometry's "
            f"max_position_embeddings={mcfg.max_position_embeddings}; "
            f"the decode position would silently alias past the RoPE "
            f"table (ADVICE r3). Use a geometry that covers the sweep.")
    dev = jax.devices()[0]
    print(f"# {dev.platform}:{dev.device_kind} model={model} quant={quant} "
          f"kv={kv_quant} seq={seq} attn={attn_impl}", file=sys.stderr)

    for batch in batches:
        # int8 pools need 32-token blocks (int8 sublane tile)
        bs = 32 if kv_quant == "int8" else 16
        bps = (seq + 256 + bs - 1) // bs
        ecfg = EngineConfig(max_model_len=seq + 256, kv_block_size=bs,
                            num_kv_blocks=batch * bps + 2,
                            max_num_seqs=batch, prefill_buckets=[128],
                            quantization=quant, kv_quantization=kv_quant)
        core = EngineCore(mcfg, ecfg, attn_impl=attn_impl,
                          param_dtype=jnp.bfloat16)
        statics = core.statics
        rng = np.random.default_rng(0)
        layout = os.environ.get("PROF_TABLES", "random")
        if layout == "contig":
            # the run-tracking allocator's layout: one consecutive run
            # per slot (wraps at the pool end for oversized sweeps)
            t = (np.arange(batch * core.M).reshape(batch, core.M)
                 % (ecfg.num_kv_blocks - 1)) + 1
            tables_np = t.astype(np.int32)
        else:
            tables_np = rng.integers(
                1, ecfg.num_kv_blocks, size=(batch, core.M)).astype(
                    np.int32)
        from dynamo_tpu.engine.attention import dma_copy_counts
        dma = dma_copy_counts(
            tables_np, np.full((batch,), seq + 1, np.int32),
            block_size=bs, pool_blocks=ecfg.num_kv_blocks,
            dual_stream=mcfg.kv_lora_rank == 0)
        print(f"# tables={layout} dma_copies/wave="
              f"{dma['copies_per_wave']:.2f} "
              f"({dma['coalesced_waves']}/{dma['waves']} waves "
              f"coalesced)", file=sys.stderr)
        tables = jnp.asarray(tables_np, jnp.int32)
        positions = jnp.asarray(np.full((batch,), seq, np.int32))
        tokens = jnp.asarray(rng.integers(1, 1000, size=(batch,)), jnp.int32)
        params, kv = core.params, core.kv

        @partial(jax.jit, static_argnums=0)
        def run_layers(n, params, kv, tokens, positions, tables):
            def body(i, carry):
                kv, toks, acc = carry
                logits, kv = llama.decode_forward(
                    params, kv, toks, positions, tables, statics)
                # feed a data-dependent token back so XLA can't hoist
                return (kv,
                        jnp.argmax(logits[:, :1000], -1).astype(jnp.int32),
                        acc + logits[:, 0])
            _kv, toks, acc = jax.lax.fori_loop(
                0, n, body, (kv, tokens, jnp.zeros((tokens.shape[0],))))
            return acc

        # stack WITHOUT the lm head: argmax over the raw hidden state
        @partial(jax.jit, static_argnums=0)
        def run_stack_only(n, params, kv, tokens, positions, tables):
            emb_dim = mcfg.hidden_size

            def body(i, carry):
                kv, toks, acc = carry
                x = llama._embed(params, toks, mcfg)
                x, kv = llama._run_layers(
                    params, kv, x, positions,
                    tables[jnp.arange(toks.shape[0]), positions // bs] * bs
                    + positions % bs,
                    mcfg,
                    _attn_fn(params, kv, positions, tables))
                return (kv,
                        jnp.argmax(x[:, :1000], -1).astype(jnp.int32),
                        acc + x[:, 0])
            _kv, toks, acc = jax.lax.fori_loop(
                0, n, body, (kv, tokens, jnp.zeros((tokens.shape[0],))))
            return acc

        def _attn_fn(params, kv, positions, tables):
            from dynamo_tpu.engine.attention import paged_attention
            scale = mcfg.head_dim ** -0.5
            seq_lens = positions + 1

            def attn(q, _k, _v, k_flat, v_flat, li, sliding):
                nb = k_flat.shape[0] // (mcfg.num_layers * bs)
                return paged_attention(q, k_flat, v_flat,
                                       tables + li * nb, seq_lens,
                                       block_size=bs, scale=scale,
                                       impl=statics.attn_impl,
                                       kv_heads=mcfg.num_kv_heads)
            return attn

        @partial(jax.jit, static_argnums=0)
        def run_full(n, params, kv, tokens, positions, tables):
            keys0 = jnp.asarray(np.zeros((batch,), np.int64))
            temp = jnp.full((batch,), 0.7, jnp.float32)
            topk = jnp.zeros((batch,), jnp.int32)
            topp = jnp.ones((batch,), jnp.float32)

            def body(i, carry):
                kv, toks, acc = carry
                logits, kv = llama.decode_forward(
                    params, kv, toks, positions, tables, statics)
                keys = make_slot_keys(0, keys0, i.astype(jnp.int64))
                toks2, lps = sample_tokens(logits, keys, temp, topk, topp)
                return kv, toks2, acc + lps
            _kv, toks, acc = jax.lax.fori_loop(
                0, n, body, (kv, tokens, jnp.zeros((tokens.shape[0],))))
            return acc

        args = (params, kv, tokens, positions, tables)
        t_stack = slope_time(run_stack_only, args)
        t_layers = slope_time(run_layers, args)
        t_full = slope_time(run_full, args)
        print(f"B={batch:4d}  stack={t_stack*1e3:7.3f}ms  "
              f"+head={t_layers*1e3:7.3f}ms  "
              f"+sample={t_full*1e3:7.3f}ms  "
              f"head={(t_layers-t_stack)*1e3:7.3f}ms  "
              f"sample={(t_full-t_layers)*1e3:7.3f}ms  "
              f"tok/s={batch/t_full:9.1f}")


if __name__ == "__main__":
    main()
