"""Adversarial sweep + deterministic replay for the pipelined-preemption
exactness race (KNOWN_ISSUES). Runs the contended two-slot scenario from
tests/test_preemption.py with a recorder attached, sweeping admission
jitter until a run's streams diverge from the uncontended references, then:

1. re-executes the recorded schedule synchronously (engine.replay.replay)
   and reports whether the corruption reproduces (deterministic logic bug)
   or vanishes (async buffer/donation hazard);
2. runs the pool-slot last-writer simulation (check_log) to catch stale
   KV reads directly from the log;
3. runs input-consistency invariants (check_inputs).

Usage: JAX_PLATFORMS=cpu python tools/race_replay.py [trials] [seed]
"""

import asyncio
import pickle
import sys

sys.path.insert(0, ".")

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.core import FINISH_SENTINEL, EngineCore, EngineRequest
from dynamo_tpu.engine.replay import (Recorder, check_inputs, check_log,
                                      compare_replay, replay)
from dynamo_tpu.engine.sampling import SlotSampling

TINY = ModelConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                   max_position_embeddings=512)
K = 4
MAX_NEW = 40


def make_core(num_kv_blocks, pipeline=True, record=False):
    ecfg = EngineConfig(max_model_len=256, kv_block_size=8,
                        num_kv_blocks=num_kv_blocks, max_num_seqs=2,
                        prefill_buckets=[32, 64, 128],
                        decode_steps_per_dispatch=K,
                        decode_dispatch_pipeline=pipeline)
    core = EngineCore(TINY, ecfg, attn_impl="xla", param_dtype=jnp.float32)
    if record:
        core.recorder = Recorder()
    return core


async def run_req(core, prompt, rid, delay=0.0):
    if delay:
        await asyncio.sleep(delay)
    req = EngineRequest(rid=rid, prompt=list(prompt),
                        sampling=SlotSampling(temperature=0.0),
                        max_new_tokens=MAX_NEW, eos_ids=frozenset())
    await core.submit(req)
    toks = []
    while True:
        item, payload = await asyncio.wait_for(req.out_queue.get(), 60)
        if item is FINISH_SENTINEL:
            return toks
        toks.append(item)


async def references(p1, p2):
    big = make_core(64)
    try:
        ref1 = await run_req(big, p1, "ref1")
        ref2 = await run_req(big, p2, "ref2")
    finally:
        await big.stop()
    return ref1, ref2


async def one_trial(p1, p2, jitter):
    core = make_core(16, record=True)
    try:
        g1, g2 = await asyncio.gather(
            run_req(core, p1, "a"),
            run_req(core, p2, "b", delay=jitter))
    finally:
        await core.stop()
    return core, g1, g2


def main():
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 36
    seed = int(sys.argv[2]) if len(sys.argv) > 2 else 23
    rng = np.random.default_rng(seed)
    p1 = rng.integers(1, TINY.vocab_size, size=30).tolist()
    p2 = rng.integers(1, TINY.vocab_size, size=30).tolist()
    ref1, ref2 = asyncio.run(references(p1, p2))
    print(f"references ready ({len(ref1)}/{len(ref2)} tokens)")

    for t in range(trials):
        jitter = t * 0.001
        core, g1, g2 = asyncio.run(one_trial(p1, p2, jitter))
        bad1 = g1 != ref1
        bad2 = g2 != ref2
        n_pre = core.preemptions
        print(f"trial {t}: jitter={jitter*1e3:.0f}ms preempt={n_pre} "
              f"a={'BAD' if bad1 else 'ok'} b={'BAD' if bad2 else 'ok'}")
        if not (bad1 or bad2):
            continue

        events = core.recorder.events
        with open("/tmp/race_log.pkl", "wb") as f:
            pickle.dump(events, f)
        print(f"--- divergent run captured ({len(events)} events; "
              f"log saved to /tmp/race_log.pkl)")
        if bad1:
            d = next(i for i, (x, y) in enumerate(zip(g1, ref1)) if x != y)
            print(f"  stream a diverges at token {d}: {g1[d]} vs {ref1[d]}")
        if bad2:
            d = next(i for i, (x, y) in enumerate(zip(g2, ref2)) if x != y)
            print(f"  stream b diverges at token {d}: {g2[d]} vs {ref2[d]}")

        print("--- [1] synchronous replay of the recorded schedule")
        rep = replay(core, events)
        diffs = compare_replay(events, rep)
        if diffs:
            print("  REPLAY DIVERGES FROM LIVE (async-overlap hazard):")
            for d in diffs[:10]:
                print("   ", d)
        else:
            print("  replay EXACTLY reproduces the live (corrupt) tokens:")
            print("  -> deterministic logic bug; inspect recorded inputs")

        print("--- [2] pool-slot last-writer simulation (stale reads)")
        stale = check_log(events, block_size=8)
        if stale:
            for s in stale[:12]:
                print("   ", s)
        else:
            print("  no cross-request stale reads found in the log")

        print("--- [3] input-consistency invariants")
        problems = check_inputs(events)
        if problems:
            for p in problems[:12]:
                print("   ", p)
        else:
            print("  all dispatch inputs consistent with reconstructed state")
        return
    print("no divergent trial found")


if __name__ == "__main__":
    main()
