"""Bandwidth-bound models for the host-KV tier and the disagg wire plane.

Why this exists (VERDICT r2, weak 3 & 5): this rig's tunneled chip moves
device→host bytes at ~12 MB/s, so every e2e measurement of the host tier
or the TCP wire plane is link-dominated and says nothing about a real
deployment. This tool replaces "re-run on real hardware" with explicit
bounds: analytic transfer budgets at realistic link speeds, anchored by
(a) device-truth prefill/decode throughput measured on the chip
(PERF.md / BENCH_LOCAL.jsonl) and (b) the wire serialization cost
MEASURED live on this host (the one part of the path the tunnel does not
distort).

Reference claims being bounded: docs/architecture.md:91 (+40% TTFT from
KV reuse) and the NIXL bulk-transfer role (SURVEY §5.8).

Usage: python tools/bandwidth_model.py [--model 1b|8b|70b] [--json]
"""

import argparse
import json
import sys
import time

import numpy as np

# (layers, kv_heads, head_dim, params) — bf16 KV
GEOMETRIES = {
    "1b": (16, 8, 64, 1.24e9),
    "8b": (32, 8, 128, 8.0e9),
    "70b": (80, 8, 128, 70e9),
}

V5E_BF16_PEAK = 197e12
# measured anchor (PERF.md "Prefill"): flash prefill runs at ~56% MFU on
# the chip, so prefill throughput for a geometry is 0.56 * peak / 2P
PREFILL_MFU = 0.56

D2H_GBPS = (10.0, 30.0, 100.0)      # TPU-VM device↔host links
DCN_GBITS = (10.0, 25.0)            # cross-host links (Gb/s)


def kv_bytes_per_token(model: str, itemsize: int = 2) -> int:
    L, kvh, dh, _ = GEOMETRIES[model]
    return 2 * L * kvh * dh * itemsize


def prefill_tok_per_s(model: str) -> float:
    _, _, _, params = GEOMETRIES[model]
    return PREFILL_MFU * V5E_BF16_PEAK / (2.0 * params)


def measure_serialization_ms(model: str, tokens: int,
                             block_size: int = 16) -> float:
    """Time the REAL wire pack (engine/block_copy.to_wire_format) for this
    many tokens of KV on this host — measured, not modeled."""
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    from dynamo_tpu.engine.block_copy import to_wire_format
    L, kvh, dh, _ = GEOMETRIES[model]
    n = max(tokens // block_size, 1)
    slab = np.zeros((L, n, block_size, kvh * dh), np.float16)
    t0 = time.monotonic()
    to_wire_format(slab, kvh)
    return 1e3 * (time.monotonic() - t0) * 2      # k and v


def host_tier_table(model: str) -> list:
    """Restore-vs-recompute: reusing `hit` tokens of host KV pays iff the
    h2d restore beats re-prefilling them. Rows per d2h bandwidth."""
    bpt = kv_bytes_per_token(model)
    pf = prefill_tok_per_s(model)
    rows = []
    for gbps in D2H_GBPS:
        # break-even: restore wins for any hit length when link tok/s
        # exceeds prefill tok/s (both scale linearly; dispatch overhead
        # ~1 ms is shared noise)
        link_tok_s = gbps * 1e9 / bpt
        hit = 2048
        restore_ms = 1e3 * hit * bpt / (gbps * 1e9) + 1.0
        recompute_ms = 1e3 * hit / pf
        rows.append({
            "d2h_gbps": gbps,
            "link_tok_per_s": round(link_tok_s),
            "prefill_tok_per_s": round(pf),
            "restore_ms_2k_hit": round(restore_ms, 2),
            "recompute_ms_2k_hit": round(recompute_ms, 2),
            "tier_pays": bool(link_tok_s > pf),
            "ttft_saving_pct_2k": round(
                100.0 * (recompute_ms - restore_ms)
                / max(recompute_ms, 1e-9), 1),
        })
    return rows


def wire_plane_table(model: str, isl: int = 3072) -> list:
    """Disagg KV handoff across hosts: serialization (measured here) +
    bytes over DCN, compared to the agg baseline prefill."""
    bpt = kv_bytes_per_token(model)
    ser_ms = measure_serialization_ms(model, isl)
    pf_ms = 1e3 * isl / prefill_tok_per_s(model)
    rows = []
    for gbits in DCN_GBITS:
        xfer_ms = 1e3 * isl * bpt / (gbits * 1e9 / 8)
        overhead = ser_ms + xfer_ms
        rows.append({
            "dcn_gbit": gbits,
            "kv_mb": round(isl * bpt / 1e6, 1),
            "serialize_ms_measured": round(ser_ms, 2),
            "transfer_ms": round(xfer_ms, 2),
            "overhead_ms": round(overhead, 2),
            "agg_prefill_ms": round(pf_ms, 2),
            "overhead_vs_agg_pct": round(100.0 * overhead / pf_ms, 1),
        })
    return rows


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=sorted(GEOMETRIES), default="1b")
    p.add_argument("--isl", type=int, default=3072)
    p.add_argument("--json", action="store_true")
    args = p.parse_args()

    host = host_tier_table(args.model)
    wire = wire_plane_table(args.model, args.isl)
    if args.json:
        print(json.dumps({"model": args.model, "isl": args.isl,
                          "host_tier": host, "wire_plane": wire}))
        return
    bpt = kv_bytes_per_token(args.model)
    print(f"# {args.model}: {bpt} KV bytes/token, prefill "
          f"{prefill_tok_per_s(args.model):,.0f} tok/s "
          f"(measured {PREFILL_MFU:.0%} MFU anchor)\n")
    print("## host tier (restore 2048-token hit vs recompute)")
    print("| d2h GB/s | link tok/s | restore ms | recompute ms | pays | "
          "TTFT saving |")
    print("|---|---|---|---|---|---|")
    for r in host:
        print(f"| {r['d2h_gbps']} | {r['link_tok_per_s']:,} | "
              f"{r['restore_ms_2k_hit']} | {r['recompute_ms_2k_hit']} | "
              f"{'yes' if r['tier_pays'] else 'no'} | "
              f"{r['ttft_saving_pct_2k']}% |")
    print(f"\n## wire plane (disagg handoff, ISL={args.isl})")
    print("| DCN Gb/s | KV MB | serialize ms (measured) | transfer ms | "
          "overhead ms | vs agg prefill |")
    print("|---|---|---|---|---|---|")
    for r in wire:
        print(f"| {r['dcn_gbit']} | {r['kv_mb']} | "
              f"{r['serialize_ms_measured']} | {r['transfer_ms']} | "
              f"{r['overhead_ms']} | {r['overhead_vs_agg_pct']}% |")


if __name__ == "__main__":
    main()
