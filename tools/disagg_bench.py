"""Disagg TTFT vs aggregated TTFT, and handoff latency vs ISL.

The reference's headline disagg claim is +30% throughput/GPU at 3K ISL /
150 OSL with KV moved by NIXL RDMA (docs/architecture.md:57). The gate for
our device bulk plane (BASELINE config 3): disagg TTFT must not be worse
than aggregated TTFT for long prompts. This tool measures, per ISL:

  agg_ttft      — prefill + first token on one engine
  disagg_ttft   — decode-side TTFT with remote prefill on a second engine
                  in the same process (device plane: gather → device_put →
                  scatter, no host staging)
  handoff_ms    — the pure KV transfer+scatter cost (disagg TTFT minus the
                  prefill compute both paths share)

Both engines share the one available chip, so this measures the per-hop
software + DMA cost of the plane; on a real split (4+4 chips) prefill and
decode overlap and disagg wins additionally from specialization.

Usage: python tools/disagg_bench.py [isl ...]    (default 512 1024 2048 3072)
Env: DISAGG_MODEL (tiny|1b, default 1b), DISAGG_PLANE (device|wire).
"""

import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def model_cfg(name):
    from dynamo_tpu.engine.config import ModelConfig
    if name == "tiny":
        return ModelConfig(vocab_size=2048, hidden_size=256,
                           intermediate_size=512, num_layers=4, num_heads=8,
                           num_kv_heads=4, head_dim=32,
                           max_position_embeddings=8192)
    return ModelConfig(vocab_size=128256, hidden_size=2048,
                       intermediate_size=8192, num_layers=16,
                       num_heads=32, num_kv_heads=8, head_dim=64,
                       max_position_embeddings=8192,
                       rope_theta=500000.0, tie_word_embeddings=True)


async def run(isls, model, plane):
    import jax.numpy as jnp
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.core import EngineCore, FINISH_SENTINEL, \
        EngineRequest
    from dynamo_tpu.engine.sampling import SlotSampling
    from dynamo_tpu.llm.disagg import (DisaggEngine, DisaggregatedRouter,
                                       PrefillWorker)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    mcfg = model_cfg(model)
    max_isl = max(isls)
    bs = 16
    bps = (max_isl + 64 + bs - 1) // bs
    ecfg = dict(max_model_len=max_isl + 64, kv_block_size=bs,
                num_kv_blocks=2 * bps + 2, max_num_seqs=2,
                prefill_buckets=sorted({*isls, max_isl + 64}),
                enable_prefix_reuse=False)   # each trial must prefill fully

    def core():
        return EngineCore(mcfg, EngineConfig(**ecfg), attn_impl="auto",
                          param_dtype=jnp.bfloat16)

    async def ttft(engine_core, submit):
        """Submit via `submit(prompt, rid)` → seconds to first token."""
        rng = np.random.default_rng(0)

        async def once(isl, rid):
            prompt = rng.integers(1, 1000, size=isl).tolist()
            t0 = time.monotonic()
            req = await submit(prompt, rid)
            dt = None
            while True:
                item, _ = await asyncio.wait_for(req.out_queue.get(), 300)
                if item is FINISH_SENTINEL:
                    break
                if dt is None:
                    dt = time.monotonic() - t0   # FIRST token only
            return dt

        return once

    results = []
    # ---- aggregated reference
    agg = core()

    async def agg_submit(prompt, rid):
        req = EngineRequest(rid=rid, prompt=prompt,
                            sampling=SlotSampling(temperature=0.0),
                            max_new_tokens=2, eos_ids=frozenset())
        await agg.submit(req)
        return req

    once = await ttft(agg, agg_submit)
    agg_ttft = {}
    for isl in isls:
        await once(isl, f"warm-{isl}")          # compile this bucket
        agg_ttft[isl] = min([await once(isl, f"agg-{isl}-{i}")
                             for i in range(3)])
    await agg.stop()

    # ---- disagg pair (same chip: measures the handoff hop itself)
    rt = DistributedRuntime.in_process()
    prefill_core, decode_core = core(), core()
    router = DisaggregatedRouter(rt, "m", max_local_prefill_length=0,
                                 conditional=False)
    engine = DisaggEngine(decode_core, rt, router, prefill_timeout=300.0,
                          device_plane=(plane == "device"))
    worker = await PrefillWorker(prefill_core, rt).start()

    async def dis_submit(prompt, rid):
        from dynamo_tpu.llm.protocols.common import (PreprocessedRequest,
                                                     SamplingOptions,
                                                     StopConditions)
        from dynamo_tpu.runtime import Context
        from dynamo_tpu.runtime.engine import EngineContext
        pre = PreprocessedRequest(
            token_ids=list(prompt),
            stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
            sampling_options=SamplingOptions(greedy=True))
        # generate() drives the full disagg path; recover the EngineRequest
        # via the engine core's slot after submit — instead, reuse the
        # DisaggEngine building blocks directly for a clean TTFT probe
        req = engine.build_request(Context(pre, ctx=EngineContext(rid)))
        hit = engine._estimate_prefix_hit(req)
        payload = await engine._remote_prefill(req, hit)
        if payload is None:
            # a silent local fallback would report aggregated TTFT as
            # disagg TTFT — fail the bench loudly instead
            raise RuntimeError(
                f"remote prefill fell back for {rid} "
                f"(remote_failures={engine.remote_failures}); "
                "bench numbers would be meaningless")
        req.precomputed = payload
        await decode_core.submit(req)
        return req

    once = await ttft(decode_core, dis_submit)
    for isl in isls:
        await once(isl, f"dwarm-{isl}")
        vals = [await once(isl, f"dis-{isl}-{i}") for i in range(3)]
        dis = min(vals)
        results.append({
            "isl": isl,
            "agg_ttft_ms": round(agg_ttft[isl] * 1e3, 1),
            "disagg_ttft_ms": round(dis * 1e3, 1),
            "handoff_overhead_ms": round((dis - agg_ttft[isl]) * 1e3, 1),
            "disagg_not_worse": dis <= agg_ttft[isl] * 1.05,
        })
    await worker.stop()
    await prefill_core.stop()
    await decode_core.stop()
    await rt.shutdown()

    import json
    print(f"# plane={plane} model={model} "
          f"device_transfers={engine.device_transfers}", file=sys.stderr)
    for r in results:
        print(json.dumps(r))


def main():
    isls = [int(a) for a in sys.argv[1:]] or [512, 1024, 2048, 3072]
    model = os.environ.get("DISAGG_MODEL", "1b")
    plane = os.environ.get("DISAGG_PLANE", "device")
    asyncio.run(run(isls, model, plane))


if __name__ == "__main__":
    main()
