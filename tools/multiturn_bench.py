"""Multi-turn TTFT benchmark: the host-KV-tier payoff measurement.

Reference claim being matched: KV cache offload to system memory buys +40%
TTFT on multi-turn workloads (docs/architecture.md:91, 80 users × 10-turn
conversations). Setup here: U users × T turns; each turn's prompt is the
whole conversation so far plus new user tokens. The DEVICE reuse pool is
sized so concurrent conversations evict each other between turns — the
host tier (async onboarding, llm/kv/offload.py) is the only place the
prefix can survive. Compare per-turn TTFT with the host tier on vs off.

Usage: python tools/multiturn_bench.py [users] [turns]
Env: MT_MODEL (tiny|1b, default 1b), MT_TURN_TOKENS (default 128),
     MT_GEN (default 32).

Prints one JSON line per config + a final comparison line.
"""

import asyncio
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def model_cfg(name):
    from dynamo_tpu.engine.config import ModelConfig
    if name == "tiny":
        return ModelConfig(vocab_size=2048, hidden_size=256,
                           intermediate_size=512, num_layers=4, num_heads=8,
                           num_kv_heads=4, head_dim=32,
                           max_position_embeddings=8192)
    return ModelConfig(vocab_size=128256, hidden_size=2048,
                       intermediate_size=8192, num_layers=16,
                       num_heads=32, num_kv_heads=8, head_dim=64,
                       max_position_embeddings=8192,
                       rope_theta=500000.0, tie_word_embeddings=True)


async def run_config(users, turns, turn_tokens, gen, mcfg, host_blocks):
    import jax.numpy as jnp
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.core import (FINISH_SENTINEL, EngineCore,
                                        EngineRequest)
    from dynamo_tpu.engine.sampling import SlotSampling

    bs = 16
    max_len = turns * (turn_tokens + gen) + 64
    bps = (max_len + bs - 1) // bs
    slots = min(users, 8)
    # device pool: room for ~2 full conversations — with `users` rotating,
    # finished conversations get LRU-evicted between turns, so the HOST
    # tier is the only surviving prefix source
    ecfg = EngineConfig(
        max_model_len=max_len, kv_block_size=bs,
        num_kv_blocks=2 * bps + 2, max_num_seqs=slots,
        prefill_buckets=sorted({turn_tokens,
                                *(t * (turn_tokens + gen) + turn_tokens
                                  for t in range(turns)), max_len}),
        decode_steps_per_dispatch=8, decode_dispatch_pipeline=True,
        quantization="int8", host_kv_blocks=host_blocks)
    core = EngineCore(mcfg, ecfg, attn_impl="auto", param_dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    ttfts = {t: [] for t in range(turns)}
    hits = {t: [] for t in range(turns)}

    async def conversation(u):
        history = []
        for t in range(turns):
            history = history + rng.integers(
                1, mcfg.vocab_size - 1, size=turn_tokens).tolist()
            req = EngineRequest(
                rid=f"u{u}t{t}", prompt=list(history),
                sampling=SlotSampling(temperature=0.0),
                max_new_tokens=gen, eos_ids=frozenset())
            t0 = time.monotonic()
            await core.submit(req)
            toks = []
            ttft = None
            while True:
                item, _ = await asyncio.wait_for(req.out_queue.get(), 600)
                if item is FINISH_SENTINEL:
                    break
                if ttft is None:
                    ttft = time.monotonic() - t0
                toks.append(item)
            ttfts[t].append(ttft)
            hits[t].append(req.prefix_hit_tokens)
            history = history + toks
            # think time: lets the engine offload + other users run
            await asyncio.sleep(0.05)

    # warmup: one throwaway conversation compiles every turn bucket so
    # measured TTFTs are steady-state (conversation() writes through the
    # closure cells, so point them at scratch dicts for the warm run)
    real_ttfts, real_hits = ttfts, hits
    ttfts = {t: [] for t in range(turns)}
    hits = {t: [] for t in range(turns)}
    await conversation("warm")
    ttfts, hits = real_ttfts, real_hits

    # stagger users so turns interleave (forces device-tier eviction)
    await asyncio.gather(*(conversation(u) for u in range(users)))
    stats = {
        "host_blocks": host_blocks,
        "onboards": core.host_onboards,
        "offloaded": (core.offload_engine.offloaded_blocks_total
                      if core.offload_engine else 0),
        "ttft_turn0_ms": round(1e3 * float(np.mean(ttfts[0])), 1),
        "ttft_later_ms": round(1e3 * float(np.mean(
            [x for t in range(1, turns) for x in ttfts[t]])), 1),
        "hit_tokens_later": round(float(np.mean(
            [x for t in range(1, turns) for x in hits[t]])), 1),
    }
    await core.stop()
    return stats


def main():
    users = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    turns = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    turn_tokens = int(os.environ.get("MT_TURN_TOKENS", "128"))
    gen = int(os.environ.get("MT_GEN", "32"))
    mcfg = model_cfg(os.environ.get("MT_MODEL", "1b"))

    async def run():
        on = await run_config(users, turns, turn_tokens, gen, mcfg,
                              host_blocks=4096)
        off = await run_config(users, turns, turn_tokens, gen, mcfg,
                               host_blocks=0)
        return on, off

    on, off = asyncio.run(run())
    print(json.dumps({"host_tier": "on", **on}))
    print(json.dumps({"host_tier": "off", **off}))
    # reduction = (off - on)/off — "how much TTFT the tier removes";
    # the previous off/on-1 formula was the inverse ratio (speedup) and
    # overstated the reference-pillar comparison
    gain = (off["ttft_later_ms"] - on["ttft_later_ms"])         / max(off["ttft_later_ms"], 1e-9)
    print(json.dumps({
        "metric": "host_tier_ttft_reduction_multiturn",
        "value": round(gain * 100, 1), "unit": "% TTFT reduction vs no host tier",
        "later_turn_ttft_ms": {"on": on["ttft_later_ms"],
                               "off": off["ttft_later_ms"]},
    }))


if __name__ == "__main__":
    main()
