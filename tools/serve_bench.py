"""End-to-end engine-loop serving benchmark: N requests stream through the
real EngineCore asyncio loop (admissions, continuous batching, harvests),
reporting wall-clock throughput and TTFT/ITL percentiles — RAW and NET of
the measured tunnel round-trip tax.

Why the decomposition (VERDICT r3 weak #5 / next #7): on this rig every
device→host value fetch pays ~131 ms of tunnel RTT, so raw serving
latency is tunnel-dominated and says nothing about the <500 ms p50 TTFT
north star (BASELINE.md config 4). The engine MEASURES the wall time its
synchronous fetches actually stall the loop (EngineCore.host_stall_s —
an async copy that already landed, or a host-value "fetch", measures ~0
by construction, so there is no modeled-RTT over/under-subtraction);
this tool samples that clock at each request's submit / first-token /
finish and subtracts the in-window delta — the latency a local TPU-VM
(where a fetch is microseconds) would see from the same scheduler
decisions. Raw numbers are printed beside it; nothing is hidden.

Usage: python tools/serve_bench.py [n_requests] [max_num_seqs] [lanes]
"""

import asyncio
import statistics
import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, bench_model_config
from dynamo_tpu.engine.core import FINISH_SENTINEL, EngineCore, EngineRequest
from dynamo_tpu.engine.sampling import SlotSampling

PROMPT = 128
GEN = 64


def measure_rtt(reps: int = 15) -> float:
    """Median seconds for one device→host value fetch of a small array —
    the per-round-trip tunnel tax (microseconds on a local TPU-VM)."""
    x = jnp.arange(64, dtype=jnp.int32)
    times = []
    for i in range(reps + 2):
        y = x + i                      # fresh value: no fetch caching
        t0 = time.monotonic()
        np.asarray(y)
        times.append(time.monotonic() - t0)
    return statistics.median(times[2:])   # first reps warm compile/queue


def pct(xs, p):
    xs = sorted(xs)
    return xs[min(int(len(xs) * p), len(xs) - 1)]


def main():
    n_req = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    lanes = int(sys.argv[3]) if len(sys.argv) > 3 else 0

    mcfg = bench_model_config("1b")
    max_len = PROMPT + GEN + 64
    ecfg = EngineConfig(
        max_model_len=max_len, kv_block_size=16,
        num_kv_blocks=slots * ((max_len + 15) // 16) + 2,
        max_num_seqs=slots, prefill_buckets=[PROMPT, max_len],
        decode_steps_per_dispatch=16, decode_dispatch_pipeline=True,
        lane_prefill_max_tokens=lanes, quantization="int8")
    core = EngineCore(mcfg, ecfg, attn_impl="auto",
                      param_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 32000, PROMPT).tolist() for _ in range(n_req)]

    gens = [int(g) for g in rng.integers(GEN // 2, GEN * 2, n_req)]
    gaps = rng.exponential(0.15, n_req)     # paced arrivals (open loop-ish)

    rtt = measure_rtt()
    platform = jax.devices()[0].platform

    async def one(i, delay=0.0):
        if delay:
            await asyncio.sleep(delay)
        req = EngineRequest(rid=f"r{i}", prompt=prompts[i],
                            sampling=SlotSampling(temperature=0.7, seed=i),
                            max_new_tokens=gens[i], eos_ids=frozenset())
        stall0 = core.host_stall_s
        t0 = time.monotonic()
        await core.submit(req)
        n = 0
        ttft = ttft_host = None
        stall_first = stall0
        while True:
            item, _ = await req.out_queue.get()  # dynalint: ok DL007 in-process bench harness owns both ends; a timeout would skew measured ITL
            if item is FINISH_SENTINEL:
                dt = time.monotonic() - t0
                gen_stall = core.host_stall_s - stall_first
                itl = ((dt - ttft) / max(n - 1, 1)) if ttft else None
                itl_host = (max(dt - ttft - gen_stall, 0.0)
                            / max(n - 1, 1)) if ttft else None
                return n, ttft, ttft_host, itl, itl_host
            if ttft is None:
                ttft = time.monotonic() - t0
                stall_first = core.host_stall_s
                # every measured fetch stall in the window blocked the
                # single-threaded loop, delaying this first token
                ttft_host = max(ttft - (stall_first - stall0), 0.0)
            n += 1

    async def run():
        # warm the compiles with one request end-to-end
        _ = await one(0)
        rt_base, stall_base = core.host_roundtrips, core.host_stall_s
        t0 = time.monotonic()
        arrivals = np.cumsum(gaps)
        outs = await asyncio.gather(
            *[one(i, delay=float(arrivals[i])) for i in range(n_req)])
        dt = time.monotonic() - t0
        await core.stop()
        total = sum(n for n, *_ in outs)
        ttfts = [t for _, t, *_ in outs if t is not None]
        ttfts_host = [t for _, _, t, *_ in outs if t is not None]
        itls = [x for *_, x, _ in outs if x is not None]
        itls_host = [x for *_, x in outs if x is not None]
        print(f"lanes={lanes}: {n_req} reqs x ({PROMPT}p+{GEN}g), "
              f"slots={slots}: {total} tokens in {dt:.1f}s = "
              f"{total / dt:.0f} tok/s | rtt={rtt * 1e3:.0f}ms "
              f"({platform})\n"
              f"  raw : TTFT p50 {pct(ttfts, .5):.2f}s "
              f"p95 {pct(ttfts, .95):.2f}s | "
              f"ITL p50 {pct(itls, .5) * 1e3:.0f}ms\n"
              f"  host: TTFT p50 {pct(ttfts_host, .5) * 1e3:.0f}ms "
              f"p95 {pct(ttfts_host, .95) * 1e3:.0f}ms | "
              f"ITL p50 {pct(itls_host, .5) * 1e3:.0f}ms "
              f"(net of {core.host_stall_s - stall_base:.1f}s measured "
              f"stall over {core.host_roundtrips - rt_base} fetches)\n"
              f"  lane_admissions={core.lane_admissions} "
              f"prefill_tok={core.total_prefill_tokens}")
        if platform != "cpu":
            # record the defensible <500ms-p50-TTFT proxy (BENCH_LOCAL)
            import bench
            await asyncio.to_thread(bench._record_success, {
                "metric": "serving_ttft_p50_host_ms",
                "value": round(pct(ttfts_host, .5) * 1e3, 1),
                "unit": "ms",
                "vs_baseline": round(
                    500.0 / max(pct(ttfts_host, .5) * 1e3, 1e-6), 3),
                "extra": {
                    "platform": platform,
                    "ttft_p95_host_ms": round(pct(ttfts_host, .95) * 1e3, 1),
                    "ttft_p50_raw_s": round(pct(ttfts, .5), 3),
                    "itl_p50_host_ms": round(pct(itls_host, .5) * 1e3, 1),
                    "rtt_ms": round(rtt * 1e3, 1),
                    "host_roundtrips": core.host_roundtrips - rt_base,
                    "host_stall_s": round(
                        core.host_stall_s - stall_base, 2),
                    "n_requests": n_req, "slots": slots, "lanes": lanes,
                    "tok_per_s_wall": round(total / dt, 1),
                },
            })

    asyncio.run(run())


if __name__ == "__main__":
    main()
