"""End-to-end engine-loop serving benchmark: N requests stream through the
real EngineCore asyncio loop (admissions, continuous batching, harvests),
reporting wall-clock throughput and TTFT percentiles. Complements bench.py
(which measures the bare dispatch loop): this is where admission policy —
prefill-program vs lane prefill (--lanes) — shows up.

Usage: python tools/serve_bench.py [n_requests] [max_num_seqs] [lanes]
"""

import asyncio
import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.core import FINISH_SENTINEL, EngineCore, EngineRequest
from dynamo_tpu.engine.sampling import SlotSampling

PROMPT = 128
GEN = 64


def main():
    n_req = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    lanes = int(sys.argv[3]) if len(sys.argv) > 3 else 0

    mcfg = ModelConfig(vocab_size=128256, hidden_size=2048,
                       intermediate_size=8192, num_layers=16,
                       num_heads=32, num_kv_heads=8, head_dim=64,
                       max_position_embeddings=4096,
                       rope_theta=500000.0, tie_word_embeddings=True)
    max_len = PROMPT + GEN + 64
    ecfg = EngineConfig(
        max_model_len=max_len, kv_block_size=16,
        num_kv_blocks=slots * ((max_len + 15) // 16) + 2,
        max_num_seqs=slots, prefill_buckets=[PROMPT, max_len],
        decode_steps_per_dispatch=16, decode_dispatch_pipeline=True,
        lane_prefill_max_tokens=lanes, quantization="int8")
    core = EngineCore(mcfg, ecfg, attn_impl="auto",
                      param_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 32000, PROMPT).tolist() for _ in range(n_req)]

    gens = [int(g) for g in rng.integers(GEN // 2, GEN * 2, n_req)]
    gaps = rng.exponential(0.15, n_req)     # paced arrivals (open loop-ish)

    async def one(i, delay=0.0):
        if delay:
            await asyncio.sleep(delay)
        req = EngineRequest(rid=f"r{i}", prompt=prompts[i],
                            sampling=SlotSampling(temperature=0.7, seed=i),
                            max_new_tokens=gens[i], eos_ids=frozenset())
        await core.submit(req)
        n = 0
        ttft = None
        t0 = time.monotonic()
        while True:
            item, _ = await req.out_queue.get()
            if item is FINISH_SENTINEL:
                return n, ttft
            if ttft is None:
                ttft = time.monotonic() - t0
            n += 1

    async def run():
        # warm the compiles with one request end-to-end
        _ = await one(0)
        t0 = time.monotonic()
        arrivals = np.cumsum(gaps)
        outs = await asyncio.gather(
            *[one(i, delay=float(arrivals[i])) for i in range(n_req)])
        dt = time.monotonic() - t0
        await core.stop()
        total = sum(n for n, _ in outs)
        ttfts = sorted(t for _, t in outs if t is not None)
        p50 = ttfts[len(ttfts) // 2]
        p95 = ttfts[int(len(ttfts) * 0.95)]
        print(f"lanes={lanes}: {n_req} reqs x ({PROMPT}p+{GEN}g), "
              f"slots={slots}: {total} tokens in {dt:.1f}s = "
              f"{total / dt:.0f} tok/s | TTFT p50 {p50:.2f}s p95 {p95:.2f}s "
              f"| lane_admissions={core.lane_admissions} "
              f"prefill_tok={core.total_prefill_tokens}")

    asyncio.run(run())


if __name__ == "__main__":
    main()
