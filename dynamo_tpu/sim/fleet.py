"""SimFleet: the real control plane over a simulated fleet.

What is REAL here (imported production code, not reimplementation):

- :class:`~dynamo_tpu.components.planner.Planner` — the standing SLO
  loop with hysteresis/cooldown, graceful drain, disagg retune — started
  exactly as in production against a real ``MemoryKvStore`` + real
  ``Client`` (sim workers write real discovery/stats/drain records);
- :class:`~dynamo_tpu.llm.kv_router.indexer.KvIndexer` — the radix
  prefix index, fed tier-tagged RouterEvents by the sim workers;
- :class:`~dynamo_tpu.llm.kv_router.scheduler.KvScheduler` — the cost
  model picking a worker per request (NetKV network-adjusted overlap,
  draining exclusion, optimistic accounting);
- :class:`~dynamo_tpu.llm.disagg.DisaggregatedRouter` — the local-vs-
  remote prefill decision, live-rewatched as the planner retunes it;
- :class:`~dynamo_tpu.llm.kv.fabric.AdmissionGate` /
  ``PeerLinkTable`` / ``PrefillRateEstimator`` — fetch-vs-recompute
  pricing per worker over measured-shaped links.

What is SIMULATED: request service times (sim/worker.py over the
measured device models), the network links' parameters, and the traffic
(sim/workload.py). Stats flow to the planner shaped exactly like
``ForwardPassMetrics`` — because they are built with that dataclass.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import struct
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import xxhash

from ..components.planner import Planner, PlannerActuator, PlannerConfig
from ..llm.disagg import DisaggregatedRouter, disagg_config_key
from ..llm.kv.blocks import HASH_SEED, chain_hash
from ..llm.kv.fabric import PeerLinkTable
from ..llm.kv_router.indexer import KvIndexer
from ..llm.kv_router.scheduler import KvScheduler
from ..llm.kv_router.scoring import Endpoint as ScoringEndpoint
from ..llm.kv_router.scoring import ProcessedEndpoints
from ..llm.slo import ServiceLevelObjective, percentile
from ..runtime.bus import MemoryBus
from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.kvstore import MemoryKvStore, WatchEventType
from .models import WorkerPerfModel
from .report import EventLog
from .worker import SimRequest, SimWorker
from .workload import RequestSpec, Workload

__all__ = ["FleetConfig", "SimFleet", "SimActuator"]


@dataclasses.dataclass
class FleetConfig:
    namespace: str = "sim"
    replicas: int = 8
    prefill_replicas: int = 0
    slots: int = 4
    kv_blocks: int = 512
    host_blocks: int = 256
    block_size: int = 32
    tenant_prefix_blocks: int = 4      # per-tenant shared system prefix
    model_name: str = "sim-model"
    perf: Optional[WorkerPerfModel] = None
    link_gbps: float = 8.0
    link_rtt_s: float = 2e-3
    link_jitter: float = 0.25          # ± fraction, per-worker (seeded)
    admission: str = "auto"
    provision_delay_s: float = 20.0
    stats_interval_s: float = 5.0
    scrape_interval_s: float = 2.0
    retry_backoff_s: float = 0.5
    max_retries: int = 3
    drainout_s: float = 300.0
    planner_enabled: bool = True
    slo: Optional[ServiceLevelObjective] = None
    planner_cfg: Optional[PlannerConfig] = None
    new_worker_profile: str = "slow-start:20"
    initial_profiles: Tuple[str, ...] = ()   # cycled over initial workers
    # multi-tenant serving plane (llm/tenancy.py): {tenant: {weight,
    # kv_quota_blocks, qos}} policies. Non-None turns on fair-share
    # waiting queues (WDRR + QoS) and per-worker quota-preferred
    # eviction — the REAL policy classes under the determinism gate.
    tenant_policies: Optional[Dict[str, dict]] = None
    # streaming layer-wise KV handoff (llm/kv/stream.py): > 0 prices the
    # disagg P→D handoff at the EXPOSED overlapped transfer for that
    # pipeline depth (AdmissionGate.modeled_fetch_overlap_s) instead of
    # the serial cost — the sim's lever for predicting what streaming
    # buys a fleet before turning it on. 0 = monolithic (unchanged).
    stream_layers: int = 0


class SimLatencyCollector:
    """Collector-shaped latency source (the planner consumes it through
    llm/slo.latency_percentiles exactly like the fleet trace
    collector): sliding window of completed-request TTFT/ITL."""

    def __init__(self, clock, window_s: float = 180.0):
        self.clock = clock
        self.window_s = window_s
        self._ttft: deque = deque()
        self._itl: deque = deque()

    def record(self, ttft_ms: float, itl_ms: Optional[float]) -> None:
        now = self.clock.now
        self._ttft.append((now, ttft_ms))
        if itl_ms is not None:
            self._itl.append((now, itl_ms))

    def _prune(self) -> None:
        cut = self.clock.now - self.window_s
        for dq in (self._ttft, self._itl):
            while dq and dq[0][0] < cut:
                dq.popleft()

    def latency_percentiles(self, p: float = 90.0) -> dict:
        self._prune()
        return {"ttft_p_ms": percentile([v for _, v in self._ttft], p),
                "itl_p_ms": percentile([v for _, v in self._itl], p),
                "n_traces": float(len(self._ttft))}


class HashCatalog:
    """Deterministic per-session block-hash chains without materializing
    token ids: block i's local hash is xxh3 over (seed, scope, i) and
    the sequence hashes chain through the REAL chain_hash — the first
    ``tenant_prefix_blocks`` blocks are scoped to the TENANT (the shared
    system prompt every session of that tenant reuses)."""

    def __init__(self, seed: int, block_size: int, tenant_prefix_blocks: int):
        self.seed = seed
        self.block_size = block_size
        self.tenant_prefix_blocks = tenant_prefix_blocks
        self._chains: Dict[str, List[int]] = {}

    def chain(self, tenant: str, session: str, n_blocks: int) -> List[int]:
        chain = self._chains.get(session)
        if chain is None:
            chain = self._chains[session] = []
        while len(chain) < n_blocks:
            i = len(chain)
            scope = tenant if i < self.tenant_prefix_blocks else session
            local = xxhash.xxh3_64_intdigest(
                struct.pack("<q", self.seed) + scope.encode()
                + struct.pack("<q", i), seed=HASH_SEED)
            parent = chain[-1] if chain else None
            chain.append(chain_hash(parent, local))
        return chain[:n_blocks]


class SimActuator(PlannerActuator):
    """The planner's substrate: scale-up provisions new sim workers after
    the configured provision delay (with the scenario's new-worker
    profile — typically slow-start); retire force-exits a worker the
    planner gave up draining (the drain-timeout path; a cleanly drained
    worker already exited on its own)."""

    def __init__(self, fleet: "SimFleet"):
        self.fleet = fleet

    async def scale_up(self, role: str, count: int) -> None:
        self.fleet.log.log("planner_scale_up", role=role, count=count)
        for _ in range(count):
            self.fleet.schedule_spawn(self.fleet.cfg.new_worker_profile,
                                      prefill=(role == "prefill"))

    async def retire(self, role: str, worker_id: int) -> None:
        self.fleet.log.log("planner_retire", role=role, worker=worker_id)
        w = (self.fleet.workers.get(worker_id)
             or self.fleet.prefill_workers.get(worker_id))
        if w is not None and not w.dead:
            w.exit(clean=False)


class SimPrefillQueue:
    """Planner-visible prefill backlog (the ``prefill_queue.depth()``
    signal driving the disagg retune)."""

    def __init__(self):
        self.items: deque = deque()
        self.inflight = 0

    async def depth(self) -> int:
        return len(self.items) + self.inflight


class SimFleet:
    def __init__(self, cfg: FleetConfig, seed: int = 0):
        self.cfg = cfg
        self.seed = seed
        self.rng = random.Random(seed ^ 0x51AFEED)
        self.perf = cfg.perf or WorkerPerfModel.from_bench()
        self.clock = None              # bound at start() from the loop
        self.log: Optional[EventLog] = None
        self.runtime: Optional[DistributedRuntime] = None
        self.endpoint: Optional[Endpoint] = None
        self.prefill_endpoint: Optional[Endpoint] = None
        self.workers: Dict[int, SimWorker] = {}
        self.prefill_workers: Dict[int, SimWorker] = {}
        self.draining: set = set()
        self.links = PeerLinkTable(default_gbps=cfg.link_gbps,
                                   default_rtt_s=cfg.link_rtt_s)
        self.indexer = KvIndexer(cfg.block_size, prefer_native=False)
        self.scheduler = KvScheduler(cfg.block_size,
                                     rng=random.Random(seed ^ 0x5C3D))
        # instance-local tenant table (NOT the process-global one: two
        # fleets in one test must not share policy state)
        self.tenant_table = None
        if cfg.tenant_policies is not None:
            from ..llm.tenancy import TenantPolicy, TenantTable
            self.tenant_table = TenantTable(
                {t: TenantPolicy(**p)
                 for t, p in cfg.tenant_policies.items()})
        self.catalog = HashCatalog(seed, cfg.block_size,
                                   cfg.tenant_prefix_blocks)
        self.prefill_queue = SimPrefillQueue()
        self.collector = None
        self.planner: Optional[Planner] = None
        self.disagg_router: Optional[DisaggregatedRouter] = None
        self._next_wid = 0x51A0001
        self._tasks: List[asyncio.Task] = []
        self._watchers: list = []
        self._spawned: List[asyncio.Task] = []
        self._t0 = 0.0
        self._specs: List[RequestSpec] = []
        self._next_spec = 0
        self.counters: Dict[str, int] = {
            "arrived": 0, "completed": 0, "dropped": 0, "lost": 0,
            "retried": 0, "no_capacity": 0, "remote_prefills": 0,
            "fabric_fetch_blocks": 0, "hit_blocks": 0, "isl_blocks": 0,
            "crashes": 0, "clean_exits": 0, "forced_exits": 0,
            "spawned": 0, "shed_writes": 0, "tenant_evictions": 0,
        }
        self.ttft_ms: List[float] = []
        self.itl_ms: List[float] = []
        self.kv_events = 0
        self.replica_peak = 0
        self.prefill_peak = 0

    # ------------------------------------------------------------ wiring
    def spawn(self, coro) -> asyncio.Task:
        t = asyncio.get_running_loop().create_task(coro)
        self._spawned.append(t)
        return t

    def log_event(self, kind: str, **fields) -> None:
        self.log.log(kind, **fields)

    async def start(self) -> "SimFleet":
        loop = asyncio.get_running_loop()
        self.clock = loop.clock      # VirtualTimeLoop
        self.log = EventLog(self.clock)
        self.collector = SimLatencyCollector(self.clock)
        store = MemoryKvStore(now=self.clock.monotonic)
        self.runtime = DistributedRuntime(store, MemoryBus())
        ns = self.cfg.namespace
        self.endpoint = Endpoint(self.runtime, ns, "worker", "generate")
        self.prefill_endpoint = Endpoint(self.runtime, ns, "prefill",
                                         "generate")
        for i in range(self.cfg.replicas):
            prof = ""
            if self.cfg.initial_profiles:
                prof = self.cfg.initial_profiles[
                    i % len(self.cfg.initial_profiles)]
            await self._spawn_worker(profile=prof)
        for _ in range(self.cfg.prefill_replicas):
            await self._spawn_worker(prefill=True)
        # the REAL disagg router, watching the REAL retune key
        self.disagg_router = DisaggregatedRouter(
            self.runtime, self.cfg.model_name,
            max_local_prefill_length=(
                self.cfg.slo.max_local_prefill_length
                if self.cfg.slo else 512))
        await self.disagg_router.start()
        # drain watch: ONE fleet-level watcher per tier dispatching to
        # workers (the worker-side half of the planner's drain protocol;
        # the prefill tier drains through its own endpoint's keys)
        w = await store.watch_prefix(self.endpoint.drain_prefix())
        self._watchers.append(w)
        self._tasks.append(loop.create_task(
            self._drain_watch(w, self.workers), name="sim-drain-watch"))
        wp = await store.watch_prefix(self.prefill_endpoint.drain_prefix())
        self._watchers.append(wp)
        self._tasks.append(loop.create_task(
            self._drain_watch(wp, self.prefill_workers),
            name="sim-prefill-drain-watch"))
        # retune observability: log threshold changes into the event log
        w2 = await store.watch_prefix(disagg_config_key(self.cfg.model_name))
        self._watchers.append(w2)
        self._tasks.append(loop.create_task(self._retune_watch(w2),
                                            name="sim-retune-watch"))
        self._tasks.append(loop.create_task(self._stats_loop(),
                                            name="sim-stats"))
        self._tasks.append(loop.create_task(self._scrape_loop(),
                                            name="sim-scrape"))
        self._scrape_once()
        if self.cfg.planner_enabled:
            self.planner = Planner(
                self.runtime, self.endpoint, SimActuator(self),
                slo=self.cfg.slo, config=self.cfg.planner_cfg,
                prefill_queue=(self.prefill_queue
                               if self.cfg.prefill_replicas > 0 else None),
                prefill_endpoint=(self.prefill_endpoint
                                  if self.cfg.prefill_replicas > 0
                                  else None),
                model_name=(self.cfg.model_name
                            if self.cfg.prefill_replicas > 0 else None),
                traces=lambda: [], collector=self.collector)
            await self.planner.start()
        return self

    async def stop(self) -> None:
        if self.planner is not None:
            await self.planner.stop()
        if self.disagg_router is not None:
            await self.disagg_router.stop()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for w in self._watchers:
            w.close()
        for w in list(self.workers.values()) + list(
                self.prefill_workers.values()):
            w._cancel_timers()
        if self._spawned:
            await asyncio.gather(*self._spawned, return_exceptions=True)
        await self.runtime.shutdown()

    # ----------------------------------------------------------- workers
    def _jitter(self, base: float) -> float:
        j = self.cfg.link_jitter
        return base * (1.0 + self.rng.uniform(-j, j))

    async def _spawn_worker(self, profile: str = "",
                            prefill: bool = False) -> SimWorker:
        wid = self._next_wid
        self._next_wid += 1
        w = SimWorker(self, wid, perf=self.perf, profile=profile,
                      slots=self.cfg.slots, kv_blocks=self.cfg.kv_blocks,
                      host_blocks=self.cfg.host_blocks,
                      block_size=self.cfg.block_size, prefill_only=prefill)
        # seed the measured link table (jittered per worker, then
        # decay-averaged exactly like live probes would refine it)
        self.links.observe_rtt(wid, self._jitter(self.cfg.link_rtt_s))
        self.links.observe_transfer(
            wid, int(self._jitter(self.cfg.link_gbps) * 1e9), 1.0)
        await w.register()
        (self.prefill_workers if prefill else self.workers)[wid] = w
        self.counters["spawned"] += 1
        self.replica_peak = max(self.replica_peak, self.live_decode_count())
        self.prefill_peak = max(self.prefill_peak,
                                self.live_prefill_count())
        self.log.log("worker_up", worker=wid, prefill=prefill,
                     profile=w.profile.name)
        if prefill:
            self._pump_prefill_queue()
        return w

    def schedule_spawn(self, profile: str = "",
                       prefill: bool = False) -> None:
        asyncio.get_running_loop().call_later(
            self.cfg.provision_delay_s,
            lambda: self.spawn(self._spawn_worker(profile=profile,
                                                  prefill=prefill)))

    def live_decode_count(self) -> int:
        return sum(1 for w in self.workers.values() if not w.dead)

    def live_prefill_count(self) -> int:
        return sum(1 for w in self.prefill_workers.values() if not w.dead)

    def on_worker_exit(self, w: SimWorker, clean: bool) -> None:
        self.draining.discard(w.worker_id)
        self.counters["clean_exits" if clean else "forced_exits"] += 1
        self.log.log("worker_exit", worker=w.worker_id, clean=clean)
        self.indexer.remove_worker(w.worker_id)
        self.links.drop(w.worker_id)
        ep = w.endpoint
        store = self.runtime.store
        self.spawn(store.kv_delete(ep.discovery_key(w.worker_id)))
        self.spawn(store.kv_delete(ep.stats_key(w.worker_id)))
        self._scrape_once()

    def on_worker_crash(self, w: SimWorker) -> None:
        self.draining.discard(w.worker_id)
        self.counters["crashes"] += 1
        self.log.log("worker_crash", worker=w.worker_id)
        self.indexer.remove_worker(w.worker_id)
        self.links.drop(w.worker_id)
        ep = w.endpoint
        store = self.runtime.store
        self.spawn(store.kv_delete(ep.discovery_key(w.worker_id)))
        self.spawn(store.kv_delete(ep.stats_key(w.worker_id)))
        self._scrape_once()

    def on_drain_begin(self, w: SimWorker) -> None:
        self.draining.add(w.worker_id)
        self.log.log("drain_begin", worker=w.worker_id)

    def on_shed_writes(self, w: SimWorker, n: int) -> None:
        """Disk-pressure fault: a demote the colder tier refused — the
        write-behind sheds and serving continues (disk_pressure
        scenario's asserted behavior)."""
        self.counters["shed_writes"] += n

    async def _drain_watch(self, watcher, pool: Dict[int, SimWorker]
                           ) -> None:
        from ..runtime.tracing import detach_trace
        detach_trace()
        async for ev in watcher:
            if ev.type != WatchEventType.PUT:
                continue
            try:
                wid = int(ev.entry.key.rsplit(":", 1)[-1], 16)
            except ValueError:
                continue
            w = pool.get(wid)
            if w is not None:
                w.begin_drain()

    async def _retune_watch(self, watcher) -> None:
        import json as _json
        async for ev in watcher:
            if ev.type != WatchEventType.PUT:
                continue
            try:
                d = _json.loads(ev.entry.value)
            except ValueError:
                continue
            self.log.log("retune",
                         threshold=d.get("max_local_prefill_length"))

    # ------------------------------------------------------- stats plane
    async def _stats_loop(self) -> None:
        from ..runtime.tracing import detach_trace
        detach_trace()
        store = self.runtime.store
        while True:
            for w in list(self.workers.values()) + list(
                    self.prefill_workers.values()):
                if not w.dead and not w.partitioned:
                    # a partitioned worker's stats plane is dark: its
                    # last-published record goes stale — the planner's
                    # view of the brownout (sim/scenarios.py
                    # partition_brownout)
                    await store.kv_put(
                        w.endpoint.stats_key(w.worker_id), w.stats_json())
            await asyncio.sleep(self.cfg.stats_interval_s)

    def _scrape_once(self, sample: bool = False) -> None:
        eps = [ScoringEndpoint(w.worker_id, w.scraped_metrics())
               for w in self.workers.values() if not w.dead]
        self.scheduler.update_endpoints(ProcessedEndpoints(eps))
        if sample and eps:
            n = len(eps)
            self.log.log(
                "load_sample", n=n,
                queue_depth=round(sum(e.metrics.num_requests_waiting
                                      for e in eps) / n, 3),
                slot_util=round(sum(e.metrics.request_active_slots
                                    for e in eps)
                                / max(sum(e.metrics.request_total_slots
                                          for e in eps), 1), 4))

    async def _scrape_loop(self) -> None:
        while True:
            self._scrape_once(sample=True)
            await asyncio.sleep(self.cfg.scrape_interval_s)

    # ------------------------------------------------------- request flow
    def apply_kv_event(self, ev) -> None:
        self.kv_events += 1
        self.indexer.apply_event(ev)

    def _start_frontend(self, workload: Workload) -> None:
        self._specs = list(workload)
        self._next_spec = 0
        self._dispatch_due()

    def _dispatch_due(self) -> None:
        loop = asyncio.get_running_loop()
        now = loop.time()
        while (self._next_spec < len(self._specs)
               and self._t0 + self._specs[self._next_spec].at <= now + 1e-9):
            spec = self._specs[self._next_spec]
            self._next_spec += 1
            self.counters["arrived"] += 1
            self.log.log("arrive", rid=spec.rid, tenant=spec.tenant,
                         isl=spec.isl, osl=spec.osl, turn=spec.turn)
            self._admit(spec)
        if self._next_spec < len(self._specs):
            loop.call_at(self._t0 + self._specs[self._next_spec].at,
                         self._dispatch_due)

    def _route(self, spec: RequestSpec):
        """One pass of the REAL router: radix overlap + KvScheduler."""
        isl_blocks = max(spec.isl // self.cfg.block_size, 1)
        hashes = self.catalog.chain(spec.tenant, spec.session, isl_blocks)
        overlap = self.indexer.find_matches(hashes)
        exclude = set(self.draining)
        wid = self.scheduler.schedule(spec.isl, overlap, exclude=exclude,
                                      tenant=spec.tenant)
        if wid is not None and wid in self.workers \
                and not self.workers[wid].dead:
            return wid, hashes, overlap
        # Every worker slot-full (or only draining workers left): fall
        # back to least-backlogged so pressure lands in worker queues —
        # the num_requests_waiting signal the planner scales on — and a
        # full fleet NEVER drops a request.
        #
        # With tenancy on, the fallback keeps CACHE AFFINITY instead:
        # the per-tenant WDRR waiting queues guarantee a victim tenant's
        # request is popped at its fair share no matter how deep the
        # flooding tenant's backlog on that worker is — so routing into
        # a backlogged affinity worker is safe, and a flood can no
        # longer strip everyone else's hit rate by saturating the fleet
        # (backlog-blind affinity is exactly what fair-share queues buy).
        if self.tenant_table is not None:
            best = [(-overlap.weighted.get(wid_, 0.0),
                     len(w.waiting) + w.active_slots, wid_)
                    for wid_, w in self.workers.items()
                    if not w.dead and wid_ not in exclude]
            if best:
                best.sort()
                return best[0][2], hashes, overlap
        live = [(len(w.waiting) + w.active_slots, wid_)
                for wid_, w in self.workers.items()
                if not w.dead and wid_ not in exclude]
        if not live:
            live = [(len(w.waiting) + w.active_slots, wid_)
                    for wid_, w in self.workers.items() if not w.dead]
        if not live:
            return None, hashes, overlap
        live.sort()
        return live[0][1], hashes, overlap

    def _admit(self, spec: RequestSpec, retries: int = 0) -> None:
        wid, hashes, overlap = self._route(spec)
        if wid is None:
            # no live decode workers at all — the planner's
            # "no_workers" verdict is already scaling; retry shortly
            self.counters["no_capacity"] += 1
            if retries == 0:
                self.log.log("no_capacity", rid=spec.rid)
            asyncio.get_running_loop().call_later(
                self.cfg.retry_backoff_s,
                lambda: self._admit(spec, retries + 1))
            return
        bs = self.cfg.block_size
        isl_blocks = len(hashes)
        hit = min(overlap.scores.get(wid, 0), isl_blocks)
        self.counters["hit_blocks"] += hit
        self.counters["isl_blocks"] += isl_blocks
        w = self.workers[wid]
        remote = (self.disagg_router.prefill_remote(spec.isl, hit * bs)
                  and any(not p.dead
                          for p in self.prefill_workers.values()))
        if remote:
            self.counters["remote_prefills"] += 1
            req = SimRequest(spec, hashes, new_tokens=spec.isl,
                             fetch_s=0.0, fetched_blocks=0, hit_blocks=hit,
                             arrive_t=self.clock.now, kind="prefill",
                             target_wid=wid)
            req.retries = retries
            self.log.log("route", rid=spec.rid, worker=wid, hit=hit,
                         blocks=isl_blocks, remote=True)
            self.prefill_queue.items.append(req)
            self._pump_prefill_queue()
            return
        # fabric credit: blocks some OTHER worker holds are fetched over
        # the chosen worker's measured link iff ITS real AdmissionGate
        # prices the fetch under the recompute
        fetched = 0
        fetch_s = 0.0
        extra = min(overlap.fleet_depth, isl_blocks) - hit
        if extra > 0 and w.gate.admit(extra, w.link):
            fetched = extra
            fetch_s = w.gate.modeled_fetch_s(extra, w.link)
            self.counters["fabric_fetch_blocks"] += fetched
        new_tokens = max(spec.isl - (hit + fetched) * bs, 0)
        req = SimRequest(spec, hashes, new_tokens=new_tokens,
                         fetch_s=fetch_s, fetched_blocks=fetched,
                         hit_blocks=hit, arrive_t=self.clock.now)
        req.retries = retries
        self.log.log("route", rid=spec.rid, tenant=spec.tenant,
                     worker=wid, hit=hit, fetched=fetched,
                     blocks=isl_blocks, remote=False)
        w.submit(req)

    # ------------------------------------------------- disagg prefill leg
    def _pump_prefill_queue(self) -> None:
        q = self.prefill_queue
        while q.items:
            idle = [w for w in self.prefill_workers.values()
                    if not w.dead and w.prefill is None and not w.waiting]
            if not idle:
                return
            req = q.items.popleft()
            q.inflight += 1
            # the prefill worker's own prefix cache shortens its work
            p_overlap = self.indexer.find_matches(req.hashes)
            p_hit = min(p_overlap.scores.get(idle[0].worker_id, 0),
                        len(req.hashes))
            req.new_tokens = max(req.spec.isl
                                 - p_hit * self.cfg.block_size, 0)
            idle[0].submit(req)

    def on_prefill_handoff(self, req: SimRequest, pw: SimWorker) -> None:
        """Remote prefill finished: price the KV handoff to the decode
        worker over its measured link, then admit decode with the KV
        already shipped (new_tokens=0)."""
        self.prefill_queue.inflight -= 1
        wid = req.target_wid
        w = self.workers.get(wid)
        if w is None or w.dead or w.draining:
            live = sorted(wid_ for wid_, w_ in self.workers.items()
                          if not w_.dead and wid_ not in self.draining)
            if not live:
                self.on_requests_lost([req])
                self._pump_prefill_queue()
                return
            wid = live[0]
            w = self.workers[wid]
        n_blocks = len(req.hashes)
        handoff_s = (w.gate.modeled_fetch_overlap_s(
            n_blocks, w.link, self.cfg.stream_layers)
            if self.cfg.stream_layers > 0
            else w.gate.modeled_fetch_s(n_blocks, w.link))
        dreq = SimRequest(req.spec, req.hashes, new_tokens=0,
                          fetch_s=handoff_s, fetched_blocks=n_blocks,
                          hit_blocks=req.hit_blocks,
                          arrive_t=req.arrive_t)
        dreq.retries = req.retries
        self.log.log("prefill_handoff", rid=req.spec.rid,
                     prefill_worker=pw.worker_id, worker=wid,
                     blocks=n_blocks)
        w.submit(dreq)
        self._pump_prefill_queue()

    # -------------------------------------------------------- completions
    def on_first_token(self, req: SimRequest, w: SimWorker) -> None:
        ttft_ms = (req.first_t - req.arrive_t) * 1e3
        self.log.log("first_token", rid=req.spec.rid, worker=w.worker_id,
                     ttft_ms=round(ttft_ms, 3))

    def on_complete(self, req: SimRequest, w: SimWorker) -> None:
        now = self.clock.now
        ttft_ms = (req.first_t - req.arrive_t) * 1e3
        itl_ms = None
        if req.spec.osl > 1:
            itl_ms = (now - req.first_t) * 1e3 / (req.spec.osl - 1)
        self.counters["completed"] += 1
        self.ttft_ms.append(ttft_ms)
        if itl_ms is not None:
            self.itl_ms.append(itl_ms)
        self.collector.record(ttft_ms, itl_ms)
        self.log.log("complete", rid=req.spec.rid, tenant=req.spec.tenant,
                     worker=w.worker_id, ttft_ms=round(ttft_ms, 3),
                     itl_ms=round(itl_ms, 3) if itl_ms is not None else None)

    def on_requests_lost(self, reqs: List[SimRequest]) -> None:
        """A crash or forced retire cut these in-flight requests: the
        frontend retries them (bounded), exactly as production clients
        re-dispatch on a vanished instance."""
        for req in reqs:
            self.counters["lost"] += 1
            if req.retries >= self.cfg.max_retries:
                self.counters["dropped"] += 1
                self.log.log("drop", rid=req.spec.rid,
                             retries=req.retries)
                continue
            self.counters["retried"] += 1
            self.log.log("retry", rid=req.spec.rid, retries=req.retries + 1)
            spec = req.spec
            nxt = req.retries + 1
            asyncio.get_running_loop().call_later(
                self.cfg.retry_backoff_s,
                lambda s=spec, r=nxt: self._admit(s, r))

    # -------------------------------------------------------------- drive
    @property
    def inflight(self) -> int:
        done = (self.counters["completed"] + self.counters["dropped"])
        return self.counters["arrived"] - done

    async def run(self, workload: Workload,
                  faults: Tuple[Tuple[float, str, Callable], ...] = (),
                  duration_s: Optional[float] = None) -> None:
        loop = asyncio.get_running_loop()
        self._t0 = loop.time()
        duration = duration_s or (workload.duration_s + 1.0)
        for at, name, fn in faults:
            loop.call_at(self._t0 + at,
                         lambda n=name, f=fn: (self.log.log("fault", name=n),
                                               f(self)))
        self._start_frontend(workload)
        end = self._t0 + duration
        while loop.time() < end:
            await asyncio.sleep(min(5.0, end - loop.time()))
        grace = end + self.cfg.drainout_s
        while self.inflight > 0 and loop.time() < grace:
            await asyncio.sleep(1.0)
        self.log.log("sim_end", inflight=self.inflight)

    # ------------------------------------------------------------- report
    def report(self, wall_s: Optional[float] = None) -> dict:
        slo = self.cfg.slo or ServiceLevelObjective()
        attained = (sum(1 for v in self.ttft_ms if v <= slo.ttft_p90_ms)
                    / max(len(self.ttft_ms), 1))
        r = {
            "seed": self.seed,
            "virtual_s": round(self.clock.now, 3),
            "requests": dict(self.counters),
            "replicas": {"start": self.cfg.replicas,
                         "end": self.live_decode_count(),
                         "peak": self.replica_peak},
            "prefill_replicas": {"start": self.cfg.prefill_replicas,
                                 "end": self.live_prefill_count(),
                                 "peak": self.prefill_peak},
            "latency_ms": {
                "ttft_p50": percentile(self.ttft_ms, 50),
                "ttft_p90": percentile(self.ttft_ms, 90),
                "ttft_p99": percentile(self.ttft_ms, 99),
                "itl_p50": percentile(self.itl_ms, 50),
                "itl_p90": percentile(self.itl_ms, 90),
            },
            "slo": {"ttft_target_ms": slo.ttft_p90_ms,
                    "ttft_attainment": round(attained, 4)},
            "router": {
                "kv_events": self.kv_events,
                "hit_rate_blocks": round(
                    self.counters["hit_blocks"]
                    / max(self.counters["isl_blocks"], 1), 4),
                "fabric_fetch_blocks": self.counters["fabric_fetch_blocks"],
            },
            "events": len(self.log),
            "event_log_digest": self.log.digest(),
        }
        if self.tenant_table is not None:
            # per-tenant serving summary (noisy_neighbor's check input):
            # routed decisions + residual residency per live worker
            r["tenants"] = {
                "admitted": self.scheduler.tenant_counters(),
                "kv_blocks": {
                    t: sum(sum(w.ledger.snapshot().get(t, {}).values())
                           for w in self.workers.values()
                           if not w.dead and w.ledger is not None)
                    for t in sorted(self.tenant_table.policies)},
            }
        if self.planner is not None:
            r["planner"] = {
                "counters": dict(self.planner.counters),
                "disagg_threshold": self.planner.disagg_threshold,
            }
        if wall_s is not None:
            r["wall_s"] = round(wall_s, 3)
        return r
