"""Virtual time for the fleet simulator: a discrete-event asyncio loop.

The control plane under test (components/planner.py, runtime/kvstore.py
leases, runtime/egress.py watches) is ordinary asyncio code that sleeps,
schedules timers and reads ``time.monotonic()``. Rather than reimplement
it against an ad-hoc event queue — which would test a COPY of the
planner, not the planner — the simulator runs the real code on a real
asyncio event loop whose notion of time is virtual:

- :class:`VirtualTimeLoop` is a ``SelectorEventLoop`` whose ``time()``
  reads a :class:`VirtualClock`, and whose selector never blocks: when
  no callback is ready and no fd fired, it ADVANCES the clock straight
  to the next scheduled timer. ``asyncio.sleep(300)`` costs microseconds
  of wall time; a simulated hour of planner evaluations completes in
  seconds.
- :func:`virtual_time` patches ``time.monotonic`` / ``time.time`` /
  ``time.perf_counter`` to the same clock for the duration of a run, so
  code that timestamps outside the loop (planner hysteresis/cooldown,
  kvstore lease deadlines, trace offsets) sees one consistent timeline.

Determinism: a single loop, no real I/O waits, seeded RNGs and a fixed
virtual epoch mean the same seed replays the exact same event sequence —
the byte-identical-event-log gate in tests/test_fleet_sim.py. The sim
core deliberately never reads the wall clock or unseeded randomness
(the DL005 discipline extended outside jit; the determinism test is the
enforcement).
"""

from __future__ import annotations

import asyncio
import contextlib
import selectors
import time

__all__ = ["VirtualClock", "VirtualTimeLoop", "virtual_time",
           "run_simulation", "REAL_MONOTONIC", "REAL_PERF_COUNTER"]

# Wall-clock handles captured BEFORE any patching — the tier-1 wall-time
# budget assertions must keep measuring real time while virtual time is
# in effect.
REAL_MONOTONIC = time.monotonic
REAL_PERF_COUNTER = time.perf_counter
_REAL_TIME = time.time

# Fixed virtual epoch: ``time.time()`` under virtual_time() returns
# EPOCH + clock.now, so epoch timestamps in planner decisions / status
# records are seed-deterministic too.
VIRTUAL_EPOCH = 1_700_000_000.0


class VirtualClock:
    """The simulation's single source of time (seconds, starts at 0)."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def advance(self, dt: float) -> None:
        self.now += dt

    def monotonic(self) -> float:
        return self.now

    def time(self) -> float:
        return VIRTUAL_EPOCH + self.now

    def perf_counter(self) -> float:
        return self.now


class _VirtualSelector(selectors.SelectSelector):
    """Selector that never blocks: polls real fds (the loop's self-pipe,
    a lazily-bound TcpStreamServer listener) with timeout 0, and when
    nothing is ready jumps virtual time forward by the requested timeout
    — which the event loop computed as the gap to its next timer."""

    def __init__(self, clock: VirtualClock):
        super().__init__()
        self._clock = clock

    def select(self, timeout=None):
        ready = super().select(0)
        if ready:
            return ready
        if timeout is None:
            # No ready callbacks, no scheduled timers, no fd activity:
            # the simulation deadlocked. Fail loudly instead of hanging.
            raise RuntimeError(
                "virtual-time deadlock: the loop is waiting on I/O that "
                "can never arrive (no timers scheduled)")
        if timeout > 0:
            self._clock.advance(timeout)
        return ready


class VirtualTimeLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop running on a :class:`VirtualClock`."""

    def __init__(self, clock: VirtualClock):
        super().__init__(_VirtualSelector(clock))
        self.clock = clock

    def time(self) -> float:
        return self.clock.now


@contextlib.contextmanager
def virtual_time(clock: VirtualClock):
    """Patch the stdlib time sources to ``clock`` (restored on exit)."""
    time.monotonic = clock.monotonic
    time.time = clock.time
    time.perf_counter = clock.perf_counter
    try:
        yield clock
    finally:
        time.monotonic = REAL_MONOTONIC
        time.time = _REAL_TIME
        time.perf_counter = REAL_PERF_COUNTER


def run_simulation(main_fn, clock: VirtualClock = None):
    """Run ``await main_fn()`` to completion on a fresh virtual-time loop
    with the stdlib clocks patched, then tear the loop down (pending
    tasks cancelled and awaited). Returns the coroutine's result.

    ``main_fn`` is a zero-arg coroutine FUNCTION so the coroutine object
    is created with the virtual loop already current.
    """
    clock = clock or VirtualClock()
    loop = VirtualTimeLoop(clock)
    asyncio.set_event_loop(loop)
    try:
        with virtual_time(clock):
            result = loop.run_until_complete(main_fn())
            pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for t in pending:
                t.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            loop.run_until_complete(loop.shutdown_asyncgens())
        return result
    finally:
        asyncio.set_event_loop(None)
        loop.close()
