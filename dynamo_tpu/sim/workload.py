"""Trace-driven workload generation for the fleet simulator.

A workload is a time-ordered list of request specs (the JSONL trace
format documented in docs/fleet_sim.md):

    {"at": 12.125, "rid": "r000042", "tenant": "t03",
     "session": "t03-s0007", "turn": 2, "isl": 1536, "osl": 96}

``at`` is the virtual arrival offset in seconds; ``isl``/``osl`` are
input/output sequence lengths in tokens. Requests in the same
``session`` share a token prefix: turn N's prompt is turn N-1's prompt
plus its output plus fresh user tokens, so agentic multi-turn traffic
produces the DEEP prefix reuse the KV router's radix index is built for.
Token ids themselves are materialized lazily and deterministically by
the fleet (sim/fleet.py TokenCatalog) from (seed, session).

Generators (all seed-deterministic):

- diurnal burst: sinusoidal arrival rate between base_rps and peak_rps
  (thinned-Poisson sampling);
- multi-tenant skew: tenants drawn from a Zipf-like weight vector, each
  with a shared per-tenant system-prefix block (cross-request reuse);
- agentic multi-turn: a fraction of arrivals continue an open session
  (prompt grows by the previous turn), the rest open new sessions;
- long-context tails: a small fraction of prompts inflated ~8-16×.
"""

from __future__ import annotations

import dataclasses
import json
import math
import random
from typing import Dict, Iterator, List, Optional

__all__ = ["RequestSpec", "Workload", "generate_workload", "diurnal_rate"]


@dataclasses.dataclass
class RequestSpec:
    at: float
    rid: str
    tenant: str
    session: str
    turn: int
    isl: int
    osl: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RequestSpec":
        return cls(at=float(d["at"]), rid=d["rid"], tenant=d["tenant"],
                   session=d["session"], turn=int(d["turn"]),
                   isl=int(d["isl"]), osl=int(d["osl"]))


class Workload:
    def __init__(self, specs: List[RequestSpec]):
        self.specs = sorted(specs, key=lambda s: (s.at, s.rid))

    def __len__(self) -> int:
        return len(self.specs)

    def __iter__(self) -> Iterator[RequestSpec]:
        return iter(self.specs)

    @property
    def duration_s(self) -> float:
        return self.specs[-1].at if self.specs else 0.0

    def save_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for s in self.specs:
                f.write(json.dumps(s.to_dict(), sort_keys=True) + "\n")

    @classmethod
    def load_jsonl(cls, path: str) -> "Workload":
        specs = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    specs.append(RequestSpec.from_dict(json.loads(line)))
        return cls(specs)


def diurnal_rate(t: float, base_rps: float, peak_rps: float,
                 period_s: float, phase: float = 0.0) -> float:
    """Sinusoidal arrival rate: base at the trough, peak at the crest —
    one ``period_s`` models a compressed diurnal cycle."""
    mid = (base_rps + peak_rps) / 2.0
    amp = (peak_rps - base_rps) / 2.0
    return mid + amp * math.sin(2.0 * math.pi * (t / period_s) + phase)


@dataclasses.dataclass
class _Session:
    sid: str
    turn: int
    context_tokens: int      # accumulated prompt length (isl of next turn
    #                          starts from here)


def generate_workload(duration_s: float, seed: int = 0, *,
                      base_rps: float = 2.0, peak_rps: float = 8.0,
                      period_s: Optional[float] = None,
                      tenants: int = 8, zipf_a: float = 1.1,
                      agentic_frac: float = 0.35,
                      long_tail_frac: float = 0.04,
                      isl_base: int = 256, isl_spread: int = 768,
                      osl_base: int = 24, osl_spread: int = 104,
                      burst_at: Optional[float] = None,
                      burst_len_s: float = 0.0,
                      burst_factor: float = 1.0,
                      flood_tenant: Optional[str] = None,
                      flood_at: float = 0.0,
                      flood_len_s: float = 0.0,
                      flood_factor: float = 1.0) -> Workload:
    """The mixed default trace: diurnal burst x multi-tenant skew x
    agentic multi-turn x long-context tails. ``burst_*`` overlays a
    square-wave surge (the scale-storm ingredient) on the diurnal base.
    ``flood_*`` overlays a NOISY-NEIGHBOR surge: during the flood
    window, ``flood_tenant``'s arrival rate multiplies ``flood_factor``×
    while everyone else's traffic is untouched — the adversary the
    tenant fair-share scheduler and KV quotas must absorb
    (docs/multi_tenant.md; scenario ``noisy_neighbor``)."""
    rng = random.Random(seed)
    period = period_s or duration_s
    # Zipf-like tenant weights
    weights = [1.0 / (i + 1) ** zipf_a for i in range(tenants)]
    wsum = sum(weights)
    weights = [w / wsum for w in weights]
    tenant_ids = [f"t{i:02d}" for i in range(tenants)]
    open_sessions: Dict[str, List[_Session]] = {t: [] for t in tenant_ids}
    session_count = 0

    rate_max = max(peak_rps, base_rps) * max(burst_factor, 1.0)
    specs: List[RequestSpec] = []
    t = 0.0
    n = 0
    while True:
        # thinned Poisson: candidate arrivals at the envelope rate,
        # accepted with probability rate(t)/rate_max
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            break
        rate = diurnal_rate(t, base_rps, peak_rps, period)
        if (burst_at is not None and burst_at <= t < burst_at + burst_len_s):
            rate *= burst_factor
        if rng.random() * rate_max > rate:
            continue
        tenant = rng.choices(tenant_ids, weights=weights)[0]
        sessions = open_sessions[tenant]
        osl = osl_base + int(rng.random() * osl_spread)
        if sessions and rng.random() < agentic_frac:
            # continue an open session: prompt = full prior context +
            # fresh user turn (deep prefix reuse)
            s = rng.choice(sessions)
            s.turn += 1
            new_user = 32 + int(rng.random() * 128)
            isl = s.context_tokens + new_user
        else:
            session_count += 1
            s = _Session(sid=f"{tenant}-s{session_count:05d}", turn=0,
                         context_tokens=0)
            sessions.append(s)
            if len(sessions) > 32:      # bound open-session memory
                sessions.pop(0)
            isl = isl_base + int(rng.random() * isl_spread)
            if rng.random() < long_tail_frac:
                isl *= 8 + int(rng.random() * 8)   # long-context tail
        s.context_tokens = isl + osl
        specs.append(RequestSpec(
            at=round(t, 6), rid=f"r{n:06d}", tenant=tenant, session=s.sid,
            turn=s.turn, isl=isl, osl=osl))
        n += 1
    if flood_tenant is not None and flood_len_s > 0 and flood_factor > 1:
        # noisy-neighbor overlay: an INDEPENDENT seeded Poisson stream
        # of fresh-session arrivals for the flooding tenant during the
        # window, on top of its organic share — (factor-1)× the mean
        # base rate, so factor≈ the tenant's total amplification
        frng = random.Random(seed ^ 0xF100D)
        mean_rps = (base_rps + peak_rps) / 2.0
        flood_rps = (flood_factor - 1.0) * mean_rps
        t = flood_at
        fn = 0
        while True:
            t += frng.expovariate(flood_rps)
            if t >= min(flood_at + flood_len_s, duration_s):
                break
            session_count += 1
            isl = isl_base + int(frng.random() * isl_spread)
            osl = osl_base + int(frng.random() * osl_spread)
            specs.append(RequestSpec(
                at=round(t, 6), rid=f"f{fn:06d}", tenant=flood_tenant,
                session=f"{flood_tenant}-f{session_count:05d}", turn=0,
                isl=isl, osl=osl))
            fn += 1
    return Workload(specs)
