"""Fleet-scale co-simulation (docs/fleet_sim.md).

A deterministic discrete-event simulator that runs the REAL control
plane — the SLA planner (components/planner.py), the KV router
(kv_router/{indexer,scheduler,scoring}.py), the disagg-threshold retune,
and the fabric admission gate (llm/kv/fabric.py) — against hundreds of
simulated replicas whose prefill/decode/KV-transfer timing comes from
the measured device models already in-repo (parallel/ici_model.py,
BENCH_LOCAL.jsonl step-time fits, the fabric PeerLinkTable cost model).

The whole fleet runs on a VIRTUAL clock (sim/clock.py): a simulated hour
of bursty trace-driven traffic over 200+ replicas completes in seconds
of tier-1 CPU time, and a fixed seed reproduces a byte-identical event
log — the determinism gate every scenario test asserts.

Lazy exports (PEP 562): light consumers — the mock worker pulling
:class:`BehaviorProfile`, tooling reading the trace format — must not
drag the full fleet/engine import chain in; only touching the fleet or
scenario surface does.
"""

_LAZY = {
    "VirtualClock": ".clock", "run_simulation": ".clock",
    "FleetConfig": ".fleet", "SimFleet": ".fleet",
    "BehaviorProfile": ".profiles",
    "EventLog": ".report",
    "SCENARIOS": ".scenarios", "run_scenario": ".scenarios",
    "check_report": ".scenarios",
    "Workload": ".workload", "generate_workload": ".workload",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod, __name__), name)
