"""Synthetic worker behavior profiles, shared between the fleet
simulator's worker model (sim/worker.py) and the live mock worker
(components/mock_worker.py --profile) — the same fault vocabulary drives
both, so a scenario rehearsed in simulation is replayable against real
processes in a smoke test.

Profiles compose from four knobs:

- ``slow-start:T[:F]`` — for the first T seconds after start the worker
  serves F× slower (default 4×): the XLA-compile / cold-cache ramp. The
  admission gate's age-weighted prefill-rate estimator
  (llm/kv/fabric.PrefillRateEstimator) exists precisely because of this
  window.
- ``crash-at:T`` — the worker dies T seconds after start: discovery
  entry gone, in-flight requests lost (the router/planner must absorb
  it — the cascading-failure ingredient).
- ``drain-ignore`` — the worker never honors a drain request: the
  planner's drain-timeout path (retire-anyway) is the only way out.
- ``latency:F`` — every service time inflated F× for the worker's whole
  life (the chronically-slow replica).
"""

from __future__ import annotations

import dataclasses

__all__ = ["BehaviorProfile"]


@dataclasses.dataclass
class BehaviorProfile:
    name: str = "steady"
    slow_start_s: float = 0.0
    slow_start_factor: float = 4.0
    crash_at_s: float = 0.0          # 0 = never
    drain_ignore: bool = False
    latency_factor: float = 1.0

    def speed_factor(self, age_s: float) -> float:
        """Service-rate multiplier at worker age ``age_s`` (1.0 = the
        perf model's nominal rates; <1 = slower)."""
        f = 1.0 / max(self.latency_factor, 1e-6)
        if self.slow_start_s > 0 and age_s < self.slow_start_s:
            f /= max(self.slow_start_factor, 1.0)
        return f

    def service_delay_s(self, age_s: float, unit_s: float = 0.01) -> float:
        """Additive per-request delay for REAL-TIME fixtures (the mock
        worker): the same shape as speed_factor, expressed as small
        absolute delays so live smoke tests stay fast."""
        d = (self.latency_factor - 1.0) * unit_s
        if self.slow_start_s > 0 and age_s < self.slow_start_s:
            d += (self.slow_start_factor - 1.0) * unit_s
        return max(d, 0.0)

    @classmethod
    def parse(cls, spec: str) -> "BehaviorProfile":
        """Parse a comma-joined spec, e.g.
        ``slow-start:30``, ``crash-at:120,latency:2``,
        ``drain-ignore``. Empty/"steady" → the neutral profile."""
        p = cls(name=spec or "steady")
        if not spec or spec == "steady":
            return p
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition(":")
            if key == "slow-start":
                args = val.split(":") if val else []
                p.slow_start_s = float(args[0]) if args else 30.0
                if len(args) > 1:
                    p.slow_start_factor = float(args[1])
            elif key == "crash-at":
                p.crash_at_s = float(val)
            elif key == "drain-ignore":
                p.drain_ignore = True
            elif key == "latency":
                p.latency_factor = float(val)
            else:
                raise ValueError(f"unknown profile knob {part!r}")
        return p
