"""Event log + scenario report for the fleet simulator.

The event log is the determinism contract: every simulation appends
(virtual_time, kind, fields) tuples for request lifecycle, worker
lifecycle, planner actions and retunes — and two runs with the same seed
must serialize to BYTE-IDENTICAL JSONL (tests/test_fleet_sim.py gate).
Nothing wall-clock-derived or hash-randomized may enter an entry.
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Tuple

__all__ = ["EventLog"]


class EventLog:
    """Append-only (t, kind, fields) log on the virtual clock."""

    def __init__(self, clock):
        self.clock = clock
        self.entries: List[Tuple[float, str, dict]] = []

    def log(self, kind: str, **fields) -> None:
        self.entries.append((round(self.clock.now, 6), kind, fields))

    def count(self, kind: str) -> int:
        return sum(1 for _, k, _f in self.entries if k == kind)

    def of_kind(self, kind: str) -> List[Tuple[float, dict]]:
        return [(t, f) for t, k, f in self.entries if k == kind]

    def to_jsonl_bytes(self) -> bytes:
        out = []
        for t, kind, fields in self.entries:
            out.append(json.dumps({"t": t, "ev": kind, **fields},
                                  sort_keys=True, separators=(",", ":")))
        return ("\n".join(out) + "\n").encode()

    def digest(self) -> str:
        return hashlib.sha256(self.to_jsonl_bytes()).hexdigest()

    def __len__(self) -> int:
        return len(self.entries)
