"""Measured device models driving simulated worker timing.

Nothing here invents a cost model: decode step times come from the
BENCH_LOCAL.jsonl device-truth fits (the bench's measured
``device_step_ms`` per batch size, least-squares over the batch sweep),
TP collective overhead from :mod:`dynamo_tpu.parallel.ici_model`
(``tp_decode_step_s``), pp boundary cost from ``pp_boundary_s``, and KV
transfer time from the SAME ``LinkStats``/``AdmissionGate`` classes the
live fabric uses (llm/kv/fabric.py) — the simulator prices a fetch with
the exact arithmetic the production gate runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from ..parallel import ici_model

__all__ = ["WorkerPerfModel", "fit_step_times", "load_bench_step_points"]

_BENCH_LOCAL = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "BENCH_LOCAL.jsonl")

_METRIC_RE = re.compile(r"decode_tok_per_s_chip_(\w+?)_b(\d+)_")


def load_bench_step_points(path: Optional[str] = None,
                           family: str = "llama8b"
                           ) -> List[Tuple[int, float]]:
    """(batch, device_step_s) points for one model family out of the
    bench ledger. Silent empty list when the ledger is absent/foreign —
    callers fall back to the default constants."""
    path = path or _BENCH_LOCAL
    points: Dict[int, float] = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                res = rec.get("result", {})
                m = _METRIC_RE.match(res.get("metric", ""))
                if m is None or m.group(1) != family:
                    continue
                step_ms = res.get("extra", {}).get("device_step_ms")
                if step_ms:
                    # newest entry wins per batch size (ledger is
                    # append-only)
                    points[int(m.group(2))] = float(step_ms) / 1e3
    except OSError:
        return []
    return sorted(points.items())


def fit_step_times(points: List[Tuple[int, float]]
                   ) -> Optional[Tuple[float, float]]:
    """Least-squares (base_s, per_seq_s) fit of step time vs batch size —
    the continuous-batching cost curve. None when under-determined."""
    if len(points) < 2:
        return None
    n = len(points)
    sx = sum(b for b, _ in points)
    sy = sum(s for _, s in points)
    sxx = sum(b * b for b, _ in points)
    sxy = sum(b * s for b, s in points)
    denom = n * sxx - sx * sx
    if denom == 0:
        return None
    slope = (n * sxy - sx * sy) / denom
    base = (sy - slope * sx) / n
    if base <= 0 or slope <= 0:
        return None
    return base, slope


# Defaults measured on the v5e chip (BENCH_LOCAL.jsonl llama8b sweep:
# b32 17.7ms → b128 32.8ms device step) — used when the ledger is absent.
_DEFAULT_BASE_S = 0.0126
_DEFAULT_SLOPE_S = 0.000157


@dataclasses.dataclass
class WorkerPerfModel:
    """One simulated replica's timing truth.

    ``step_time_s(batch)`` is the decode dispatch time with ``batch``
    concurrent sequences (continuous batching: every active sequence
    advances one token per step). TP adds the modeled ICI collective
    cost, pp adds the DCN boundary hops — both from parallel/ici_model.
    """

    prefill_tok_per_s: float = 4000.0
    step_base_s: float = _DEFAULT_BASE_S
    step_per_seq_s: float = _DEFAULT_SLOPE_S
    tp: int = 1
    pp: int = 1
    hidden: int = 4096
    num_layers: int = 32
    kv_bytes_per_block: int = 1 << 20

    @classmethod
    def from_bench(cls, family: str = "llama8b",
                   **overrides) -> "WorkerPerfModel":
        fit = fit_step_times(load_bench_step_points(family=family))
        if fit is not None:
            overrides.setdefault("step_base_s", fit[0])
            overrides.setdefault("step_per_seq_s", fit[1])
        return cls(**overrides)

    def step_time_s(self, batch: int) -> float:
        b = max(int(batch), 1)
        t = self.step_base_s + self.step_per_seq_s * b
        if self.tp > 1:
            t += ici_model.tp_decode_step_s(b, self.hidden, self.num_layers,
                                            self.tp)
        if self.pp > 1:
            t += self.pp * ici_model.pp_boundary_s(b, self.hidden, self.pp)
        return t

    def prefill_s(self, tokens: int) -> float:
        if tokens <= 0:
            return 0.0
        return tokens / self.prefill_tok_per_s
