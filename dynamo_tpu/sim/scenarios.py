"""Scenario library: the named fleet storms the ROADMAP asks the control
plane to survive, each returning a report with its violated expectations
(empty = pass). ``fleetsim`` (tools/fleetsim.py) and the tier-1 sim
tests are thin wrappers over :func:`run_scenario`.

Every scenario is deterministic under its seed: the report carries the
event-log digest, and running the same (scenario, seed) twice must
produce byte-identical logs — the gate in tests/test_fleet_sim.py.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Callable, Dict, List, Tuple

from ..components.planner import PlannerConfig
from ..llm.slo import ServiceLevelObjective
from .clock import REAL_PERF_COUNTER, run_simulation
from .fleet import FleetConfig, SimFleet
from .models import WorkerPerfModel
from .workload import Workload, generate_workload

__all__ = ["SCENARIOS", "Scenario", "run_scenario", "check_report"]


# Fleet-class perf points (measured-fit shapes scaled to model class;
# sim/models.py pulls the llama8b device-step fit when the bench ledger
# is present):
def _perf_small() -> WorkerPerfModel:
    return WorkerPerfModel.from_bench(prefill_tok_per_s=3000.0,
                                      step_base_s=0.03,
                                      step_per_seq_s=0.005)


def _perf_large() -> WorkerPerfModel:
    # a 70B-class replica: slow steps, slow prefill — 200 of these are
    # meaningfully loaded by tens of rps
    return WorkerPerfModel(prefill_tok_per_s=800.0, step_base_s=0.12,
                           step_per_seq_s=0.02, tp=8, hidden=8192,
                           num_layers=80, kv_bytes_per_block=1 << 21)


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    build: Callable[..., Tuple[FleetConfig, Workload, tuple, float]]
    check: Callable[[SimFleet, dict], List[str]]


def _fault_mass_drain(count: int):
    """Ops-driven storm: drain keys written for ``count`` workers AT
    ONCE through the real drain protocol (store key → fleet watch →
    worker re-announce draining → drain-to-exit). Like real node-pool
    rotation tooling, it respects the fleet's min_decode_workers floor
    — the planner may already have shrunk the fleet by the time the
    rotation fires."""

    def fault(fleet: SimFleet) -> None:
        live = sorted(w for w, x in fleet.workers.items()
                      if not x.dead and w not in fleet.draining)
        floor = fleet.cfg.slo.min_decode_workers if fleet.cfg.slo else 1
        n = min(count, max(len(live) - floor, 0))
        for wid in live[-n:] if n else []:
            fleet.spawn(fleet.runtime.store.kv_put(
                fleet.endpoint.drain_key(wid), b"{}"))
    return fault


def _fault_crash(count: int, stagger_s: float = 3.0):
    def fault(fleet: SimFleet) -> None:
        loop = asyncio.get_running_loop()
        live = sorted(w for w, x in fleet.workers.items() if not x.dead)
        for i, wid in enumerate(live[-count:]):
            loop.call_later(i * stagger_s, fleet.workers[wid].crash)
    return fault


def _fault_flush(fleet: SimFleet) -> None:
    n = sum(w.flush_kv() for w in fleet.workers.values() if not w.dead)
    fleet.log.log("prefix_flush", blocks=n)


def _fault_brownout(count: int, latency_factor: float = 8.0):
    """Slow-not-dead + stats partition: ``count`` workers keep serving
    at latency_factor× while their published stats freeze — the router
    and planner keep seeing the healthy pre-brownout numbers (the
    kvstore-partition shape). Deterministic victim choice (sorted)."""

    def fault(fleet: SimFleet) -> None:
        live = sorted(w for w, x in fleet.workers.items() if not x.dead)
        for wid in live[-count:]:
            fleet.workers[wid].set_brownout(latency_factor,
                                            partition=True)
            fleet.log.log("brownout", worker=wid,
                          factor=latency_factor)
    return fault


def _fault_brownout_recover(fleet: SimFleet) -> None:
    for wid, w in sorted(fleet.workers.items()):
        if not w.dead and w.partitioned:
            w.clear_brownout()
            fleet.log.log("brownout_recover", worker=wid)


def _fault_disk_pressure(full: bool):
    """ENOSPC mid-spill fleet-wide: every worker's demote tier refuses
    writes; the write-behind SHEDS (counted) and serving continues."""

    def fault(fleet: SimFleet) -> None:
        for wid, w in sorted(fleet.workers.items()):
            if not w.dead:
                w.disk_full = full
        fleet.log.log("disk_pressure", full=full)
    return fault


# --------------------------------------------------------------- builders
def _baseline_hour(seed: int, replicas: int = 200,
                   duration_s: float = 3600.0):
    slo = ServiceLevelObjective(
        ttft_p90_ms=6000.0, itl_p90_ms=400.0, max_queue_depth=3.0,
        min_decode_workers=max(replicas - 10, 1),
        max_decode_workers=replicas + 30)
    cfg = FleetConfig(
        replicas=replicas, slots=2, kv_blocks=384, host_blocks=192,
        perf=_perf_large(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=5.0, cooldown_s=60.0,
                                  breach_cycles=3, scale_step=4,
                                  drain_timeout_s=240.0, drain_poll_s=1.0,
                                  status_interval_s=30.0),
        stats_interval_s=5.0, scrape_interval_s=2.0,
        provision_delay_s=30.0, drainout_s=600.0)
    # bursty diurnal mix sized to ~30% mean utilization of the 200-
    # replica fleet (capacity ≈ replicas·slots/service_s ≈ 19 rps) —
    # request count is the sim's wall-clock driver, so the load sits
    # where the planner still sees real pressure at peak without
    # burning tier-1 budget on idle-ish requests
    wl = generate_workload(duration_s, seed, base_rps=2.5, peak_rps=8.0,
                           agentic_frac=0.4, long_tail_frac=0.03,
                           osl_base=64, osl_spread=128)
    return cfg, wl, (), duration_s


def _check_baseline(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if r["requests"]["completed"] < 0.98 * r["requests"]["arrived"]:
        v.append("fewer than 98% of requests completed")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} requests")
    if r["slo"]["ttft_attainment"] < 0.9:
        v.append(f"TTFT attainment {r['slo']['ttft_attainment']} < 0.9")
    if r["planner"]["counters"]["evaluations"] < 100:
        v.append("planner barely ran")
    if r["router"]["hit_rate_blocks"] <= 0.05:
        v.append("prefix reuse never materialized")
    return v


def _scale_storm(seed: int, replicas: int = 12,
                 duration_s: float = 1500.0):
    slo = ServiceLevelObjective(
        ttft_p90_ms=4000.0, itl_p90_ms=400.0, max_queue_depth=2.0,
        min_decode_workers=max(replicas // 2, 2),
        max_decode_workers=replicas + 16)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=20.0,
                                  breach_cycles=3, scale_step=2,
                                  drain_timeout_s=120.0, drain_poll_s=0.5,
                                  status_interval_s=10.0),
        stats_interval_s=2.0, scrape_interval_s=1.0,
        provision_delay_s=15.0, new_worker_profile="slow-start:20",
        drainout_s=600.0)
    wl = generate_workload(duration_s, seed, base_rps=1.0, peak_rps=1.8,
                           burst_at=240.0, burst_len_s=600.0,
                           burst_factor=6.0, osl_base=64, osl_spread=128)
    return cfg, wl, (), duration_s


def _check_scale_storm(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if r["planner"]["counters"]["scale_up"] < 2:
        v.append("planner never scaled into the storm")
    if r["replicas"]["peak"] < r["replicas"]["start"] + 4:
        v.append("fleet did not grow under the burst")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} requests")
    if r["requests"]["completed"] < 0.99 * r["requests"]["arrived"]:
        v.append("storm lost requests")
    # SLO attainment once the scale-out landed (late window)
    if r["slo"]["late_attainment"] < 0.85:
        v.append(f"late-window TTFT attainment "
                 f"{r['slo']['late_attainment']} < 0.85")
    return v


def _drain_storm(seed: int, replicas: int = 24,
                 duration_s: float = 1400.0):
    slo = ServiceLevelObjective(
        ttft_p90_ms=4000.0, itl_p90_ms=400.0, max_queue_depth=3.0,
        min_decode_workers=6, max_decode_workers=replicas + 4)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=15.0,
                                  breach_cycles=3, scale_step=2,
                                  drain_timeout_s=200.0, drain_poll_s=0.5,
                                  status_interval_s=10.0),
        stats_interval_s=2.0, scrape_interval_s=1.0, drainout_s=600.0)
    # heavy first third, then the load collapses — the planner should
    # drain the excess; at t=500 ops additionally mass-drains 8 workers
    wl = generate_workload(duration_s / 3.0, seed, base_rps=3.0,
                           peak_rps=6.0, osl_base=64, osl_spread=128)
    faults = ((duration_s / 3.0 + 60.0, "mass_drain",
               _fault_mass_drain(8)),)
    return cfg, wl, faults, duration_s


def _check_drain_storm(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} in-flight requests")
    if r["requests"]["completed"] != r["requests"]["arrived"]:
        v.append("not every admitted request completed")
    if r["requests"]["forced_exits"]:
        v.append("a drain was forced (in-flight work cut)")
    if r["requests"]["clean_exits"] < 8:
        v.append("mass drain did not retire 8 workers cleanly")
    if r["planner"]["counters"]["drains_completed"] < 1:
        v.append("planner never drained the idle excess")
    if r["replicas"]["end"] >= r["replicas"]["start"]:
        v.append("fleet did not shrink after the load collapsed")
    return v


def _crash_cascade(seed: int, replicas: int = 16,
                   duration_s: float = 1000.0):
    slo = ServiceLevelObjective(
        ttft_p90_ms=5000.0, itl_p90_ms=400.0, max_queue_depth=2.0,
        min_decode_workers=replicas - 2, max_decode_workers=replicas + 8)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=20.0,
                                  breach_cycles=3, scale_step=2,
                                  drain_timeout_s=120.0, drain_poll_s=0.5,
                                  status_interval_s=10.0),
        stats_interval_s=2.0, scrape_interval_s=1.0,
        provision_delay_s=15.0, max_retries=5, drainout_s=600.0)
    wl = generate_workload(duration_s * 0.6, seed, base_rps=5.0,
                           peak_rps=8.0, osl_base=64, osl_spread=128)
    faults = ((300.0, "crash_cascade", _fault_crash(5, stagger_s=3.0)),)
    return cfg, wl, faults, duration_s


def _check_crash_cascade(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if r["requests"]["crashes"] != 5:
        v.append("expected exactly 5 crashes")
    if r["requests"]["dropped"]:
        v.append(f"retries did not absorb the cascade: "
                 f"{r['requests']['dropped']} dropped")
    if r["requests"]["completed"] != r["requests"]["arrived"]:
        v.append("not every request completed after the cascade")
    if r["planner"]["counters"]["scale_up"] < 1:
        v.append("planner never responded to the crash-induced pressure")
    if r["replicas"]["end"] < r["replicas"]["start"] - 4:
        v.append("planner never replaced the crashed replicas")
    return v


def _prefix_flush(seed: int, replicas: int = 10,
                  duration_s: float = 1200.0):
    slo = ServiceLevelObjective(
        ttft_p90_ms=6000.0, itl_p90_ms=400.0, max_queue_depth=4.0,
        min_decode_workers=replicas, max_decode_workers=replicas + 6)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=4096, host_blocks=1024,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=5.0, cooldown_s=30.0,
                                  status_interval_s=20.0),
        stats_interval_s=5.0, scrape_interval_s=2.0, drainout_s=600.0)
    # agentic-heavy: deep prefix reuse builds up, then the flush storm
    wl = generate_workload(duration_s, seed, base_rps=3.0, peak_rps=6.0,
                           tenants=4, agentic_frac=0.7,
                           osl_base=48, osl_spread=96)
    faults = ((600.0, "prefix_flush", _fault_flush),)
    return cfg, wl, faults, duration_s


def _check_prefix_flush(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    flush_t = next((t for t, f in fleet.log.of_kind("fault")
                    if f.get("name") == "prefix_flush"), None)
    if flush_t is None:
        return ["flush fault never fired"]
    flushed = next((f["blocks"] for _, f in
                    fleet.log.of_kind("prefix_flush")), 0)
    if flushed < 500:
        v.append(f"flush removed only {flushed} blocks — no storm")
    pre, post = [], []
    for t, f in fleet.log.of_kind("route"):
        frac = f["hit"] / max(f["blocks"], 1)
        if flush_t - 300 <= t < flush_t:
            pre.append(frac)
        elif flush_t <= t < flush_t + 15:
            post.append(frac)
    if not pre or not post:
        return ["no routed traffic around the flush"]
    pre_hit = sum(pre) / len(pre)
    post_hit = sum(post) / len(post)
    if pre_hit < 0.2:
        v.append(f"prefix reuse never warmed up (pre-flush hit {pre_hit:.2f})")
    # the crater is short — in-flight prefills re-register hot chains
    # within seconds — so measure right after the flush
    if post_hit > 0.85 * pre_hit:
        v.append(f"flush did not cool the prefix cache "
                 f"(hit {pre_hit:.2f} → {post_hit:.2f})")
    # the recompute storm must show up as a TTFT spike after the flush
    from ..llm.slo import percentile
    pre_ttft = percentile([f["ttft_ms"] for t, f in
                           fleet.log.of_kind("complete")
                           if flush_t - 300 <= t < flush_t], 90)
    post_ttft = percentile([f["ttft_ms"] for t, f in
                            fleet.log.of_kind("complete")
                            if flush_t <= t < flush_t + 120], 90)
    if pre_ttft is not None and post_ttft is not None \
            and post_ttft <= pre_ttft:
        v.append("flush produced no recompute-storm TTFT spike")
    if r["requests"]["completed"] < 0.98 * r["requests"]["arrived"]:
        v.append("fleet did not keep serving through the flush")
    if r["requests"]["dropped"]:
        v.append("flush dropped requests")
    return v


def _oscillate(seed: int, replicas: int = 6, duration_s: float = 900.0):
    """Anti-thrash: load oscillating across the scale-up boundary FASTER
    than the hysteresis window — the planner must hold, not flap."""
    # latency SLOs are deliberately loose: TTFT rides a 180s collector
    # window (a LAGGING indicator by design), so an oscillation test on
    # the hysteresis boundary drives the INSTANT signals — queue depth
    # and slot utilization — across their thresholds instead
    slo = ServiceLevelObjective(
        ttft_p90_ms=60000.0, itl_p90_ms=5000.0, max_queue_depth=2.0,
        min_decode_workers=replicas - 2, max_decode_workers=replicas + 6,
        slot_util_low=0.05)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        # breach must persist 6 consecutive 5s evaluations = 30s; the
        # 20s-period load breaches for only ~5-10s per crest before the
        # trough drains the backlog — hysteresis must hold through it
        planner_cfg=PlannerConfig(interval_s=5.0, cooldown_s=30.0,
                                  breach_cycles=6, status_interval_s=15.0),
        stats_interval_s=2.0, scrape_interval_s=1.0, drainout_s=300.0)
    wl = generate_workload(duration_s, seed, base_rps=0.3, peak_rps=5.0,
                           period_s=20.0, osl_base=48, osl_spread=96)
    return cfg, wl, (), duration_s


def _check_oscillate(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    c = r["planner"]["counters"]
    if c["evaluations"] < 100:
        v.append("planner barely evaluated")
    # the load must actually CROSS the scale-up boundary (instantaneous
    # fleet queue depth above the SLO threshold at some samples)...
    slo = fleet.cfg.slo
    peaks = sum(1 for _, f in fleet.log.of_kind("load_sample")
                if f["queue_depth"] > slo.max_queue_depth
                or f["slot_util"] > slo.slot_util_high)
    if peaks < 3:
        v.append("load never crossed the scale-up boundary — "
                 "the anti-thrash case was not exercised")
    # ...while breach-cycle hysteresis keeps the planner from flapping
    flaps = c["scale_up"] + c["drains_started"]
    if flaps > 1:
        v.append(f"planner flapped under oscillating load "
                 f"({flaps} actions)")
    if r["requests"]["dropped"]:
        v.append("oscillation dropped requests")
    return v


def _disagg_retune(seed: int, replicas: int = 8,
                   duration_s: float = 1000.0,
                   link_gbps: float = 10.0, link_rtt_s: float = 1e-3):
    slo = ServiceLevelObjective(
        ttft_p90_ms=1500.0, itl_p90_ms=500.0, max_queue_depth=2.0,
        min_decode_workers=replicas, max_decode_workers=replicas,
        # pin the prefill tier: this scenario proves the RETUNE lever,
        # so the (round-12) prefill-fleet actuator is held at its start
        # size rather than absorbing the backlog the retune should see
        min_prefill_workers=2, max_prefill_workers=2,
        max_local_prefill_length=512)
    cfg = FleetConfig(
        replicas=replicas, prefill_replicas=2, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo, link_gbps=link_gbps,
        link_rtt_s=link_rtt_s,
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=20.0,
                                  status_interval_s=10.0),
        stats_interval_s=2.0, scrape_interval_s=1.0, drainout_s=400.0)
    # long-prompt traffic: most prefills cross the 512-token threshold,
    # the 2-replica prefill tier backs up, the planner retunes UP; when
    # the queue clears under TTFT pressure it retunes back DOWN —
    # floored at the fleet's fetch-vs-recompute crossover
    wl = generate_workload(duration_s * 0.7, seed, base_rps=2.0,
                           peak_rps=6.0, isl_base=1024, isl_spread=2048,
                           agentic_frac=0.1, long_tail_frac=0.0,
                           osl_base=32, osl_spread=64)
    return cfg, wl, (), duration_s


def _prefill_storm(seed: int, replicas: int = 10,
                   duration_s: float = 1400.0):
    """Prefill-as-a-Service proving ground (ISSUE 12 rung (c)): a
    prefix-MISS surge — long fresh-session prompts with no reuse —
    drives the prefill queue while the decode tier stays comfortable;
    the planner must scale the PREFILL tier out (the new actuator, not
    the decode one or the retune) and late-window SLO must recover."""
    slo = ServiceLevelObjective(
        ttft_p90_ms=4000.0, itl_p90_ms=400.0, max_queue_depth=2.0,
        # decode pinned: the storm is a prefill-capacity problem
        min_decode_workers=replicas, max_decode_workers=replicas,
        min_prefill_workers=2, max_prefill_workers=12,
        max_local_prefill_length=256)
    cfg = FleetConfig(
        replicas=replicas, prefill_replicas=2, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        # retune_max == the threshold itself: the disagg-retune lever is
        # deliberately out of headroom, so only the prefill-fleet
        # actuator can absorb the storm
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=20.0,
                                  breach_cycles=3, scale_step=2,
                                  drain_timeout_s=120.0, drain_poll_s=0.5,
                                  status_interval_s=10.0,
                                  retune_max=256),
        stats_interval_s=2.0, scrape_interval_s=1.0,
        provision_delay_s=15.0, new_worker_profile="slow-start:20",
        drainout_s=600.0)
    # fresh long prompts (agentic_frac=0: every session is new, so the
    # prefix indexes miss) crossing the 256-token disagg threshold; the
    # surge quadruples arrivals for ~8 minutes
    wl = generate_workload(duration_s * 0.7, seed, base_rps=1.0,
                           peak_rps=1.6, burst_at=240.0, burst_len_s=480.0,
                           burst_factor=5.0, tenants=16,
                           agentic_frac=0.0, long_tail_frac=0.0,
                           isl_base=768, isl_spread=1024,
                           osl_base=32, osl_spread=64)
    return cfg, wl, (), duration_s


def _check_prefill_storm(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    c = r["planner"]["counters"]
    if r["requests"]["remote_prefills"] < 50:
        v.append("prefill queue barely exercised — storm never formed")
    if c.get("prefill_scale_up", 0) < 1:
        v.append("planner never scaled the prefill tier into the storm")
    if r["prefill_replicas"]["peak"] <= r["prefill_replicas"]["start"]:
        v.append("prefill tier did not grow under the surge")
    if c["scale_up"] != 0:
        v.append("decode tier scaled — the storm leaked out of the "
                 "prefill tier (decode is pinned by the SLO bounds)")
    if r["slo"]["late_attainment"] < 0.85:
        v.append(f"late-window TTFT attainment "
                 f"{r['slo']['late_attainment']} < 0.85 — scaling the "
                 f"prefill tier did not restore SLO")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} requests")
    return v


def _partition_brownout(seed: int, replicas: int = 12,
                        duration_s: float = 1400.0):
    """Chaos-hardening scenario (ISSUE 13): 3 replicas brown out at
    t=240 — serving 8× slower with FROZEN published stats (the router
    and planner see the stale healthy view) — and recover at t=700.
    The fleet must absorb the brownout without hanging or dropping:
    retries/queueing carry the slow window, the planner may scale into
    the pressure, and late-window SLO must recover once the brownout
    lifts."""
    slo = ServiceLevelObjective(
        ttft_p90_ms=5000.0, itl_p90_ms=600.0, max_queue_depth=3.0,
        min_decode_workers=replicas - 2, max_decode_workers=replicas + 8)
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=2.0, cooldown_s=20.0,
                                  breach_cycles=3, scale_step=2,
                                  drain_timeout_s=120.0, drain_poll_s=0.5,
                                  status_interval_s=10.0),
        stats_interval_s=2.0, scrape_interval_s=1.0,
        provision_delay_s=15.0, drainout_s=600.0)
    wl = generate_workload(duration_s * 0.7, seed, base_rps=2.0,
                           peak_rps=3.5, osl_base=48, osl_spread=96)
    faults = ((240.0, "brownout", _fault_brownout(3, 8.0)),
              (700.0, "brownout_recover", _fault_brownout_recover))
    return cfg, wl, faults, duration_s


def _check_partition_brownout(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if fleet.log.count("brownout") < 3:
        v.append("brownout fault never browned out 3 workers")
    if fleet.log.count("brownout_recover") < 3:
        v.append("browned-out workers never recovered")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} in-flight requests")
    if r["requests"]["completed"] != r["requests"]["arrived"]:
        v.append("not every request completed — something hung")
    # the brownout must actually BITE: TTFT p90 across the brownout
    # window above the pre-brownout window (slow-not-dead, not a no-op)
    from ..llm.slo import percentile
    pre = percentile([f["ttft_ms"] for t, f in
                      fleet.log.of_kind("complete") if t < 240.0], 90)
    mid = percentile([f["ttft_ms"] for t, f in
                      fleet.log.of_kind("complete")
                      if 260.0 <= t < 700.0], 90)
    if pre is not None and mid is not None and mid <= pre:
        v.append("brownout produced no TTFT degradation — "
                 "the fault was a no-op")
    if r["slo"]["late_attainment"] < 0.9:
        v.append(f"late-window TTFT attainment "
                 f"{r['slo']['late_attainment']} < 0.9 — SLO never "
                 f"recovered after the brownout lifted")
    return v


def _disk_pressure(seed: int, replicas: int = 8,
                   duration_s: float = 1200.0):
    """Chaos-hardening scenario (ISSUE 13): fleet-wide ENOSPC mid-spill
    at t=300 (every demote refused until t=700). Write-behind must SHED
    — cache blocks are lost, counted, and serving continues — with zero
    drops and late-window SLO recovered."""
    slo = ServiceLevelObjective(
        ttft_p90_ms=5000.0, itl_p90_ms=600.0, max_queue_depth=3.0,
        min_decode_workers=replicas, max_decode_workers=replicas + 4)
    cfg = FleetConfig(
        # small device tier + agentic reuse → steady demote pressure,
        # so the refused-writes window has real traffic to shed
        replicas=replicas, slots=4, kv_blocks=96, host_blocks=64,
        perf=_perf_small(), slo=slo,
        planner_cfg=PlannerConfig(interval_s=5.0, cooldown_s=30.0,
                                  status_interval_s=20.0),
        stats_interval_s=2.0, scrape_interval_s=1.0, drainout_s=600.0)
    wl = generate_workload(duration_s * 0.7, seed, base_rps=2.5,
                           peak_rps=5.0, tenants=4, agentic_frac=0.6,
                           osl_base=48, osl_spread=96)
    faults = ((300.0, "disk_pressure_on", _fault_disk_pressure(True)),
              (700.0, "disk_pressure_off", _fault_disk_pressure(False)))
    return cfg, wl, faults, duration_s


def _check_disk_pressure(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if fleet.log.count("disk_pressure") < 2:
        v.append("disk pressure fault never toggled on+off")
    if r["requests"]["shed_writes"] < 20:
        v.append(f"only {r['requests']['shed_writes']} writes shed — "
                 f"the pressure window never refused real spill traffic")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} requests — "
                 f"disk pressure must shed cache, not serving")
    if r["requests"]["completed"] != r["requests"]["arrived"]:
        v.append("not every request completed — something hung")
    if r["slo"]["late_attainment"] < 0.9:
        v.append(f"late-window TTFT attainment "
                 f"{r['slo']['late_attainment']} < 0.9")
    return v


NOISY_FLOOD_AT = 300.0
NOISY_FLOOD_LEN = 400.0


def _noisy_neighbor(seed: int, replicas: int = 10,
                    duration_s: float = 1200.0):
    """Multi-tenant fair-share proving ground (ISSUE 14,
    docs/multi_tenant.md): 8 Zipf-weighted tenants; at t=300 tenant t00
    FLOODS ~10× its organic rate for 400s. The fleet is PINNED (no
    scale-out escape hatch) so the only thing standing between the
    flood and everyone else is the tenant machinery: fair-share WDRR
    waiting queues throttle t00 to its weight share of service, and
    per-tenant KV quotas land its eviction storm on its OWN blocks.
    Victims must keep late-window SLO >= 0.9 and a flood-window prefix
    hit rate within 10% of their quiet baseline, with zero drops."""
    slo = ServiceLevelObjective(
        ttft_p90_ms=5000.0, itl_p90_ms=600.0, max_queue_depth=30.0,
        # pinned: fairness carries the storm, not the planner
        min_decode_workers=replicas, max_decode_workers=replicas)
    policies = {f"t{i:02d}": {"weight": 1.0, "kv_quota_blocks": 192}
                for i in range(8)}
    cfg = FleetConfig(
        replicas=replicas, slots=4, kv_blocks=512, host_blocks=256,
        perf=_perf_small(), slo=slo,
        tenant_policies=policies,
        planner_cfg=PlannerConfig(interval_s=5.0, cooldown_s=60.0,
                                  status_interval_s=20.0),
        stats_interval_s=2.0, scrape_interval_s=1.0, drainout_s=600.0)
    # agentic mix builds every tenant's warm prefix state BEFORE the
    # flood, so the quota story (the flood must not crater the victims'
    # hit rate) has a real baseline to protect
    wl = generate_workload(duration_s * 0.85, seed, base_rps=0.8,
                           peak_rps=1.2, tenants=8, zipf_a=0.6,
                           agentic_frac=0.6, long_tail_frac=0.0,
                           osl_base=48, osl_spread=96,
                           flood_tenant="t00", flood_at=NOISY_FLOOD_AT,
                           flood_len_s=NOISY_FLOOD_LEN, flood_factor=10.0)
    return cfg, wl, (), duration_s


def _check_noisy_neighbor(fleet: SimFleet, r: dict) -> List[str]:
    from ..llm.slo import percentile
    v = []
    f0, f1 = NOISY_FLOOD_AT, NOISY_FLOOD_AT + NOISY_FLOOD_LEN
    arrivals = {}
    for _t, f in fleet.log.of_kind("arrive"):
        arrivals[f["tenant"]] = arrivals.get(f["tenant"], 0) + 1
    flood_n = arrivals.get("t00", 0)
    victim_n = sum(n for t, n in arrivals.items() if t != "t00")
    if flood_n < 2 * victim_n:
        v.append(f"flood never formed (t00 sent {flood_n} vs "
                 f"{victim_n} victim arrivals)")
    if r["requests"]["dropped"]:
        v.append(f"dropped {r['requests']['dropped']} requests")
    if r["requests"]["completed"] != r["requests"]["arrived"]:
        v.append("not every request completed — something starved")
    # victims' late-window SLO holds despite the flood
    cut = fleet.clock.now * 0.75
    late_victims = [f["ttft_ms"] for t, f in fleet.log.of_kind("complete")
                    if t >= cut and f["tenant"] != "t00"]
    slo = fleet.cfg.slo
    if late_victims:
        att = (sum(1 for x in late_victims if x <= slo.ttft_p90_ms)
               / len(late_victims))
        if att < 0.9:
            v.append(f"victim late-window TTFT attainment {att:.3f} < 0.9")
    else:
        v.append("no victim traffic in the late window")
    # the throttle: inside the flood window the flooder queues behind
    # its own backlog — its TTFT p90 must sit well above the victims'
    flood_ttft = percentile([f["ttft_ms"] for t, f in
                             fleet.log.of_kind("complete")
                             if f0 + 60 <= t < f1 and f["tenant"] == "t00"],
                            90)
    victim_ttft = percentile([f["ttft_ms"] for t, f in
                              fleet.log.of_kind("complete")
                              if f0 + 60 <= t < f1
                              and f["tenant"] != "t00"], 90)
    if flood_ttft is None or victim_ttft is None:
        v.append("flood window saw no completions on one side")
    elif flood_ttft < 1.5 * victim_ttft:
        v.append(f"flooder was not throttled: its in-flood TTFT p90 "
                 f"{flood_ttft:.0f}ms vs victims' {victim_ttft:.0f}ms")
    # quota isolation: victims' prefix hit rate in the flood window
    # stays within 10% of their pre-flood baseline
    def victim_hit(lo, hi):
        fr = [f["hit"] / max(f["blocks"], 1)
              for t, f in fleet.log.of_kind("route")
              if lo <= t < hi and f.get("tenant") not in (None, "t00")]
        return sum(fr) / len(fr) if fr else None
    pre = victim_hit(f0 - 200, f0)
    mid = victim_hit(f0 + 60, f1)
    if pre is None or mid is None:
        v.append("no victim routing around the flood window")
    else:
        if pre < 0.15:
            v.append(f"victims' prefix reuse never warmed up "
                     f"(pre-flood hit {pre:.2f})")
        if mid < 0.9 * pre:
            v.append(f"flood cratered victims' hit rate: "
                     f"{pre:.3f} → {mid:.3f} (>10% drop)")
    # the quota machinery must actually engage: the flooder's over-
    # quota blocks took preferred evictions
    if r["requests"].get("tenant_evictions", 0) < 10:
        v.append("tenant-quota eviction preference never engaged")
    if victim_n == 0:
        v.append("no victim arrivals at all (workload misconfigured)")
    return v


def _check_disagg_retune(fleet: SimFleet, r: dict) -> List[str]:
    v = []
    if r["requests"]["remote_prefills"] < 10:
        v.append("disagg path barely exercised")
    if r["planner"]["counters"]["retunes"] < 1:
        v.append("planner never retuned the disagg threshold")
    if not fleet.log.count("retune"):
        v.append("retune never reached the DisaggregatedRouter watch key")
    if r["requests"]["dropped"]:
        v.append("retune scenario dropped requests")
    return v


SCENARIOS: Dict[str, Scenario] = {
    "baseline_hour": Scenario(
        "baseline_hour",
        "200 replicas x 1 simulated hour of bursty diurnal mixed traffic "
        "with the real planner/router/retune in the loop",
        _baseline_hour, _check_baseline),
    "scale_storm": Scenario(
        "scale_storm",
        "sudden 6x burst; the planner must scale out and restore SLO",
        _scale_storm, _check_scale_storm),
    "drain_storm": Scenario(
        "drain_storm",
        "load collapse + ops mass-drain; zero dropped in-flight",
        _drain_storm, _check_drain_storm),
    "crash_cascade": Scenario(
        "crash_cascade",
        "staggered replica crashes; retries absorb, planner replaces",
        _crash_cascade, _check_crash_cascade),
    "prefix_flush": Scenario(
        "prefix_flush",
        "fleet-wide prefix-cache flush; hit rate craters then recovers",
        _prefix_flush, _check_prefix_flush),
    "oscillate": Scenario(
        "oscillate",
        "load oscillating across the scale boundary; planner must not flap",
        _oscillate, _check_oscillate),
    "disagg_retune": Scenario(
        "disagg_retune",
        "prefill-queue backlog drives the disagg threshold retune, "
        "floored at the fleet fetch-vs-recompute crossover",
        _disagg_retune, _check_disagg_retune),
    "prefill_storm": Scenario(
        "prefill_storm",
        "prefix-miss surge backs up the prefill queue; the planner "
        "scales the prefill tier out and SLO recovers",
        _prefill_storm, _check_prefill_storm),
    "partition_brownout": Scenario(
        "partition_brownout",
        "slow-not-dead replicas with frozen (partitioned) stats; zero "
        "hangs, zero drops, SLO recovers after the brownout lifts",
        _partition_brownout, _check_partition_brownout),
    "disk_pressure": Scenario(
        "disk_pressure",
        "fleet-wide ENOSPC mid-spill; write-behind sheds (counted), "
        "serving continues, SLO holds",
        _disk_pressure, _check_disk_pressure),
    "noisy_neighbor": Scenario(
        "noisy_neighbor",
        "one tenant floods 10x against a pinned fleet; fair-share WDRR "
        "throttles it to its share and KV quotas keep victims' hit "
        "rate intact (llm/tenancy.py; docs/multi_tenant.md)",
        _noisy_neighbor, _check_noisy_neighbor),
}


def _late_attainment(fleet: SimFleet, slo: ServiceLevelObjective) -> float:
    """TTFT attainment over the last quarter of the run (the post-
    stabilization window storm checks assert on)."""
    cut = fleet.clock.now * 0.75
    late = [f["ttft_ms"] for t, f in fleet.log.of_kind("complete")
            if t >= cut]
    if not late:
        return 1.0
    return sum(1 for x in late if x <= slo.ttft_p90_ms) / len(late)


def run_scenario(name: str, seed: int = 0, **overrides) -> dict:
    """Run one scenario to completion under virtual time; returns the
    report dict (report["violations"] lists failed expectations)."""
    sc = SCENARIOS[name]
    cfg, wl, faults, run_s = sc.build(seed, **overrides)

    async def main():
        fleet = await SimFleet(cfg, seed=seed).start()
        t_wall = REAL_PERF_COUNTER()
        await fleet.run(wl, faults=faults, duration_s=run_s)
        report = fleet.report(wall_s=REAL_PERF_COUNTER() - t_wall)
        report["scenario"] = name
        report["slo"]["late_attainment"] = round(
            _late_attainment(fleet, cfg.slo), 4)
        report["violations"] = sc.check(fleet, report)
        await fleet.stop()
        return report

    return run_simulation(main)


def check_report(report: dict) -> None:
    """Raise AssertionError listing every violated expectation."""
    if report.get("violations"):
        raise AssertionError(
            f"scenario {report.get('scenario')} violated: "
            + "; ".join(report["violations"]))
