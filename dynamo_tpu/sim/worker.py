"""Simulated replica: a discrete-event model of one serving worker.

The engine model is deliberately coarse — the control plane under test
(planner, router, disagg retune) consumes QUEUE/SLOT/KV/LATENCY signals,
not kernel microstructure — but every timing input is measured:

- prefill runs serially at the perf model's measured token rate
  (sim/models.py, BENCH_LOCAL.jsonl fits), scaled by the behavior
  profile's slow-start/latency factors;
- decode is continuous batching as processor sharing: all active
  sequences advance one token per step, and the step time grows with
  batch size along the measured device-step fit (+ modeled TP/PP
  collective cost from parallel/ici_model);
- fabric fetches and disagg KV handoffs are priced by the REAL
  ``AdmissionGate.modeled_fetch_s`` over the fleet's ``PeerLinkTable``
  links — the same arithmetic a live worker's gate runs;
- the worker's measured prefill rate feeds a REAL
  ``PrefillRateEstimator`` (llm/kv/fabric.py), so a slow-starting
  replica's compile-inflated early samples are age-weighted out of the
  admission pricing exactly as on hardware.

Workers register REAL discovery + stats records in the fleet's
MemoryKvStore, so the unmodified production ``Client`` — and therefore
the unmodified ``Planner`` — watches, scrapes, and drains them through
the production code paths (drain key → draining re-announce →
drain-to-exit).
"""

from __future__ import annotations

import asyncio
import json
from collections import OrderedDict, deque
from typing import Deque, List, Optional

from ..llm.kv.fabric import AdmissionGate, PrefillRateEstimator
from ..llm.kv_router.protocols import (ForwardPassMetrics, KvRemovedEvent,
                                       KvStoredEvent, RouterEvent)
from ..runtime.component import ComponentEndpointInfo
from .profiles import BehaviorProfile

__all__ = ["SimRequest", "SimWorker"]

_EPS = 1e-9
_MIN_DT = 1e-6


class SimRequest:
    """One request in flight through the simulated fleet."""

    __slots__ = ("spec", "hashes", "new_tokens", "fetch_s", "fetched_blocks",
                 "hit_blocks", "kind", "arrive_t", "first_t", "worker_id",
                 "retries", "target_wid")

    def __init__(self, spec, hashes: List[int], new_tokens: int,
                 fetch_s: float, fetched_blocks: int, hit_blocks: int,
                 arrive_t: float, kind: str = "decode",
                 target_wid: Optional[int] = None):
        self.spec = spec
        self.hashes = hashes
        self.new_tokens = int(new_tokens)
        self.fetch_s = float(fetch_s)
        self.fetched_blocks = int(fetched_blocks)
        self.hit_blocks = int(hit_blocks)
        self.kind = kind                 # "decode" | "prefill" (disagg leg)
        self.arrive_t = arrive_t
        self.first_t: Optional[float] = None
        self.worker_id: Optional[int] = None
        self.retries = 0
        self.target_wid = target_wid     # disagg: the decode worker


class _Prefill:
    __slots__ = ("req", "fetch_left", "tokens_left", "started_t")

    def __init__(self, req: SimRequest, now: float):
        self.req = req
        self.fetch_left = req.fetch_s
        self.tokens_left = float(max(req.new_tokens, 0))
        self.started_t = now


class _Decode:
    __slots__ = ("req", "tokens_left")

    def __init__(self, req: SimRequest):
        self.req = req
        self.tokens_left = float(max(req.spec.osl - 1, 0))


class SimWorker:
    def __init__(self, fleet, worker_id: int, *, perf, profile: str = "",
                 slots: int = 4, kv_blocks: int = 512, host_blocks: int = 256,
                 block_size: int = 32, prefill_only: bool = False):
        self.fleet = fleet
        self.worker_id = worker_id
        self.perf = perf
        self.profile = (profile if isinstance(profile, BehaviorProfile)
                        else BehaviorProfile.parse(profile))
        self.slots = slots
        self.kv_blocks = kv_blocks
        self.host_blocks = host_blocks
        self.block_size = block_size
        self.prefill_only = prefill_only

        # multi-tenant serving (llm/tenancy.py — the REAL policy
        # machinery, not a sim reimplementation): with fleet tenancy on,
        # the waiting queue drains in weighted-deficit-round-robin order
        # with QoS classes (a flooding tenant's backlog sits in ITS
        # queue) and a per-worker TenantBlockLedger quota-prefers the
        # over-quota tenant's blocks at eviction time.
        self.tenant_table = getattr(fleet, "tenant_table", None)
        self.ledger = None
        if self.tenant_table is not None:
            from ..llm.tenancy import FairShareQueue, TenantBlockLedger
            self.waiting = FairShareQueue(self.tenant_table)
            self.ledger = TenantBlockLedger(self.tenant_table)
        else:
            self.waiting: Deque[SimRequest] = deque()
        self.prefill: Optional[_Prefill] = None
        self.decoding: List[_Decode] = []
        # device-tier LRU of resident block seq-hashes; evictions demote
        # to a host-tier LRU (re-announced tier="host"), whose own
        # evictions are removed-announced — the tier ladder the router's
        # weighted scoring consumes, and the eviction-storm substrate.
        self.resident: "OrderedDict[int, None]" = OrderedDict()
        self.host_resident: "OrderedDict[int, None]" = OrderedDict()

        self.estimator = PrefillRateEstimator()
        self.gate = AdmissionGate(
            bytes_per_block=perf.kv_bytes_per_block, block_size=block_size,
            prefill_tok_per_s=self.estimator.rate,
            mode=fleet.cfg.admission)
        # the router-facing metrics object, mutated in place on scrape
        self.metrics = ForwardPassMetrics(request_total_slots=slots,
                                          kv_total_blocks=kv_blocks)

        self.started_at = 0.0
        self.draining = False
        self.dead = False
        self.exited_clean = False
        self.prefills_done = 0
        self.decodes_done = 0
        # chaos-scenario state (docs/chaos.md): a PARTITIONED worker
        # keeps serving but its stats/scrape view freezes (the
        # kvstore-partition shape — routers/planner see stale numbers);
        # a worker under disk pressure SHEDS demote writes instead of
        # landing them in the colder tier (the ENOSPC write-behind
        # shape), counted in shed_writes.
        self.partitioned = False
        self.frozen_metrics: Optional[ForwardPassMetrics] = None
        self.disk_full = False
        self.shed_writes = 0
        self._timer: Optional[asyncio.TimerHandle] = None
        self._crash_timer: Optional[asyncio.TimerHandle] = None
        self._last_t = 0.0

    # ------------------------------------------------------------ lifecycle
    @property
    def link(self):
        return self.fleet.links.get(self.worker_id)

    @property
    def endpoint(self):
        return (self.fleet.prefill_endpoint if self.prefill_only
                else self.fleet.endpoint)

    async def register(self) -> None:
        """Write the REAL discovery + stats records the production Client
        watches/scrapes."""
        loop = asyncio.get_running_loop()
        self.started_at = self._last_t = loop.time()
        ep = self.endpoint
        info = ComponentEndpointInfo(
            subject=ep.subject(self.worker_id), worker_id=self.worker_id,
            component=ep.component, endpoint=ep.name,
            namespace=ep.namespace, draining=False)
        store = self.fleet.runtime.store
        await store.kv_put(ep.discovery_key(self.worker_id), info.to_json())
        await store.kv_put(ep.stats_key(self.worker_id), self.stats_json())
        if self.profile.crash_at_s > 0:
            self._crash_timer = loop.call_later(self.profile.crash_at_s,
                                                self.crash)
        if self.profile.slow_start_s > 0:
            # ramp-end boundary: re-evaluate event times at full speed
            loop.call_later(self.profile.slow_start_s, self._fire)

    # -------------------------------------------------------------- engine
    @property
    def active_slots(self) -> int:
        return len(self.decoding) + (1 if self.prefill is not None else 0)

    @property
    def idle(self) -> bool:
        return (not self.waiting and self.prefill is None
                and not self.decoding)

    def submit(self, req: SimRequest) -> None:
        if self.dead:
            self.fleet.on_requests_lost([req])
            return
        req.worker_id = self.worker_id
        if self.tenant_table is not None:
            # fair-share order (WDRR + QoS): cost = the request's new
            # prefill blocks, so a flooding tenant's LONG prompts spend
            # its deficit faster, exactly like its flood rate does
            self.waiting.push(
                req, tenant=req.spec.tenant,
                cost=max(req.new_tokens / self.block_size, 1.0))
        else:
            self.waiting.append(req)
        self._fire()

    def _speed(self, now: float) -> float:
        return self.profile.speed_factor(now - self.started_at)

    def _advance(self, now: float) -> None:
        dt = now - self._last_t
        self._last_t = now
        if dt <= 0:
            return
        speed = self._speed(now)
        if self.prefill is not None:
            p = self.prefill
            left = dt
            if p.fetch_left > 0:
                used = min(p.fetch_left, left)
                p.fetch_left -= used
                left -= used
            if left > 0:
                p.tokens_left -= left * self.perf.prefill_tok_per_s * speed
        if self.decoding:
            step = self.perf.step_time_s(len(self.decoding)) / speed
            adv = dt / step
            for d in self.decoding:
                d.tokens_left -= adv

    def _fire(self) -> None:
        if self.dead:
            return
        loop = asyncio.get_running_loop()
        now = loop.time()
        self._advance(now)
        if (self.prefill is not None and self.prefill.fetch_left <= _EPS
                and self.prefill.tokens_left <= _EPS):
            p, self.prefill = self.prefill, None
            self._prefill_done(p, now)
        if self.decoding:
            done = [d for d in self.decoding if d.tokens_left <= _EPS]
            if done:
                self.decoding = [d for d in self.decoding
                                 if d.tokens_left > _EPS]
                for d in done:
                    self.decodes_done += 1
                    self.fleet.on_complete(d.req, self)
        while (self.prefill is None and self.waiting
               and (self.prefill_only
                    or len(self.decoding) < self.slots)):
            req = self.waiting.popleft()
            self.prefill = _Prefill(req, now)
            if self.prefill.fetch_left <= _EPS \
                    and self.prefill.tokens_left <= _EPS:
                p, self.prefill = self.prefill, None
                self._prefill_done(p, now)
            else:
                break
        if self.draining and self.idle and not self.dead:
            self.exit(clean=True)
            return
        self._reschedule(now)

    def _prefill_done(self, p: _Prefill, now: float) -> None:
        req = p.req
        self.prefills_done += 1
        wall = now - p.started_t
        if req.new_tokens > 0 and wall > 0:
            # the REAL age-weighted estimator: slow-start (compile-
            # shaped) samples are excluded/decayed exactly as on a
            # young live engine
            self.estimator.observe(req.new_tokens, wall)
        self._register_blocks(req.hashes, tenant=req.spec.tenant)
        if req.kind == "prefill":
            self.fleet.on_prefill_handoff(req, self)
            return
        req.first_t = now
        self.fleet.on_first_token(req, self)
        if req.spec.osl <= 1:
            self.fleet.on_complete(req, self)
        else:
            self.decoding.append(_Decode(req))

    def _next_dt(self, now: float) -> Optional[float]:
        speed = self._speed(now)
        cands = []
        if self.prefill is not None:
            p = self.prefill
            cands.append(max(p.fetch_left, 0.0)
                         + max(p.tokens_left, 0.0)
                         / (self.perf.prefill_tok_per_s * speed))
        if self.decoding:
            step = self.perf.step_time_s(len(self.decoding)) / speed
            rem = min(d.tokens_left for d in self.decoding)
            cands.append(max(rem, 0.0) * step)
        if not cands:
            return None
        dt = min(cands)
        if self.profile.slow_start_s > 0:
            ramp_left = (self.started_at + self.profile.slow_start_s) - now
            if 0 < ramp_left < dt:
                dt = ramp_left
        return max(dt, _MIN_DT)

    def _reschedule(self, now: float) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        dt = self._next_dt(now)
        if dt is not None:
            self._timer = asyncio.get_running_loop().call_at(
                now + dt, self._fire)

    # ----------------------------------------------------------- KV model
    def _register_blocks(self, hashes: List[int],
                         tenant: Optional[str] = None) -> None:
        """Device-tier residency with chained stored-announces: the
        longest already-resident prefix is touched (LRU), the suffix is
        announced tier=device off its parent — feeding the REAL radix
        indexer the router queries. With tenancy on, new blocks are
        noted in the worker's ledger and eviction victims come from an
        OVER-QUOTA tenant first (bounded LRU-front scan — the device
        pool's quota preference, llm/tenancy.py)."""
        resident = self.resident
        i = 0
        for h in hashes:
            if h in resident:
                resident.move_to_end(h)
                i += 1
            else:
                break
        new = hashes[i:]
        if new:
            parent = hashes[i - 1] if i > 0 else None
            for h in new:
                resident[h] = None
                self.host_resident.pop(h, None)
                if self.ledger is not None:
                    self.ledger.forget(h, "host")
                    self.ledger.note(h, tenant, "device")
            self.fleet.apply_kv_event(RouterEvent(
                worker_id=self.worker_id,
                stored=KvStoredEvent(parent_hash=parent, block_hashes=new)))
        evicted = []
        while len(resident) > self.kv_blocks:
            victim = None
            if self.ledger is not None:
                for j, h in enumerate(resident):
                    if j >= 64:
                        break
                    if self.ledger.is_over_quota_hash(h, "device"):
                        victim = h
                        break
            if victim is None:
                victim, _ = resident.popitem(last=False)
            else:
                resident.pop(victim)
                self.fleet.counters["tenant_evictions"] += 1
            if self.ledger is not None:
                self.ledger.forget(victim, "device")
            evicted.append(victim)
        if evicted:
            self._demote(evicted)

    # ----------------------------------------------------- chaos controls
    def set_brownout(self, latency_factor: float,
                     partition: bool = True) -> None:
        """Slow-not-dead: inflate every service time ``latency_factor``×
        and (optionally) freeze the worker's published stats — the
        router/planner keep seeing the pre-brownout numbers, exactly the
        stale-view a kvstore partition produces."""
        self.profile = BehaviorProfile(
            name=f"brownout:{latency_factor:g}",
            latency_factor=latency_factor)
        self.partitioned = partition
        if partition and self.frozen_metrics is None:
            self.frozen_metrics = ForwardPassMetrics.from_dict(
                self.refresh_metrics().to_dict())
        if not partition:
            self.frozen_metrics = None
        self._fire()                     # reschedule at the new speed

    def clear_brownout(self) -> None:
        self.profile = BehaviorProfile(name="steady")
        self.partitioned = False
        self.frozen_metrics = None
        self._fire()

    def scraped_metrics(self) -> ForwardPassMetrics:
        """What the router/planner see: live numbers, or the frozen
        pre-partition snapshot while the stats plane is dark."""
        if self.partitioned and self.frozen_metrics is not None:
            return self.frozen_metrics
        return self.refresh_metrics()

    def _demote(self, hashes: List[int]) -> None:
        """Device eviction → host-tier demote announce; host overflow →
        removed announce (the router's tier-weighted view tracks both).
        Under disk pressure (``disk_full``) the demote is SHED: the
        blocks leave the ladder immediately (removed announce) and the
        shed is counted — the sim analog of the spill pump's
        ENOSPC-shedding (diskstore.DiskSpillEngine.shed_writes_total)."""
        if self.disk_full:
            self.shed_writes += len(hashes)
            self.fleet.on_shed_writes(self, len(hashes))
            self.fleet.apply_kv_event(RouterEvent(
                worker_id=self.worker_id,
                removed=KvRemovedEvent(block_hashes=list(hashes))))
            return
        host = self.host_resident
        for h in hashes:
            host[h] = None
            if self.ledger is not None:
                self.ledger.note(h, None, "host")   # owner from ledger memory
        self.fleet.apply_kv_event(RouterEvent(
            worker_id=self.worker_id,
            stored=KvStoredEvent(parent_hash=None, block_hashes=hashes,
                                 tier="host")))
        removed = []
        while len(host) > self.host_blocks:
            victim = None
            if self.ledger is not None:
                for j, h in enumerate(host):
                    if j >= 64:
                        break
                    if self.ledger.is_over_quota_hash(h, "host"):
                        victim = h
                        break
            if victim is None:
                victim, _ = host.popitem(last=False)
            else:
                host.pop(victim)
                self.fleet.counters["tenant_evictions"] += 1
            if self.ledger is not None:
                self.ledger.forget(victim, "host")
            removed.append(victim)
        if removed:
            self.fleet.apply_kv_event(RouterEvent(
                worker_id=self.worker_id,
                removed=KvRemovedEvent(block_hashes=removed)))

    def flush_kv(self) -> int:
        """The fleet-wide prefix-cache-flush fault: drop every resident
        block and announce the removals (an eviction storm for the
        router index)."""
        hashes = list(self.resident) + list(self.host_resident)
        if self.ledger is not None:
            for h in self.resident:
                self.ledger.forget(h, "device")
            for h in self.host_resident:
                self.ledger.forget(h, "host")
        self.resident.clear()
        self.host_resident.clear()
        if hashes:
            self.fleet.apply_kv_event(RouterEvent(
                worker_id=self.worker_id,
                removed=KvRemovedEvent(block_hashes=hashes)))
        return len(hashes)

    # -------------------------------------------------------------- stats
    def refresh_metrics(self) -> ForwardPassMetrics:
        m = self.metrics
        m.request_active_slots = self.active_slots
        m.request_total_slots = self.slots
        m.num_requests_waiting = len(self.waiting)
        # ACTIVE usage = blocks pinned by in-flight requests (the
        # planner's kv_util pressure signal and the scheduler's load
        # metric) — NOT the resident cache, which like any LRU sits at
        # capacity forever once warm
        pinned = sum(len(d.req.hashes) for d in self.decoding)
        if self.prefill is not None:
            pinned += len(self.prefill.req.hashes)
        m.kv_active_blocks = pinned
        m.kv_total_blocks = self.kv_blocks
        m.gpu_cache_usage_perc = min(pinned / max(self.kv_blocks, 1), 1.0)
        link = self.link
        m.remote_link_gbps = link.gbps
        m.remote_link_rtt_s = link.rtt_s
        m.kv_bytes_per_block = self.perf.kv_bytes_per_block
        m.kv_block_size = self.block_size
        m.prefill_tok_per_s = self.estimator.rate()
        m.remote_admission_rejects_total = self.gate.rejects_total
        if self.fleet.cfg.stream_layers > 0:
            # streaming handoff plane on: publish the pipeline depth so
            # the REAL scoring path (network_adjusted_overlap /
            # crossover_tokens) prices this worker's fetches overlapped
            m.disagg_stream_layers = self.fleet.cfg.stream_layers
        if self.ledger is not None:
            # per-tenant residency (the nv_llm_tenant_kv_blocks shape);
            # admission/throttle counters live fleet-side in the sim
            m.tenant_stats = {
                t: {"admitted": 0, "throttled": 0,
                    "kv_blocks": sum(tiers.values()), "hit_rate": 0.0}
                for t, tiers in sorted(self.ledger.snapshot().items())}
        return m

    def stats_json(self) -> bytes:
        return json.dumps(self.refresh_metrics().to_dict()).encode()

    # --------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        if self.dead or self.draining:
            return
        if self.profile.drain_ignore:
            self.fleet.log_event("drain_ignored", worker=self.worker_id)
            return
        self.draining = True
        self.fleet.on_drain_begin(self)
        ep = self.endpoint
        info = ComponentEndpointInfo(
            subject=ep.subject(self.worker_id), worker_id=self.worker_id,
            component=ep.component, endpoint=ep.name,
            namespace=ep.namespace, draining=True)
        self.fleet.spawn(self.fleet.runtime.store.kv_put(
            ep.discovery_key(self.worker_id), info.to_json()))
        if self.idle:
            self.exit(clean=True)

    # ---------------------------------------------------------------- exit
    def _cancel_timers(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._crash_timer is not None:
            self._crash_timer.cancel()
            self._crash_timer = None

    def exit(self, clean: bool) -> None:
        """Drain-to-exit (clean) or planner force-retire after a drain
        timeout (in-flight work is CUT — reported as lost)."""
        if self.dead:
            return
        self.dead = True
        self.exited_clean = clean
        self._cancel_timers()
        cut = ([p.req for p in ([self.prefill] if self.prefill else [])]
               + [d.req for d in self.decoding] + list(self.waiting))
        self.prefill = None
        self.decoding = []
        self.waiting.clear()
        self.fleet.on_worker_exit(self, clean=clean and not cut)
        if cut:
            self.fleet.on_requests_lost(cut)

    def crash(self) -> None:
        """The crash-at-T fault: in-flight requests lost, discovery entry
        gone (the router/planner see a vanished instance)."""
        if self.dead:
            return
        self.dead = True
        self._cancel_timers()
        lost = ([p.req for p in ([self.prefill] if self.prefill else [])]
                + [d.req for d in self.decoding] + list(self.waiting))
        self.prefill = None
        self.decoding = []
        self.waiting.clear()
        self.fleet.on_worker_crash(self)
        if lost:
            self.fleet.on_requests_lost(lost)
