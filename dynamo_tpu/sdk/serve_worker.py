"""Per-process service entry: resolve deps, run on-start hooks, serve
endpoints.

Reference: cli/serve_dynamo.py:44-190 — the per-watcher worker the circus
supervisor launches: ``@dynamo_worker`` builds the DistributedRuntime,
``component.create_service()``, binds the class instance, runs
``@async_on_start`` hooks, then blocks in ``serve_endpoint``."""

from __future__ import annotations

import argparse
import asyncio
import importlib
import logging
from typing import Any, AsyncIterator

from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.engine import (AsyncEngine, ManyOut, ResponseStream, SingleIn)
from .client import DependencyClient
from .config import ServiceConfig
from .service import DynamoService

logger = logging.getLogger("dynamo_tpu.sdk.worker")

__all__ = ["serve_service", "resolve_service"]


class _EndpointMethodEngine(AsyncEngine):
    """Adapts a bound async-generator endpoint method to AsyncEngine."""

    def __init__(self, fn):
        self.fn = fn

    async def generate(self, request: SingleIn) -> ManyOut:
        gen = self.fn(request.data)
        if hasattr(gen, "__aiter__"):
            stream = gen
        else:
            # plain coroutine → single-item stream
            async def one() -> AsyncIterator[Any]:
                yield await gen
            stream = one()
        return ResponseStream(stream, request.ctx)


def resolve_service(target: str) -> DynamoService:
    """``pkg.module:Attr`` → the DynamoService object."""
    mod_name, _, attr = target.partition(":")
    if not attr:
        raise SystemExit(f"service target must be module:Attr, got {target!r}")
    mod = importlib.import_module(mod_name)
    svc = getattr(mod, attr)
    if not isinstance(svc, DynamoService):
        raise SystemExit(f"{target} is not a @service")
    return svc


def find_in_graph(entry: DynamoService, name: str) -> DynamoService:
    for svc in entry.graph():
        if svc.name == name:
            return svc
    raise SystemExit(f"service {name!r} not reachable from {entry.name}")


async def serve_service(svc: DynamoService, runtime: DistributedRuntime
                        ) -> Any:
    """Bind + serve one service instance. Returns the instance (the caller
    owns the serve-forever wait)."""
    instance = svc.instantiate()
    # config injection (DYNAMO_SERVICE_CONFIG → instance.config) and the
    # runtime handle (the reference's @dynamo_worker passes the
    # DistributedRuntime into the service, cli/serve_dynamo.py:61-190) —
    # on-start hooks need it for KV event publishers, prefill queues, etc.
    instance.config = ServiceConfig.get_instance().for_service(svc.name)
    instance.runtime = runtime
    # dependency resolution
    for attr, dep in svc.dependencies.items():
        setattr(instance, attr,
                await DependencyClient.connect(runtime, dep.on))
    # on-start hooks (reference async_on_start: engine boot, metadata
    # publication, etc.)
    for hook in svc.on_start_hooks:
        await getattr(instance, hook)()
    # serve every endpoint
    for ep_name, attr in svc.endpoints.items():
        endpoint = Endpoint(runtime, svc.namespace, svc.name, ep_name)
        stats = getattr(instance, "stats_handler", None)
        await endpoint.serve(_EndpointMethodEngine(getattr(instance, attr)),
                             stats_handler=stats)
        logger.info("%s serving %s", svc.name, endpoint.path)
    return instance


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-serve-worker")
    p.add_argument("--target", required=True, help="graph module:Attr entry")
    p.add_argument("--service-name", required=True)
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)
    entry = resolve_service(args.target)
    svc = find_in_graph(entry, args.service_name)
    runtime = await DistributedRuntime.connect(args.runtime_server)
    stop = asyncio.Event()
    drained = asyncio.Event()
    runtime.on_lease_lost = stop.set
    try:
        await serve_service(svc, runtime)
        # drain-to-exit (docs/planner.md): once EVERY endpoint this
        # process serves is draining and idle, exit cleanly (rc=0) — the
        # supervisor reaps a clean exit as retirement, not a crash

        def maybe_drained() -> None:
            if runtime._servers and all(s.draining and s.idle
                                        for s in runtime._servers):
                drained.set()

        for srv in runtime._servers:
            srv.on_drained = maybe_drained
        stop_t = asyncio.ensure_future(stop.wait())
        drain_t = asyncio.ensure_future(drained.wait())
        done, pending = await asyncio.wait(
            [stop_t, drain_t], return_when=asyncio.FIRST_COMPLETED)
        for t in pending:
            t.cancel()
        if drain_t in done:
            logger.info("all endpoints drained; retiring")
        else:
            # rc=1: a lost lease is a failure, not a retirement — the
            # supervisor must restart us (rc=0 is reserved for drain)
            logger.error("lease lost; exiting")
            raise SystemExit(1)
    finally:
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
