"""Service graph primitives.

Reference mapping (deploy/dynamo/sdk/src/dynamo/sdk/lib/):
- ``@service`` → DynamoService wrapper (service.py:30-241)
- ``@dynamo_endpoint`` → marks async-generator endpoint methods
  (decorators.py:26-100)
- ``@async_on_start`` → post-init hooks run before serving
- ``depends(Other)`` → typed client attribute resolved at serve time
  (dependency.py)
- ``A.link(B)`` → deployment edge; the serve CLI walks deps ∪ links from
  the entry service to decide what to launch (LinkedServices pruning)
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Dict, List, Optional

__all__ = ["service", "dynamo_endpoint", "async_on_start", "depends",
           "Depends", "DynamoService"]


def dynamo_endpoint(name: Optional[str] = None):
    """Mark an async-generator method as a served endpoint."""

    def wrap(fn):
        fn.__dynamo_endpoint__ = name or fn.__name__
        return fn

    # bare usage: @dynamo_endpoint without parens
    if callable(name):
        fn, name = name, None
        return wrap(fn)
    return wrap


def async_on_start(fn):
    """Mark an async method to run after dependency resolution, before
    serving endpoints."""
    fn.__dynamo_on_start__ = True
    return fn


class Depends:
    """Class-attribute placeholder for a client to another service; the
    serve runtime replaces it with a live ``DependencyClient``."""

    def __init__(self, on: "DynamoService"):
        if not isinstance(on, DynamoService):
            raise TypeError("depends() takes a @service-decorated class")
        self.on = on

    def __repr__(self) -> str:
        return f"depends({self.on.name})"


def depends(on: "DynamoService") -> Depends:
    return Depends(on)


@dataclasses.dataclass
class Resources:
    tpu: int = 0
    cpu: Optional[str] = None
    memory: Optional[str] = None

    @staticmethod
    def tpu_count(res: dict) -> int:
        """Chip count from a resources dict, accepting the reference's
        ``gpu`` key as an alias (lib/service.py resources config)."""
        return int(res.get("tpu", res.get("gpu", 0)) or 0)


class DynamoService:
    """The object a ``@service`` class becomes (the reference subclasses
    bentoml.Service; ours is standalone)."""

    def __init__(self, cls: type, name: Optional[str] = None,
                 namespace: str = "dynamo",
                 resources: Optional[dict] = None,
                 dynamo: Optional[dict] = None):
        self.inner = cls
        cfg = dynamo or {}
        self.enabled = bool(cfg.get("enabled", True))
        self.name = name or cfg.get("name") or cls.__name__
        self.namespace = cfg.get("namespace", namespace)
        res = resources or {}
        self.resources = Resources(
            tpu=Resources.tpu_count(res),
            cpu=res.get("cpu"), memory=res.get("memory"))
        self.endpoints: Dict[str, str] = {}      # endpoint name → attr name
        self.on_start_hooks: List[str] = []
        self.dependencies: Dict[str, Depends] = {}
        for attr, val in list(vars(cls).items()):
            if isinstance(val, Depends):
                self.dependencies[attr] = val
            elif callable(val) and hasattr(val, "__dynamo_endpoint__"):
                self.endpoints[val.__dynamo_endpoint__] = attr
            elif callable(val) and getattr(val, "__dynamo_on_start__", False):
                self.on_start_hooks.append(attr)
        self.links: List["DynamoService"] = []

    # graph edges ----------------------------------------------------------
    def link(self, other: "DynamoService") -> "DynamoService":
        """Record a deployment edge and return the *target* so chains like
        ``Frontend.link(Processor).link(Worker)`` build a path
        (graphs/disagg_router.py:16-22)."""
        if other not in self.links:
            self.links.append(other)
        return other

    def graph(self) -> List["DynamoService"]:
        """Every service reachable from this entry, in discovery (BFS)
        order — what the serve CLI deploys.

        A service with explicit ``link()`` edges contributes only those:
        its unused ``depends()`` are pruned (the reference's LinkedServices
        ``remove_unused_edges``, lib/service.py:30-241) — a Processor may
        declare `router = depends(Router)` yet an `agg` graph that never
        links Router won't launch one. A service without links contributes
        all its deps, so partially linked graphs still deploy every
        depended-on service."""
        seen: List[DynamoService] = []
        queue = [self]
        while queue:
            svc = queue.pop(0)
            if svc in seen or not svc.enabled:
                continue
            seen.append(svc)
            if svc.links:
                queue.extend(svc.links)
            else:
                queue.extend(d.on for d in svc.dependencies.values())
        return seen

    def instantiate(self) -> Any:
        return self.inner()

    def __repr__(self) -> str:
        return (f"DynamoService({self.name}, ns={self.namespace}, "
                f"endpoints={sorted(self.endpoints)}, "
                f"deps={sorted(self.dependencies)})")


def service(cls: Optional[type] = None, *, name: Optional[str] = None,
            namespace: str = "dynamo", resources: Optional[dict] = None,
            dynamo: Optional[dict] = None, **_ignored):
    """Class decorator → DynamoService. Usable bare or with kwargs."""

    def wrap(c: type) -> DynamoService:
        if not inspect.isclass(c):
            raise TypeError("@service decorates a class")
        return DynamoService(c, name=name, namespace=namespace,
                             resources=resources, dynamo=dynamo)

    if cls is not None:
        return wrap(cls)
    return wrap
