"""The serve CLI: deploy a service graph as supervised processes.

    python -m dynamo_tpu.sdk.serve graphs.agg:Frontend -f configs/agg.yaml

Reference: cli/serve.py + cli/serving.py — resolve the graph entry, build
one supervised worker per service (the reference uses a circus arbiter;
ours is a plain asyncio supervisor with bounded restarts), allocate
accelerator chips per service, inject per-service YAML config via the
``DYNAMO_SERVICE_CONFIG`` env var, and (unless one is given) host the
discovery/bus daemon in-process.

Round 6: the watcher list became a :class:`Supervisor` with a live scale
API — ``scale(service, n)`` programmatically, or desired-replica intents
written under ``planner/scale/{service}`` in the KV store (the dynamic
planner's actuator path, components/planner.py). Scale-down is graceful
by construction: replicas whose serve_worker exits cleanly (rc=0, the
drain-to-exit path) are reaped as retirements, not crashes."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys
from typing import Dict, List, Optional

from .allocator import TpuAllocator
from .config import ENV_VAR, ServiceConfig
from .serve_worker import resolve_service

logger = logging.getLogger("dynamo_tpu.sdk.serve")

MAX_RESTARTS = 3


class Watcher:
    """One supervised service process (circus Watcher analog,
    serving.py:127-166)."""

    def __init__(self, target: str, service_name: str, runtime_server: str,
                 env: Dict[str, str], replica: int = 0, alloc=None):
        self.target = target
        self.service_name = service_name
        self.runtime_server = runtime_server
        self.env = env
        self.replica = replica
        self.alloc = alloc                  # chips to release on retirement
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.restarts = 0
        self.retired = False                # clean drain-to-exit observed
        self._stopping = False

    async def start(self) -> None:
        env = {**os.environ, **self.env,
               "DYN_SERVICE_REPLICA": str(self.replica)}
        self.proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dynamo_tpu.sdk.serve_worker",
            "--target", self.target,
            "--service-name", self.service_name,
            "--runtime-server", self.runtime_server,
            env=env)
        logger.info("started %s[%d] (pid %d)", self.service_name,
                    self.replica, self.proc.pid)

    async def supervise(self) -> None:
        while not self._stopping:
            rc = await self.proc.wait()
            if self._stopping:
                return
            if rc == 0:
                # clean exit = drained worker retiring itself (the planner
                # drain protocol) — reap, don't restart
                self.retired = True
                logger.info("service %s[%d] retired (clean exit)",
                            self.service_name, self.replica)
                return
            if self.restarts >= MAX_RESTARTS:
                raise RuntimeError(
                    f"service {self.service_name} exited rc={rc} "
                    f"(gave up after {self.restarts} restarts)")
            self.restarts += 1
            logger.warning("service %s exited rc=%s — restart %d/%d",
                           self.service_name, rc, self.restarts, MAX_RESTARTS)
            await asyncio.sleep(min(2 ** self.restarts, 10))
            await self.start()

    async def stop(self, grace: float = 5.0) -> None:
        self._stopping = True
        if self.proc is None or self.proc.returncode is not None:
            return
        self.proc.terminate()
        try:
            await asyncio.wait_for(self.proc.wait(), grace)
        except asyncio.TimeoutError:
            logger.warning("killing %s (graceful timeout)", self.service_name)
            self.proc.kill()
            await self.proc.wait()


class Supervisor:
    """Replica manager for one deployed graph: per-service watcher lists,
    a scale API, and an optional KV-store intent watch so a remote planner
    can drive it (``planner/scale/{service}`` → replicas)."""

    def __init__(self, target: str, graph, cfg: ServiceConfig,
                 allocator: TpuAllocator, runtime_server: str):
        self.target = target
        self.services = {svc.name: svc for svc in graph}
        self.cfg = cfg
        self.allocator = allocator
        self.runtime_server = runtime_server
        self.watchers: Dict[str, List[Watcher]] = {
            name: [] for name in self.services}
        self._tasks: Dict[Watcher, asyncio.Task] = {}
        self._next_replica: Dict[str, int] = {name: 0
                                              for name in self.services}
        self._failure: Optional[BaseException] = None
        self._failed = asyncio.Event()
        self._scale_runtime = None
        self._scale_watcher = None
        self._scale_task: Optional[asyncio.Task] = None
        self.scale_ops = 0

    # ---------------------------------------------------------- replicas
    def _chips_for(self, name: str) -> int:
        # YAML `resources: {tpu: n}` overrides the class declaration — e.g.
        # a TpuWorker running its echo engine needs no chips (the reference
        # reads resources from the service config the same way,
        # cli/allocator.py:28-120)
        override = self.cfg.tpu_override(name)
        svc = self.services[name]
        return svc.resources.tpu if override is None else override

    async def start_replica(self, name: str) -> Watcher:
        idx = self._next_replica[name]
        self._next_replica[name] += 1
        alloc = self.allocator.allocate(f"{name}[{idx}]",
                                        self._chips_for(name))
        env = {ENV_VAR: self.cfg.to_env(), **alloc.env()}
        w = Watcher(self.target, name, self.runtime_server, env,
                    replica=idx, alloc=alloc)
        self.watchers[name].append(w)
        await w.start()
        task = asyncio.get_running_loop().create_task(
            self._supervise(w), name=f"supervise-{name}-{idx}")
        self._tasks[w] = task
        return w

    async def _supervise(self, w: Watcher) -> None:
        try:
            await w.supervise()
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — restart cap exceeded
            if self._failure is None:
                self._failure = e
            self._failed.set()
            return
        if w.retired:
            self._reap(w)

    def _reap(self, w: Watcher) -> None:
        if w in self.watchers.get(w.service_name, ()):
            self.watchers[w.service_name].remove(w)
        self._tasks.pop(w, None)
        if w.alloc is not None:
            self.allocator.release(w.alloc)

    def counts(self) -> Dict[str, int]:
        return {name: len(ws) for name, ws in self.watchers.items()}

    async def scale(self, name: str, replicas: int) -> Dict[str, int]:
        """Converge ``name`` to ``replicas`` processes. Scale-down stops
        the youngest replicas (the planner drains the actual victim
        beforehand via the discovery drain protocol; a drained worker has
        usually already retired itself by the time this runs)."""
        if name not in self.services:
            raise ValueError(f"unknown service {name!r}")
        replicas = max(replicas, 0)
        self.scale_ops += 1
        while len(self.watchers[name]) < replicas:
            await self.start_replica(name)
        while len(self.watchers[name]) > replicas:
            w = self.watchers[name][-1]
            task = self._tasks.pop(w, None)
            if task is not None:
                task.cancel()
            await w.stop()
            self.watchers[name].remove(w)
            if w.alloc is not None:
                self.allocator.release(w.alloc)
        logger.info("scaled %s → %d replicas", name, replicas)
        return self.counts()

    # ------------------------------------------------------- scale intents
    async def watch_scale_intents(self) -> None:
        """Watch ``planner/scale/{service}`` for desired-replica intents
        (the planner's SupervisorActuator writes them). Best-effort: a
        deployment without a reachable store just skips the watch."""
        from ..llm.slo import PLANNER_PREFIX
        from ..runtime.distributed import DistributedRuntime
        try:
            self._scale_runtime = await DistributedRuntime.connect(
                self.runtime_server)
            self._scale_watcher = await self._scale_runtime.store \
                .watch_prefix(f"{PLANNER_PREFIX}scale/")
        except Exception as e:  # noqa: BLE001
            logger.warning("scale-intent watch unavailable (%s)", e)
            return
        self._scale_task = asyncio.get_running_loop().create_task(
            self._scale_loop(), name="supervisor-scale-watch")

    async def _scale_loop(self) -> None:
        from ..runtime.kvstore import WatchEventType
        async for ev in self._scale_watcher:
            if ev.type != WatchEventType.PUT:
                continue
            name = ev.entry.key.rsplit("/", 1)[-1]
            if name not in self.services:
                continue
            try:
                want = int(json.loads(ev.entry.value)["replicas"])
            except Exception:  # noqa: BLE001 — admin input
                logger.warning("bad scale intent ignored: %r",
                               ev.entry.value)
                continue
            try:
                await self.scale(name, want)
            except Exception:  # noqa: BLE001 — keep watching
                logger.exception("scale intent for %s failed", name)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "Supervisor":
        for name in self.services:
            await self.start_replica(name)
        return self

    async def wait_failed(self) -> None:
        await self._failed.wait()
        raise self._failure  # noqa: B904 — the original watcher error

    async def stop(self) -> None:
        if self._scale_task is not None:
            self._scale_task.cancel()
        if self._scale_watcher is not None:
            self._scale_watcher.close()
        if self._scale_runtime is not None:
            await self._scale_runtime.shutdown()
        for task in self._tasks.values():
            task.cancel()
        for ws in self.watchers.values():
            for w in list(ws):
                await w.stop()


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-serve")
    p.add_argument("target", help="graph entry, e.g. graphs.agg:Frontend")
    p.add_argument("-f", "--config", help="per-service YAML config")
    p.add_argument("--runtime-server",
                   help="external discovery daemon (default: host one)")
    p.add_argument("--daemon-port", type=int, default=0)
    p.add_argument("--total-chips", type=int,
                   help="override detected TPU chip count")
    p.add_argument("--no-scale-api", action="store_true",
                   help="don't watch planner/scale/* intents")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)

    entry = resolve_service(args.target)
    graph = entry.graph()
    logger.info("deploying graph: %s", " → ".join(s.name for s in graph))

    cfg = (await asyncio.to_thread(ServiceConfig.from_yaml, args.config)
           if args.config else ServiceConfig())

    daemon = None
    runtime_server = args.runtime_server
    if not runtime_server:
        from ..runtime.server import DiscoveryServer
        daemon = DiscoveryServer(host="127.0.0.1", port=args.daemon_port)
        await daemon.start()
        runtime_server = daemon.address
        logger.info("hosting discovery daemon on %s", runtime_server)

    allocator = TpuAllocator(total_chips=args.total_chips)
    supervisor = Supervisor(args.target, graph, cfg, allocator,
                            runtime_server)

    stop_evt = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_evt.set)
        except NotImplementedError:
            pass

    try:
        await supervisor.start()
        if not args.no_scale_api:
            await supervisor.watch_scale_intents()
        fail_task = asyncio.ensure_future(supervisor.wait_failed())
        stop_task = asyncio.ensure_future(stop_evt.wait())
        done, _ = await asyncio.wait(
            [fail_task, stop_task], return_when=asyncio.FIRST_COMPLETED)
        for t in (fail_task, stop_task):
            if t not in done:
                t.cancel()
        for t in done:
            if t is not stop_task and t.exception() is not None:
                raise t.exception()
    finally:
        await supervisor.stop()
        if daemon is not None:
            await daemon.close()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
