"""The serve CLI: deploy a service graph as supervised processes.

    python -m dynamo_tpu.sdk.serve graphs.agg:Frontend -f configs/agg.yaml

Reference: cli/serve.py + cli/serving.py — resolve the graph entry, build
one supervised worker per service (the reference uses a circus arbiter;
ours is a plain asyncio supervisor with bounded restarts), allocate
accelerator chips per service, inject per-service YAML config via the
``DYNAMO_SERVICE_CONFIG`` env var, and (unless one is given) host the
discovery/bus daemon in-process."""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys
from typing import Dict, List, Optional

from .allocator import TpuAllocator
from .config import ENV_VAR, ServiceConfig
from .serve_worker import resolve_service

logger = logging.getLogger("dynamo_tpu.sdk.serve")

MAX_RESTARTS = 3


class Watcher:
    """One supervised service process (circus Watcher analog,
    serving.py:127-166)."""

    def __init__(self, target: str, service_name: str, runtime_server: str,
                 env: Dict[str, str]):
        self.target = target
        self.service_name = service_name
        self.runtime_server = runtime_server
        self.env = env
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.restarts = 0
        self._stopping = False

    async def start(self) -> None:
        env = {**os.environ, **self.env}
        self.proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dynamo_tpu.sdk.serve_worker",
            "--target", self.target,
            "--service-name", self.service_name,
            "--runtime-server", self.runtime_server,
            env=env)
        logger.info("started %s (pid %d)", self.service_name, self.proc.pid)

    async def supervise(self) -> None:
        while not self._stopping:
            rc = await self.proc.wait()
            if self._stopping:
                return
            if self.restarts >= MAX_RESTARTS:
                raise RuntimeError(
                    f"service {self.service_name} exited rc={rc} "
                    f"(gave up after {self.restarts} restarts)")
            self.restarts += 1
            logger.warning("service %s exited rc=%s — restart %d/%d",
                           self.service_name, rc, self.restarts, MAX_RESTARTS)
            await asyncio.sleep(min(2 ** self.restarts, 10))
            await self.start()

    async def stop(self, grace: float = 5.0) -> None:
        self._stopping = True
        if self.proc is None or self.proc.returncode is not None:
            return
        self.proc.terminate()
        try:
            await asyncio.wait_for(self.proc.wait(), grace)
        except asyncio.TimeoutError:
            logger.warning("killing %s (graceful timeout)", self.service_name)
            self.proc.kill()
            await self.proc.wait()


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-serve")
    p.add_argument("target", help="graph entry, e.g. graphs.agg:Frontend")
    p.add_argument("-f", "--config", help="per-service YAML config")
    p.add_argument("--runtime-server",
                   help="external discovery daemon (default: host one)")
    p.add_argument("--daemon-port", type=int, default=0)
    p.add_argument("--total-chips", type=int,
                   help="override detected TPU chip count")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)

    entry = resolve_service(args.target)
    graph = entry.graph()
    logger.info("deploying graph: %s", " → ".join(s.name for s in graph))

    cfg = (ServiceConfig.from_yaml(args.config) if args.config
           else ServiceConfig())

    daemon = None
    runtime_server = args.runtime_server
    if not runtime_server:
        from ..runtime.server import DiscoveryServer
        daemon = DiscoveryServer(host="127.0.0.1", port=args.daemon_port)
        await daemon.start()
        runtime_server = daemon.address
        logger.info("hosting discovery daemon on %s", runtime_server)

    allocator = TpuAllocator(total_chips=args.total_chips)
    watchers: List[Watcher] = []
    for svc in graph:
        # YAML `resources: {tpu: n}` overrides the class declaration — e.g.
        # a TpuWorker running its echo engine needs no chips (the reference
        # reads resources from the service config the same way,
        # cli/allocator.py:28-120)
        override = cfg.tpu_override(svc.name)
        want = svc.resources.tpu if override is None else override
        alloc = allocator.allocate(svc.name, want)
        env = {ENV_VAR: cfg.to_env(), **alloc.env()}
        watchers.append(Watcher(args.target, svc.name, runtime_server, env))

    stop_evt = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_evt.set)
        except NotImplementedError:
            pass

    try:
        for w in watchers:
            await w.start()
        tasks = [asyncio.ensure_future(w.supervise()) for w in watchers]
        stop_task = asyncio.ensure_future(stop_evt.wait())
        done, _ = await asyncio.wait(
            tasks + [stop_task], return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            if t is not stop_task and t.exception() is not None:
                raise t.exception()
    finally:
        for w in watchers:
            await w.stop()
        if daemon is not None:
            await daemon.close()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
