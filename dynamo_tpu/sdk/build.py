"""`python -m dynamo_tpu.sdk.build graphs.agg:Frontend -f cfg.yaml -o out/`
— package a service graph into a deployable artifact.

Reference: the SDK's `dynamo build` / `dynamo deploy` pair
(deploy/dynamo/sdk/src/dynamo/sdk/cli/{bentos,deploy}.py) packages the graph
as a bento and uploads it to the api-server control plane. TPU-native scope
(SURVEY.md §2.3 item 7: manifests instead of an operator): the artifact is a
directory with

- ``manifest.json`` — the resolved graph: services, endpoints, deps,
  namespaces, resource requests, entry target;
- ``config.yaml`` — the service config, verbatim;
- ``k8s/`` — one generated Deployment per service running the serve worker
  (plus the shared discovery daemon), ready for `kubectl apply -f`;
- ``run.sh`` — the local single-host launch line.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
from typing import List, Optional

from .config import ServiceConfig
from .serve_worker import resolve_service
from .service import DynamoService

_K8S_DEPLOYMENT = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}
  namespace: {k8s_namespace}
  labels: {{app: {name}}}
spec:
  replicas: {replicas}
  selector:
    matchLabels: {{app: {name}}}
  template:
    metadata:
      labels: {{app: {name}}}
    spec:
      containers:
        - name: service
          image: {image}
          command: ["python", "-m", "dynamo_tpu.sdk.serve_worker",
                    "--target", "{target}",
                    "--service-name", "{service}",
                    "--runtime-server", "discovery:6510"]
          env:
            - {{name: DYNAMO_SERVICE_CONFIG, value: {config_env}}}
{resources}"""

_K8S_DISCOVERY = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: discovery
  namespace: {k8s_namespace}
  labels: {{app: discovery}}
spec:
  replicas: 1
  selector:
    matchLabels: {{app: discovery}}
  template:
    metadata:
      labels: {{app: discovery}}
    spec:
      containers:
        - name: discovery
          image: {image}
          command: ["python", "-m", "dynamo_tpu.runtime.server",
                    "--host", "0.0.0.0", "--port", "6510"]
          ports:
            - {{containerPort: 6510, name: runtime}}
---
apiVersion: v1
kind: Service
metadata:
  name: discovery
  namespace: {k8s_namespace}
spec:
  selector: {{app: discovery}}
  ports:
    - {{port: 6510, targetPort: 6510, name: runtime}}
"""

_K8S_TPU_RESOURCES = """\
          resources:
            requests: {{"google.com/tpu": "{tpu}", cpu: "4", memory: 16Gi}}
            limits: {{"google.com/tpu": "{tpu}"}}
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: tpu-v5-lite-podslice
"""

_K8S_CPU_RESOURCES = """\
          resources:
            requests: {cpu: "1", memory: 2Gi}
"""


def build_artifact(target: str, config_path: Optional[str], out_dir: str,
                   image: str = "dynamo-tpu:latest",
                   k8s_namespace: str = "dynamo-tpu") -> dict:
    entry = resolve_service(target)
    graph: List[DynamoService] = entry.graph()
    cfg = (ServiceConfig.from_yaml(config_path) if config_path
           else ServiceConfig())

    os.makedirs(out_dir, exist_ok=True)
    k8s_dir = os.path.join(out_dir, "k8s")
    os.makedirs(k8s_dir, exist_ok=True)

    manifest = {
        "target": target,
        "entry": entry.name,
        "services": [{
            "name": s.name,
            "namespace": s.namespace,
            "endpoints": sorted(s.endpoints),
            "depends": sorted(d.on.name for d in s.dependencies.values()),
            "links": [l.name for l in s.links],
            "resources": {"tpu": s.resources.tpu},
        } for s in graph],
    }
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)

    if config_path:
        shutil.copy(config_path, os.path.join(out_dir, "config.yaml"))

    with open(os.path.join(k8s_dir, "discovery.yaml"), "w") as f:
        f.write(_K8S_DISCOVERY.format(k8s_namespace=k8s_namespace,
                                      image=image))

    # the env value is a JSON string inside YAML: json.dumps again yields a
    # double-quoted scalar with YAML-compatible escaping
    config_env = json.dumps(cfg.to_env())
    for svc in graph:
        override = cfg.tpu_override(svc.name)
        tpu = svc.resources.tpu if override is None else override
        replicas = cfg.get(svc.name, "replicas")
        body = _K8S_DEPLOYMENT.format(
            name=svc.name.lower(), k8s_namespace=k8s_namespace,
            replicas=1 if replicas is None else int(replicas),
            image=image, target=target, service=svc.name,
            config_env=config_env,
            resources=(_K8S_TPU_RESOURCES.format(tpu=tpu) if tpu
                       else _K8S_CPU_RESOURCES))
        with open(os.path.join(k8s_dir, f"{svc.name.lower()}.yaml"),
                  "w") as f:
            f.write(body)

    run_line = (f"python -m dynamo_tpu.sdk.serve {target}"
                + (" -f config.yaml" if config_path else ""))
    with open(os.path.join(out_dir, "run.sh"), "w") as f:
        f.write('#!/bin/sh\n# local single-host launch\n'
                'cd "$(dirname "$0")"\n' + run_line + "\n")
    os.chmod(os.path.join(out_dir, "run.sh"), 0o755)
    return manifest


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-build")
    p.add_argument("target", help="graph entry, e.g. pkg.graphs.agg:Frontend")
    p.add_argument("-f", "--config", help="service config YAML")
    p.add_argument("-o", "--out", required=True, help="artifact directory")
    p.add_argument("--image", default="dynamo-tpu:latest")
    p.add_argument("--k8s-namespace", default="dynamo-tpu")
    args = p.parse_args(argv)
    manifest = build_artifact(args.target, args.config, args.out,
                              image=args.image,
                              k8s_namespace=args.k8s_namespace)
    print(f"built {args.out}: {len(manifest['services'])} services "
          f"({', '.join(s['name'] for s in manifest['services'])})")


if __name__ == "__main__":
    main()
