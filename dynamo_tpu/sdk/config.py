"""Per-service configuration: YAML file sections + env injection.

Reference: lib/config.py ``ServiceConfig`` singleton — ``-f config.yaml``
sections keyed by service name, injected into worker subprocesses via the
``DYNAMO_SERVICE_CONFIG`` env var (service.py:110-117), with ``as_args``
flattening for engine flags."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

__all__ = ["ServiceConfig"]

ENV_VAR = "DYNAMO_SERVICE_CONFIG"


class ServiceConfig:
    _instance: Optional["ServiceConfig"] = None

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self.data: Dict[str, Any] = data or {}

    # singleton plumbing ---------------------------------------------------
    @classmethod
    def get_instance(cls) -> "ServiceConfig":
        if cls._instance is None:
            raw = os.environ.get(ENV_VAR)
            cls._instance = cls(json.loads(raw) if raw else {})
        return cls._instance

    @classmethod
    def set_instance(cls, cfg: "ServiceConfig") -> None:
        cls._instance = cfg

    @classmethod
    def reset(cls) -> None:
        cls._instance = None

    # loading --------------------------------------------------------------
    @classmethod
    def from_yaml(cls, path: str) -> "ServiceConfig":
        import yaml
        with open(path) as f:
            return cls(yaml.safe_load(f) or {})

    def to_env(self) -> str:
        return json.dumps(self.data)

    # access ---------------------------------------------------------------
    def for_service(self, name: str) -> Dict[str, Any]:
        return dict(self.data.get(name) or {})

    def get(self, service: str, key: str, default: Any = None) -> Any:
        return self.for_service(service).get(key, default)

    def tpu_override(self, service: str) -> Any:
        """Chip count from the service's YAML ``resources`` section, or
        None when the section doesn't set one — the single home of the
        'config resources win over the class declaration' rule used by
        both the serve allocator and artifact generation."""
        res = self.get(service, "resources") or {}
        if "tpu" in res or "gpu" in res:
            from .service import Resources
            return Resources.tpu_count(res)
        return None

    def as_args(self, service: str, prefix: str = "") -> List[str]:
        """Flatten a service section into ``--key value`` CLI args
        (reference as_args; booleans become bare flags when true)."""
        out: List[str] = []
        for k, v in self.for_service(service).items():
            if prefix and not k.startswith(prefix):
                continue
            key = k[len(prefix):] if prefix else k
            flag = f"--{key.replace('_', '-')}"
            if isinstance(v, bool):
                if v:
                    out.append(flag)
            elif v is not None:
                out.extend([flag, str(v)])
        return out
