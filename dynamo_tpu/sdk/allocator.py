"""Per-service TPU chip allocator.

Reference: cli/allocator.py:28-120 — the serve CLI reads each service's
``resources={gpu: n}`` and assigns disjoint ``CUDA_VISIBLE_DEVICES`` ranges
to its workers. TPU-native analog: assign chip indices and export
``TPU_VISIBLE_CHIPS`` (+ ``TPU_PROCESS_BOUNDS``-friendly count) so multiple
engine processes on one TPU-VM host split the local chips; CPU/dry-run
deployments get the same accounting with no env effect."""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.sdk.allocator")

__all__ = ["TpuAllocator"]


def _detect_chip_count(default: int = 4) -> int:
    """Chips on this host. v5e/v6e TPU-VM hosts expose 1/4/8 chips; fall
    back to the JAX device count when available, else `default`."""
    try:
        import jax
        devs = [d for d in jax.devices() if d.platform == "tpu"]
        if devs:
            return len(devs)
    except Exception:  # noqa: BLE001 — no jax / no TPU: accounting only
        pass
    return default


@dataclasses.dataclass
class Allocation:
    service: str
    chips: List[int]

    def env(self) -> Dict[str, str]:
        if not self.chips:
            return {}
        return {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in self.chips),
                "TPU_CHIPS_PER_PROCESS_BOUNDS":
                    f"1,1,{len(self.chips)}"}


class TpuAllocator:
    """Free-list allocator (was bump-pointer): the dynamic planner scales
    replicas up AND down, so released chips must be reusable."""

    def __init__(self, total_chips: Optional[int] = None):
        self.total = (_detect_chip_count() if total_chips is None
                      else total_chips)
        self._free: List[int] = list(range(self.total))
        self.allocations: Dict[str, Allocation] = {}

    @property
    def free_chips(self) -> int:
        return len(self._free)

    def allocate(self, service: str, n_chips: int) -> Allocation:
        if n_chips == 0:
            alloc = Allocation(service, [])
        else:
            if n_chips > len(self._free):
                raise RuntimeError(
                    f"service {service!r} wants {n_chips} chips but only "
                    f"{len(self._free)}/{self.total} remain")
            alloc = Allocation(service, self._free[:n_chips])
            del self._free[:n_chips]
            logger.info("allocated chips %s → %s", alloc.chips, service)
        self.allocations[service] = alloc
        return alloc

    def release(self, alloc: Allocation) -> None:
        """Return a replica's chips to the pool (planner scale-down)."""
        if alloc.chips:
            self._free = sorted(set(self._free) | set(alloc.chips))
            logger.info("released chips %s ← %s", alloc.chips,
                        alloc.service)
        if self.allocations.get(alloc.service) is alloc:
            self.allocations.pop(alloc.service, None)
