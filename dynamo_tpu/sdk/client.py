"""DependencyClient: what a ``depends()`` attribute becomes at serve time.

Reference: lib/dependency.py — a typed stub over the distributed client for
the dependency's first endpoint, with ``.generate(...)`` streaming and
``.get_endpoint(name)`` for explicit endpoint selection."""

from __future__ import annotations

from typing import Any, AsyncIterator, Optional

from ..runtime.distributed import Client, DistributedRuntime, Endpoint
from .service import DynamoService

__all__ = ["DependencyClient"]


class DependencyClient:
    def __init__(self, runtime: DistributedRuntime, svc: DynamoService):
        self.runtime = runtime
        self.service = svc
        self._clients: dict = {}

    @classmethod
    async def connect(cls, runtime: DistributedRuntime,
                      svc: DynamoService) -> "DependencyClient":
        self = cls(runtime, svc)
        for ep_name in svc.endpoints:
            await self._client(ep_name)
        return self

    def get_endpoint(self, name: str) -> Endpoint:
        return Endpoint(self.runtime, self.service.namespace,
                        self.service.name, name)

    async def _client(self, ep_name: str) -> Client:
        c = self._clients.get(ep_name)
        if c is None:
            c = self.get_endpoint(ep_name).client()
            await c.start()
            self._clients[ep_name] = c
        return c

    async def wait_ready(self, timeout: float = 60.0) -> None:
        for ep_name in self.service.endpoints:
            client = await self._client(ep_name)
            await client.wait_for_instances(timeout)

    async def call(self, endpoint: str, payload: Any,
                   instance_id: Optional[int] = None) -> AsyncIterator[Any]:
        client = await self._client(endpoint)
        if not client.instances:
            # services boot concurrently; first calls tolerate a late peer
            await client.wait_for_instances(timeout=30.0)
        from ..runtime import Context
        ctx = payload if isinstance(payload, Context) else Context(payload)
        if instance_id is not None:
            return await client.direct(ctx, instance_id)
        return await client.random(ctx)

    def __getattr__(self, name: str):
        """dep.generate(payload) — dynamic method per endpoint name."""
        if name.startswith("_") or name not in self.service.endpoints:
            raise AttributeError(name)

        async def invoke(payload: Any, instance_id: Optional[int] = None):
            return await self.call(name, payload, instance_id)

        return invoke

    async def close(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
