"""Deployment SDK: service graph decorators + serve CLI.

Reference: deploy/dynamo/sdk (BentoML-derived; SURVEY.md §2.5) —
``@service`` classes with ``@dynamo_endpoint`` async-generator methods,
``depends()`` typed clients, ``.link()`` graph edges, YAML per-service
config, and a process supervisor with a per-service accelerator allocator.
Ours is BentoML-free: plain decorators, an asyncio supervisor (circus
analog), and a TPU chip allocator."""

from .config import ServiceConfig
from .service import (DynamoService, async_on_start, depends,
                      dynamo_endpoint, service)

__all__ = ["service", "dynamo_endpoint", "async_on_start", "depends",
           "DynamoService", "ServiceConfig"]
