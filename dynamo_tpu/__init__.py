"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

A ground-up re-design of the capabilities of NVIDIA Dynamo (reference mounted
at /root/reference; see SURVEY.md) for TPU hardware: OpenAI-compatible
frontend, disaggregated prefill/decode serving, KV-aware routing over a global
radix index, paged KV-block management with host offload — with the inference
engine implemented in JAX/XLA/Pallas (pjit-sharded prefill/decode, Pallas
paged attention, ICI/DCN KV handoff) instead of delegating to external GPU
engine subprocesses.
"""

__version__ = "0.1.0"
