"""SLA-driven dynamic planner: the load-aware scale/drain control loop.

Reference: the Planner pillar ("dynamic GPU scheduling", README) — the one
serving-stack component the reference describes but this repro had stopped
short of (SURVEY.md §7 stage 8; parallel/planner.py is a STATIC topology
placer and stays one — it answers "how do I lay a model across chips",
this module answers "how many workers should exist right now").

The standing loop:

1. **Watch** signals it already has transport for — per-endpoint
   ForwardPassMetrics via ``Client.collect_stats`` (queue depth, slot and
   KV-pool utilization), prefill WorkQueue depth, and TTFT/ITL percentiles
   from the tracing ring buffer (runtime/tracing.py).
2. **Evaluate** them against declared SLOs (llm/slo.py) with hysteresis —
   a breach must persist ``breach_cycles`` consecutive evaluations — and a
   post-action ``cooldown_s`` so the loop never flaps.
3. **Act** through three actuators:
   - scale prefill/decode replica counts (PlannerActuator: the sdk/serve
     supervisor's scale API, the deploy controller's spec CAS, or an
     in-process worker factory in tests);
   - retune the disagg threshold live through the kvstore watch
     DisaggregatedRouter already honors;
   - gracefully drain decommissioned workers: write the drain-request
     key → the worker re-announces ``draining=true`` (routers stop
     admitting) → wait for in-flight completion (scraped stats) → only
     then retire the process. Zero dropped requests by construction.

Admin surface: ``llmctl planner {status,set-slo,pause,resume}`` over the
same KV keys, a ``/planner`` endpoint on the metrics service, and
planner decision counters exported to Prometheus/Grafana.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import json
import logging
import time
from typing import Dict, List, Optional

from ..llm.slo import (FleetSignals, ServiceLevelObjective, SloVerdict,
                       control_key, evaluate, latency_percentiles,
                       slo_key, status_key)
from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.kvstore import WatchEventType

logger = logging.getLogger("dynamo_tpu.components.planner")

__all__ = ["Planner", "PlannerConfig", "PlannerActuator",
           "SupervisorActuator", "ControllerActuator"]


@dataclasses.dataclass
class PlannerConfig:
    interval_s: float = 0.5            # evaluation cadence
    cooldown_s: float = 5.0            # min gap between actuations
    breach_cycles: int = 3             # consecutive breaches before acting
    scale_step: int = 1                # replicas per scale action
    drain_timeout_s: float = 60.0      # give up waiting for idle after this
    drain_poll_s: float = 0.1
    status_interval_s: float = 0.5     # status-key publish cadence
    # disagg-threshold retune bounds/step (powers of two around baseline)
    retune_min: int = 64
    retune_max: int = 8192


class PlannerActuator(abc.ABC):
    """Substrate the planner scales. Implementations map a role
    ("decode" | "prefill") onto real replicas."""

    @abc.abstractmethod
    async def scale_up(self, role: str, count: int) -> None:
        """Start ``count`` additional replicas of ``role``."""

    @abc.abstractmethod
    async def retire(self, role: str, worker_id: int) -> None:
        """Stop the DRAINED worker with discovery id ``worker_id``. Called
        only after the planner observed it idle — the implementation may
        stop a process, delete a pod, or close an in-process worker."""


class SupervisorActuator(PlannerActuator):
    """Actuates the sdk/serve.py supervisor: writes desired-replica
    intents under ``planner/scale/{service}``; the supervisor watches the
    prefix and converges. Retirement is drain-to-exit: the worker's
    serve_worker process exits cleanly once drained and the supervisor
    reaps it without restart, so the planner only adjusts the target."""

    def __init__(self, runtime: DistributedRuntime,
                 service_names: Dict[str, str]):
        """``service_names``: role → supervisor service name (e.g.
        {"decode": "TpuWorker", "prefill": "PrefillWorker"})."""
        self.runtime = runtime
        self.service_names = service_names
        self._targets: Dict[str, int] = {}

    async def _publish(self, role: str, delta: int) -> None:
        from ..llm.slo import scale_key
        service = self.service_names[role]
        cur = self._targets.get(role)
        if cur is None:
            entry = await self.runtime.store.kv_get(scale_key(service))
            cur = (json.loads(entry.value).get("replicas", 1)
                   if entry is not None else 1)
        self._targets[role] = target = max(cur + delta, 0)
        await self.runtime.store.kv_put(
            scale_key(service),
            json.dumps({"replicas": target, "at": time.time()}).encode())

    async def scale_up(self, role: str, count: int) -> None:
        await self._publish(role, count)

    async def retire(self, role: str, worker_id: int) -> None:
        # the drained serve_worker exits on its own (drain-to-exit);
        # lower the target so the supervisor doesn't replace it
        await self._publish(role, -1)


class ControllerActuator(PlannerActuator):
    """Actuates deploy/controller.py deployments (the k8s-shaped path):
    scales by CAS-updating the DeploymentSpec replica count."""

    def __init__(self, store, deployments: Dict[str, str]):
        """``deployments``: role → deployment name."""
        self.store = store
        self.deployments = deployments

    async def _bump(self, role: str, delta: int) -> None:
        from ..deploy.spec import update_spec

        def mutate(spec):
            spec.replicas = max(spec.replicas + delta, 0)

        await update_spec(self.store, self.deployments[role], mutate)

    async def scale_up(self, role: str, count: int) -> None:
        await self._bump(role, count)

    async def retire(self, role: str, worker_id: int) -> None:
        await self._bump(role, -1)


class Planner:
    """The standing control loop. One planner per namespace; workers are
    discovered through ``decode_endpoint`` (and optionally
    ``prefill_queue`` for the disagg retune signal)."""

    def __init__(self, runtime: DistributedRuntime,
                 decode_endpoint: Endpoint,
                 actuator: PlannerActuator,
                 slo: Optional[ServiceLevelObjective] = None,
                 config: Optional[PlannerConfig] = None,
                 prefill_queue=None,
                 prefill_endpoint: Optional[Endpoint] = None,
                 model_name: Optional[str] = None,
                 traces=None, collector=None):
        self.runtime = runtime
        self.endpoint = decode_endpoint
        self.actuator = actuator
        self.slo = slo or ServiceLevelObjective()
        self.cfg = config or PlannerConfig()
        self.prefill_queue = prefill_queue
        # discovery endpoint of the prefill fleet: with it the planner
        # ACTUATES prefill replicas (scale out on sustained queue
        # backlog, drain-then-retire on sustained idleness) instead of
        # only retuning the disagg threshold around a fixed tier
        self.prefill_endpoint = prefill_endpoint
        self._prefill_client = None
        # model whose disagg threshold the retune actuator manages
        self.model_name = model_name
        # latency sources, preferred first: `collector` is a fleet trace
        # collector (components/trace_collector.py — every worker's
        # published traces, the honest fleet picture); `traces` is the
        # FALLBACK callable returning local tracing dicts (the process
        # tracer ring — frontend-local truth, meaningful when the
        # planner is embedded next to the frontend/worker)
        self.collector = collector
        if traces is None:
            from ..runtime.tracing import tracer
            traces = tracer.recent
        self._traces = traces
        self.paused = False
        self._client = None
        self._tasks: List[asyncio.Task] = []
        self._watchers: list = []
        self._drain_task: Optional[asyncio.Task] = None
        # hysteresis state
        self._up_breaches = 0
        self._down_breaches = 0
        self._cooldown_until = 0.0
        self._retune_cooldown_until = 0.0
        # prefill-tier hysteresis (independent of the decode counters —
        # a decode breach must not mask a prefill backlog or vice versa)
        self._pq_breaches = 0
        self._pq_idle_cycles = 0
        self._prefill_cooldown_until = 0.0
        self._prefill_drain_task: Optional[asyncio.Task] = None
        # current disagg threshold (applied via retune)
        self.disagg_threshold = self.slo.max_local_prefill_length
        # observability
        self.counters: Dict[str, int] = {
            "evaluations": 0, "scale_up": 0, "scale_down": 0,
            "drains_started": 0, "drains_completed": 0,
            "drain_timeouts": 0, "retunes": 0, "holds": 0,
            "retune_crossover_holds": 0,
            "prefill_scale_up": 0, "prefill_scale_down": 0,
            "prefill_drains_started": 0,
        }
        self.last_decision: dict = {}
        self.last_signals: Optional[FleetSignals] = None
        # raw per-worker metrics from the last scrape — the fleet-level
        # fetch-vs-recompute crossover input (scoring.py) the retune
        # floor consumes
        self.last_stats: Dict[int, dict] = {}
        self.fleet_crossover_tokens: Optional[float] = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "Planner":
        self._client = self.endpoint.client()
        await self._client.start()
        if self.prefill_endpoint is not None:
            self._prefill_client = self.prefill_endpoint.client()
            await self._prefill_client.start()
        # live SLO + control watches (llmctl writes these)
        ns = self.endpoint.namespace
        entry = await self.runtime.store.kv_get(slo_key(ns))
        if entry is not None:
            self._apply_slo(entry.value)
        entry = await self.runtime.store.kv_get(control_key(ns))
        if entry is not None:
            self._apply_control(entry.value)
        w_slo = await self.runtime.store.watch_prefix(slo_key(ns))
        w_ctl = await self.runtime.store.watch_prefix(control_key(ns))
        self._watchers = [w_slo, w_ctl]
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._watch_loop(w_slo, self._apply_slo),
                             name="planner-slo-watch"),
            loop.create_task(self._watch_loop(w_ctl, self._apply_control),
                             name="planner-control-watch"),
            loop.create_task(self._run_loop(), name="planner-loop"),
            loop.create_task(self._status_loop(), name="planner-status"),
        ]
        logger.info("planner started for %s (slo: ttft_p90<%gms, "
                    "queue<%g, decode %d..%d)", self.endpoint.path,
                    self.slo.ttft_p90_ms, self.slo.max_queue_depth,
                    self.slo.min_decode_workers, self.slo.max_decode_workers)
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._drain_task is not None:
            self._drain_task.cancel()
        if self._prefill_drain_task is not None:
            self._prefill_drain_task.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for w in self._watchers:
            w.close()
        if self._client is not None:
            await self._client.close()
        if self._prefill_client is not None:
            await self._prefill_client.close()

    # ------------------------------------------------------------- watches
    async def _watch_loop(self, watcher, apply) -> None:
        async for ev in watcher:
            if ev.type == WatchEventType.PUT:
                apply(ev.entry.value)

    def _apply_slo(self, raw: bytes) -> None:
        try:
            self.slo = ServiceLevelObjective.from_json(raw)
            logger.info("planner SLO updated: %s", self.slo)
        except Exception:  # noqa: BLE001 — admin input
            logger.warning("bad SLO update ignored: %r", raw)

    def _apply_control(self, raw: bytes) -> None:
        try:
            self.paused = bool(json.loads(raw).get("paused", False))
            logger.info("planner %s", "paused" if self.paused else "resumed")
        except Exception:  # noqa: BLE001
            logger.warning("bad control update ignored: %r", raw)

    # ------------------------------------------------------------- signals
    async def observe(self) -> FleetSignals:
        stats = await self._client.collect_stats()
        self.last_stats = stats
        draining = set(self._client.draining_ids())
        pq_depth = 0
        if self.prefill_queue is not None:
            try:
                pq_depth = await self.prefill_queue.depth()
            except Exception:  # noqa: BLE001 — queue may not exist yet
                pq_depth = 0
        lat = latency_percentiles(collector=self.collector,
                                  traces=self._traces())
        signals = FleetSignals.from_worker_metrics(
            stats, draining=draining,
            ttft_p90_ms=lat.get("ttft_p_ms"),
            itl_p90_ms=lat.get("itl_p_ms"),
            prefill_queue_depth=pq_depth)
        # workers can register before their first stats publish lands;
        # count them from discovery so scale_up doesn't overshoot
        known = set(self._client.instance_ids()) - draining
        if len(known) > signals.n_decode:
            signals.n_decode = len(known)
        self.last_signals = signals
        return signals

    # ---------------------------------------------------------------- loop
    async def _run_loop(self) -> None:
        # long-lived task: detach the spawning context's ambient trace
        # (runtime/tracing.py) so scrape/actuate RPC spans never attach
        # to whatever request started the planner
        from ..runtime.tracing import detach_trace
        detach_trace()
        while True:
            try:
                await self._evaluate_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must never die
                logger.exception("planner evaluation failed")
            await asyncio.sleep(self.cfg.interval_s)

    async def _evaluate_once(self) -> None:
        if self.paused:
            return
        signals = await self.observe()
        verdict = evaluate(signals, self.slo)
        self.counters["evaluations"] += 1
        # hysteresis: consecutive-cycle breach counting per direction
        if verdict.action == "scale_up":
            self._up_breaches += 1
            self._down_breaches = 0
        elif verdict.action == "scale_down":
            self._down_breaches += 1
            self._up_breaches = 0
        else:
            self._up_breaches = self._down_breaches = 0
        now = time.monotonic()
        in_cooldown = now < self._cooldown_until
        draining_inflight = (self._drain_task is not None
                             and not self._drain_task.done())
        acted = False
        if (verdict.action == "scale_up"
                and self._up_breaches >= self.cfg.breach_cycles
                and not in_cooldown and not draining_inflight):
            step = min(self.cfg.scale_step,
                       self.slo.max_decode_workers - signals.n_decode)
            if step > 0:
                await self.actuator.scale_up("decode", step)
                self.counters["scale_up"] += 1
                self._record("scale_up", verdict, {"added": step})
                self._arm_cooldown()
                acted = True
        elif (verdict.action == "scale_down"
                and self._down_breaches >= self.cfg.breach_cycles
                and not in_cooldown and not draining_inflight):
            victim = self._pick_drain_victim()
            if victim is not None:
                self.counters["drains_started"] += 1
                self._record("drain_start", verdict, {"worker": victim})
                self._drain_task = asyncio.get_running_loop().create_task(
                    self._drain_and_retire(victim),
                    name=f"planner-drain-{victim:x}")
                self._arm_cooldown()
                acted = True
        if not acted:
            self.counters["holds"] += 1
            if not self.last_decision:
                self._record("hold", verdict, {})
        await self._maybe_scale_prefill(signals)
        await self._maybe_retune(signals)

    # ------------------------------------------------------- prefill fleet
    async def _maybe_scale_prefill(self, signals: FleetSignals) -> None:
        """Prefill-fleet actuation (the ROADMAP's 'planner currently only
        actuates decode replicas' gap, closed): a prefill-queue backlog
        sustained for ``breach_cycles`` evaluations scales the prefill
        tier out; a queue pinned at ZERO for twice that long drains the
        youngest prefill worker and retires it — same hysteresis +
        cooldown discipline as the decode loop, independent counters so
        neither tier's pressure masks the other's."""
        if self._prefill_client is None:
            return
        depth = signals.prefill_queue_depth
        if depth > self.slo.max_queue_depth:
            self._pq_breaches += 1
            self._pq_idle_cycles = 0
        elif depth == 0:
            self._pq_idle_cycles += 1
            self._pq_breaches = 0
        else:
            self._pq_breaches = 0
            self._pq_idle_cycles = 0
        now = time.monotonic()
        if now < self._prefill_cooldown_until:
            return
        drain_busy = (self._prefill_drain_task is not None
                      and not self._prefill_drain_task.done())
        draining = set(self._prefill_client.draining_ids())
        live = [i for i in self._prefill_client.instance_ids()
                if i not in draining]
        if self._pq_breaches >= self.cfg.breach_cycles and not drain_busy:
            step = min(self.cfg.scale_step,
                       self.slo.max_prefill_workers - len(live))
            if step > 0:
                await self.actuator.scale_up("prefill", step)
                self.counters["prefill_scale_up"] += 1
                self.last_decision = {
                    "action": "prefill_scale_up", "added": step,
                    "prefill_queue_depth": depth, "at": time.time()}
                logger.info("planner decision: prefill_scale_up +%d "
                            "(queue depth %d)", step, depth)
                self._prefill_cooldown_until = (time.monotonic()
                                                + self.cfg.cooldown_s)
                # single planner control task; resetting the breach
                # counter AFTER the actuation is the designed
                # hysteresis (breaches during the await are absorbed)
                self._pq_breaches = 0  # dynalint: ok DL008 single-writer control loop
        elif (self._pq_idle_cycles >= 2 * self.cfg.breach_cycles
                and not drain_busy
                and len(live) > self.slo.min_prefill_workers):
            victim = max(live)             # youngest lease, like decode
            self.counters["prefill_drains_started"] += 1
            self.last_decision = {
                "action": "prefill_drain_start",
                "worker": f"{victim:x}", "at": time.time()}
            self._prefill_drain_task = (
                asyncio.get_running_loop().create_task(
                    self._drain_and_retire(victim, role="prefill"),
                    name=f"planner-prefill-drain-{victim:x}"))
            self._prefill_cooldown_until = (time.monotonic()
                                            + self.cfg.cooldown_s)
            self._pq_idle_cycles = 0

    def _arm_cooldown(self) -> None:
        self._cooldown_until = time.monotonic() + self.cfg.cooldown_s
        self._up_breaches = self._down_breaches = 0

    def _record(self, action: str, verdict: SloVerdict, extra: dict) -> None:
        self.last_decision = {
            "action": action, "reason": verdict.reason,
            "breaches": verdict.breaches, "at": time.time(), **extra}
        logger.info("planner decision: %s (%s) %s", action, verdict.reason,
                    extra or "")

    # ---------------------------------------------------------------- drain
    def _pick_drain_victim(self) -> Optional[int]:
        """Least-loaded non-draining worker (fewest active slots in the
        last scrape; ties → highest id, i.e. the youngest lease)."""
        draining = set(self._client.draining_ids())
        candidates = [i for i in self._client.instance_ids()
                      if i not in draining]
        if len(candidates) <= self.slo.min_decode_workers:
            return None
        return max(candidates)

    async def _drain_and_retire(self, worker_id: int,
                                role: str = "decode") -> None:
        """The drain protocol (docs/planner.md): flag → no new admissions
        → wait in-flight completion → retire. Zero dropped requests.
        ``role`` selects the fleet (decode by default; "prefill" drains
        through the prefill endpoint's keys/client and books its own
        counters)."""
        prefill = role == "prefill"
        client = self._prefill_client if prefill else self._client
        endpoint = self.prefill_endpoint if prefill else self.endpoint
        store = self.runtime.store
        await store.kv_put(
            endpoint.drain_key(worker_id),
            json.dumps({"requested_at": time.time()}).encode())
        deadline = time.monotonic() + self.cfg.drain_timeout_s
        drained = False
        while time.monotonic() < deadline:
            # gone from discovery entirely (drain-to-exit) counts as done
            if worker_id not in client.instances:
                drained = True
                break
            stats = await client.collect_stats()
            m = stats.get(worker_id)
            if (worker_id in set(client.draining_ids())
                    and m is not None
                    and int(m.get("request_active_slots", 1)) == 0
                    and int(m.get("num_requests_waiting", 1)) == 0):
                drained = True
                break
            await asyncio.sleep(self.cfg.drain_poll_s)
        if not drained:
            self.counters["drain_timeouts"] += 1
            logger.warning("drain of %x timed out after %.0fs; retiring "
                           "anyway (in-flight work may be cut)", worker_id,
                           self.cfg.drain_timeout_s)
        try:
            await self.actuator.retire(role, worker_id)
        finally:
            if prefill:
                self.counters["prefill_scale_down"] += 1
            else:
                self.counters["drains_completed"] += 1
                self.counters["scale_down"] += 1
            self.last_decision = {
                "action": ("prefill_drain_complete" if prefill
                           else "drain_complete"),
                "worker": f"{worker_id:x}",
                "clean": drained, "at": time.time()}
            logger.info("%s worker %x drained and retired (clean=%s)",
                        role, worker_id, drained)

    # --------------------------------------------------------------- retune
    async def _maybe_retune(self, signals: FleetSignals) -> None:
        """Live disagg-threshold retune (FlowKV-style load awareness): a
        backed-up prefill queue pushes work LOCAL (threshold up — the
        remote fleet is the bottleneck); an empty queue under TTFT
        pressure pulls long prompts REMOTE (threshold down). Published
        through the kvstore watch every DisaggregatedRouter honors.

        Fleet crossover floor (ROADMAP KV-fabric item (c)): a downward
        retune is FLOORED at the fleet's median fetch-vs-recompute
        crossover depth (scoring.fleet_crossover_tokens over the last
        scrape) — below that depth, moving prefix KV across the fabric
        loses to recomputing it locally, so pushing shorter prompts
        remote can only burn link budget. A fleet whose links never pay
        (crossover inf) effectively refuses to lower at all."""
        if self.model_name is None or self.prefill_queue is None:
            return
        if time.monotonic() < self._retune_cooldown_until:
            return
        cur = self.disagg_threshold
        new = cur
        if signals.prefill_queue_depth > self.slo.max_queue_depth:
            new = min(cur * 2, self.cfg.retune_max)
        elif (signals.prefill_queue_depth == 0
              and signals.ttft_p90_ms is not None
              and signals.ttft_p90_ms > self.slo.ttft_p90_ms
              and cur > self.slo.max_local_prefill_length):
            new = max(cur // 2, self.cfg.retune_min)
        if new < cur:
            from ..llm.kv_router.scoring import fleet_crossover_tokens
            xo = fleet_crossover_tokens(self.last_stats)
            self.fleet_crossover_tokens = xo
            if xo is not None:
                floor = min(max(int(min(xo, self.cfg.retune_max)),
                                self.cfg.retune_min), self.cfg.retune_max)
                if new < floor:
                    self.counters["retune_crossover_holds"] += 1
                    new = min(floor, cur)
        if new == cur:
            return
        from ..llm.disagg import disagg_config_key
        await self.runtime.store.kv_put(
            disagg_config_key(self.model_name),
            json.dumps({"max_local_prefill_length": new}).encode())
        self.disagg_threshold = new
        self._retune_cooldown_until = time.monotonic() + self.cfg.cooldown_s
        self.counters["retunes"] += 1
        xo = self.fleet_crossover_tokens
        self.last_decision = {
            "action": "retune", "max_local_prefill_length": new,
            "was": cur, "fleet_crossover_tokens":
                None if xo is None or xo == float("inf") else round(xo, 1),
            "at": time.time()}
        logger.info("disagg threshold retuned %d → %d (prefill queue "
                    "depth %d)", cur, new, signals.prefill_queue_depth)

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        return {
            "namespace": self.endpoint.namespace,
            "endpoint": self.endpoint.path,
            "paused": self.paused,
            "slo": dataclasses.asdict(self.slo),
            "signals": (self.last_signals.to_dict()
                        if self.last_signals is not None else None),
            "workers": {
                "live": [f"{i:x}" for i in self._client.instance_ids()],
                "draining": [f"{i:x}" for i in
                             self._client.draining_ids()],
            } if self._client is not None else {},
            "prefill_workers": {
                "live": [f"{i:x}" for i in
                         self._prefill_client.instance_ids()],
                "draining": [f"{i:x}" for i in
                             self._prefill_client.draining_ids()],
            } if self._prefill_client is not None else {},
            "disagg_threshold": self.disagg_threshold,
            "fleet_crossover_tokens": (
                None if self.fleet_crossover_tokens is None
                or self.fleet_crossover_tokens == float("inf")
                else round(self.fleet_crossover_tokens, 1)),
            "last_decision": self.last_decision,
            "counters": dict(self.counters),
            "at": time.time(),
        }

    async def _status_loop(self) -> None:
        from ..runtime.tracing import detach_trace
        detach_trace()
        key = status_key(self.endpoint.namespace)
        lease = await self.runtime.primary_lease()
        while True:
            try:
                await self.runtime.store.kv_put(
                    key, json.dumps(self.status()).encode(),
                    lease_id=lease.id)
            except Exception:  # noqa: BLE001
                logger.exception("planner status publish failed")
            await asyncio.sleep(self.cfg.status_interval_s)
