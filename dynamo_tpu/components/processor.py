"""KV-aware processor frontend: OpenAI HTTP → tokenize → KV-routed dispatch
to token-protocol workers → detokenize.

Reference: the Processor + Router components of the disagg reference graph
(examples/llm/components/{processor,kv_router}.py; SURVEY.md §2.6, §3.3) —
preprocessing happens *before* routing so the router can match the prompt's
block hashes against its radix index. Run:

    python -m dynamo_tpu.components.processor \
        --runtime-server HOST:PORT --model-path DIR \
        --endpoint dyn://dynamo/worker/generate --port 8080

Workers: `python -m dynamo_tpu.launch.run in=dyn://dynamo/worker/generate \
out=jax --protocol tokens --model-path DIR --runtime-server HOST:PORT`.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os

logger = logging.getLogger("dynamo_tpu.components.processor")


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-processor")
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--model-path", required=True)
    p.add_argument("--model-name")
    p.add_argument("--endpoint", default="dyn://dynamo/worker/generate")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="must match the workers' engine block size")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)

    from ..llm.backend import Backend
    from ..llm.engines.kv_routed import KvRoutedEngine
    from ..llm.http import HttpService
    from ..llm.model_card import ModelDeploymentCard
    from ..llm.preprocessor import OpenAIPreprocessor
    from ..runtime import link
    from ..runtime.distributed import DistributedRuntime, Endpoint

    name = args.model_name or os.path.basename(
        os.path.normpath(args.model_path))
    runtime = await DistributedRuntime.connect(args.runtime_server)
    mdc = await asyncio.to_thread(ModelDeploymentCard.from_local_path,
                                  args.model_path, display_name=name)
    endpoint = Endpoint.parse_path(runtime, args.endpoint)
    engine = await KvRoutedEngine.start(endpoint,
                                        block_size=args.kv_block_size)
    # router-side tier-weight retune (llmctl kv set-weights): the
    # scheduler's TIER_WEIGHTS follow the kvtier/weights/{ns} key live
    from ..llm.kv.admin import watch_weights_loop
    weights_task = asyncio.get_running_loop().create_task(
        watch_weights_loop(runtime, endpoint.namespace),
        name="kv-weights-watch")
    pipeline = link(OpenAIPreprocessor(mdc), Backend(mdc), engine)
    svc = HttpService(port=args.port, host=args.host)
    svc.manager.add_chat_model(name, pipeline)
    svc.manager.add_completion_model(name, pipeline)
    logger.info("processor serving %s on %s:%d → %s (KV-aware)",
                name, args.host, args.port, args.endpoint)
    try:
        await svc.run_forever()
    finally:
        weights_task.cancel()
        await engine.close()
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
