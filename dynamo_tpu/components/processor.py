"""KV-aware processor frontend: OpenAI HTTP → tokenize → KV-routed dispatch
to token-protocol workers → detokenize.

Reference: the Processor + Router components of the disagg reference graph
(examples/llm/components/{processor,kv_router}.py; SURVEY.md §2.6, §3.3) —
preprocessing happens *before* routing so the router can match the prompt's
block hashes against its radix index.

Two modes:

- single model (the historical shape)::

    python -m dynamo_tpu.components.processor \
        --runtime-server HOST:PORT --model-path DIR \
        --endpoint dyn://dynamo/worker/generate --port 8080

- multi-model multiplexing (``--registry``): the OpenAI ``model`` field
  resolves through the model registry (llm/registry.py): every card
  under ``modelreg/cards/`` gets its OWN pipeline — preprocessor from
  the card's tokenizer ref, and a per-model :class:`KvRoutedEngine`
  whose KvIndexer/KvScheduler watch THAT card's worker fleet at the
  card's block size. Cards added/removed (``llmctl model {add,rm}``, or
  self-registering workers) start/stop serving live; an unknown model
  404s at the HTTP layer. One frontend, N models, N independent
  routing planes.

Workers: `python -m dynamo_tpu.launch.run in=dyn://dynamo/worker/generate \
out=jax --protocol tokens --model-path DIR --runtime-server HOST:PORT`.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os

logger = logging.getLogger("dynamo_tpu.components.processor")


class ModelMux:
    """Registry-driven model multiplexer: one pipeline + KV routing
    plane per registry card, kept in sync with ``modelreg/cards/``."""

    def __init__(self, runtime, manager, default_block_size: int = 16):
        self.runtime = runtime
        self.manager = manager
        self.default_block_size = default_block_size
        self.watcher = None
        # name → (engine, card) — engine is the per-model KvRoutedEngine
        self._engines: dict = {}

    async def start(self) -> "ModelMux":
        from ..llm.registry import RegistryWatcher
        self.watcher = await RegistryWatcher(
            self.runtime, self._on_card, self._on_removed).start()
        return self

    async def _build_pipeline(self, card):
        from ..llm.backend import Backend
        from ..llm.engines.kv_routed import KvRoutedEngine
        from ..llm.model_card import ModelDeploymentCard
        from ..llm.preprocessor import OpenAIPreprocessor
        from ..runtime import link
        from ..runtime.distributed import Endpoint

        if card.model_path:
            mdc = await asyncio.to_thread(
                ModelDeploymentCard.from_local_path, card.model_path,
                display_name=card.name)
        else:
            raise ValueError(f"registry card {card.name!r} has no "
                             f"model_path — the frontend cannot "
                             f"preprocess for it")
        endpoint = Endpoint.parse_path(self.runtime, card.endpoint)
        engine = await KvRoutedEngine.start(
            endpoint,
            block_size=card.kv_block_size or self.default_block_size)
        pipeline = link(OpenAIPreprocessor(mdc), Backend(mdc), engine)
        return engine, pipeline

    async def _on_card(self, card) -> None:
        old = self._engines.pop(card.name, None)
        try:
            engine, pipeline = await self._build_pipeline(card)
        except Exception:  # noqa: BLE001 — one bad card must not kill the mux
            logger.exception("registry card %s rejected", card.name)
            if old is not None:
                self._engines[card.name] = old   # keep serving the old rev
            return
        self._engines[card.name] = (engine, card)
        import dataclasses
        card_d = dataclasses.asdict(card)
        types = card.types()
        if "chat" in types:
            self.manager.add_chat_model(card.name, pipeline, card=card_d)
        if "completion" in types:
            self.manager.add_completion_model(card.name, pipeline,
                                              card=card_d)
        if old is not None:
            await old[0].close()
        logger.info("model %s (rev %d) → %s (program_set %s)",
                    card.name, card.revision, card.endpoint,
                    card.program_set)

    async def _on_removed(self, name: str) -> None:
        self.manager.remove_model(name)
        old = self._engines.pop(name, None)
        if old is not None:
            await old[0].close()
        logger.info("model %s removed from the serving plane", name)

    def tenant_counters(self) -> dict:
        """Aggregated per-tenant admission counters across every
        model's routing plane (the /metrics tenant feed)."""
        out: dict = {}
        for engine, _card in self._engines.values():
            for t, c in engine.admission.counters().items():
                agg = out.setdefault(t, {"admitted": 0, "throttled": 0})
                agg["admitted"] += c["admitted"]
                agg["throttled"] += c["throttled"]
        return out

    async def stop(self) -> None:
        if self.watcher is not None:
            await self.watcher.stop()
        for engine, _card in self._engines.values():
            await engine.close()
        self._engines.clear()


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-processor")
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--model-path",
                   help="single-model mode: HF-style model dir")
    p.add_argument("--model-name")
    p.add_argument("--registry", action="store_true",
                   help="multi-model mode: serve every model registry "
                        "card (llm/registry.py), resolved live — the "
                        "OpenAI 'model' field multiplexes onto the "
                        "card's worker fleet")
    p.add_argument("--endpoint", default="dyn://dynamo/worker/generate")
    p.add_argument("--namespace", default="dynamo",
                   help="namespace whose tenant policy table this "
                        "frontend watches (llmctl tenant)")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="must match the workers' engine block size "
                        "(single-model mode; registry cards carry "
                        "their own)")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)

    if not args.registry and not args.model_path:
        raise SystemExit("pass --model-path (single model) or "
                         "--registry (multi-model)")

    from ..llm.http import HttpService
    from ..runtime.distributed import DistributedRuntime

    runtime = await DistributedRuntime.connect(args.runtime_server)
    svc = HttpService(port=args.port, host=args.host)
    loop = asyncio.get_running_loop()
    # router-side live retunes: tier weights (llmctl kv set-weights) and
    # tenant policies (llmctl tenant set-weight/set-quota)
    from ..llm.kv.admin import watch_weights_loop
    from ..llm.tenancy import watch_tenants_loop
    watch_tasks = [
        loop.create_task(watch_weights_loop(runtime, args.namespace),
                         name="kv-weights-watch"),
        loop.create_task(watch_tenants_loop(runtime, args.namespace),
                         name="tenant-watch"),
    ]

    mux = None
    engine = None
    if args.registry:
        mux = await ModelMux(runtime, svc.manager,
                             default_block_size=args.kv_block_size).start()
        logger.info("processor multiplexing the model registry on "
                    "%s:%d (KV-aware, per-model routing planes)",
                    args.host, args.port)
    else:
        from ..llm.backend import Backend
        from ..llm.engines.kv_routed import KvRoutedEngine
        from ..llm.model_card import ModelDeploymentCard
        from ..llm.preprocessor import OpenAIPreprocessor
        from ..runtime import link
        from ..runtime.distributed import Endpoint

        name = args.model_name or os.path.basename(
            os.path.normpath(args.model_path))
        mdc = await asyncio.to_thread(ModelDeploymentCard.from_local_path,
                                      args.model_path, display_name=name)
        endpoint = Endpoint.parse_path(runtime, args.endpoint)
        engine = await KvRoutedEngine.start(endpoint,
                                            block_size=args.kv_block_size)
        pipeline = link(OpenAIPreprocessor(mdc), Backend(mdc), engine)
        svc.manager.add_chat_model(name, pipeline)
        svc.manager.add_completion_model(name, pipeline)
        logger.info("processor serving %s on %s:%d → %s (KV-aware)",
                    name, args.host, args.port, args.endpoint)
    try:
        await svc.run_forever()
    finally:
        for t in watch_tasks:
            t.cancel()
        if mux is not None:
            await mux.stop()
        if engine is not None:
            await engine.close()
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
