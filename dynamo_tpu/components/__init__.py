"""Standalone service components (reference components/{http,metrics} +
examples/llm/components)."""
