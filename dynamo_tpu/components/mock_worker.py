"""Mock worker: the zero-hardware routing/metrics test fixture.

Reference: components/metrics/src/bin/mock_worker.rs — a worker publishing
synthetic ForwardPassMetrics and KV events so the router/metrics stack runs
with no GPUs (SURVEY.md §4 "mock worker" tier). Ours additionally *serves*
the token protocol with an echo engine and publishes stored-block events for
every prompt it sees, so a KV-aware router's radix tree fills exactly as it
would against a real engine's prefix cache."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
from typing import Optional

from ..llm.engines.echo import EchoEngineCore
from ..llm.kv.blocks import TokenBlockSequence
from ..llm.kv_router.protocols import ForwardPassMetrics
from ..llm.kv_router.publisher import KvEventPublisher
from ..llm.protocols.annotated import encode_annotated_json
from ..llm.protocols.common import PreprocessedRequest
from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.engine import AsyncEngine, ManyOut, SingleIn

logger = logging.getLogger("dynamo_tpu.components.mock_worker")

__all__ = ["MockTokenWorker"]


class _EchoWithKvEvents(AsyncEngine):
    """Echo engine that mimics a paged engine's prefix-cache events: each
    prompt's full blocks are published as stored (chained hashes)."""

    def __init__(self, publisher: KvEventPublisher, block_size: int):
        self.inner = EchoEngineCore()
        self.publisher = publisher
        self.block_size = block_size
        self.requests_served = 0

    async def generate(self, request: SingleIn) -> ManyOut:
        pre: PreprocessedRequest = request.data
        self.requests_served += 1
        seq = TokenBlockSequence(self.block_size, pre.token_ids)
        parent = None
        for i, (sh, bh) in enumerate(zip(seq.sequence_hashes,
                                         seq.block_hashes)):
            self.publisher.publish_stored(i, sh, bh, parent)
            parent = seq.sequence_hashes[i]
        return await self.inner.generate(request)


class MockTokenWorker:
    """Embeddable fixture: serve a token-protocol endpoint with synthetic
    metrics + KV events."""

    def __init__(self, runtime: DistributedRuntime, endpoint_path: str,
                 block_size: int = 16,
                 metrics: Optional[ForwardPassMetrics] = None):
        self.runtime = runtime
        self.endpoint = Endpoint.parse_path(runtime, endpoint_path)
        self.block_size = block_size
        self.metrics = metrics or ForwardPassMetrics(
            request_active_slots=0, request_total_slots=8,
            kv_active_blocks=0, kv_total_blocks=1024)
        self.engine: Optional[_EchoWithKvEvents] = None
        self.server = None

    @property
    def worker_id(self) -> int:
        return self.server.lease_id

    async def start(self) -> "MockTokenWorker":
        component = self.runtime.namespace(
            self.endpoint.namespace).component(self.endpoint.component)
        lease = await self.runtime.primary_lease()

        async def sink(ev) -> None:
            await component.publish_event("kv_events", ev)

        publisher = KvEventPublisher(worker_id=lease.id, sink=sink)
        self.engine = _EchoWithKvEvents(publisher, self.block_size)
        self.server = await self.endpoint.serve(
            self.engine,
            decode_req=lambda raw: PreprocessedRequest.from_dict(
                json.loads(raw)),
            encode_resp=encode_annotated_json,
            stats_handler=lambda: self.metrics.to_dict(),
            stats_interval=0.2)
        return self

    async def stop(self) -> None:
        if self.server is not None:
            await self.server.stop()


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-mock-worker")
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--endpoint", default="dyn://dynamo/worker/generate")
    p.add_argument("--kv-block-size", type=int, default=16)
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging()
    runtime = await DistributedRuntime.connect(args.runtime_server)
    worker = await MockTokenWorker(runtime, args.endpoint,
                                   block_size=args.kv_block_size).start()
    logger.info("mock worker %x serving %s", worker.worker_id, args.endpoint)
    try:
        await asyncio.Event().wait()
    finally:
        await worker.stop()
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
