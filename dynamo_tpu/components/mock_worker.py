"""Mock worker: the zero-hardware routing/metrics test fixture.

Reference: components/metrics/src/bin/mock_worker.rs — a worker publishing
synthetic ForwardPassMetrics and KV events so the router/metrics stack runs
with no GPUs (SURVEY.md §4 "mock worker" tier). Ours additionally *serves*
the token protocol with an echo engine and publishes stored-block events for
every prompt it sees, so a KV-aware router's radix tree fills exactly as it
would against a real engine's prefix cache."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
from typing import Optional

from ..llm.engines.echo import EchoEngineCore
from ..llm.kv.blocks import TokenBlockSequence
from ..llm.kv_router.protocols import ForwardPassMetrics
from ..llm.kv_router.publisher import KvEventPublisher
from ..llm.protocols.annotated import encode_annotated_json
from ..llm.protocols.common import PreprocessedRequest
from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.engine import AsyncEngine, ManyOut, SingleIn

logger = logging.getLogger("dynamo_tpu.components.mock_worker")

__all__ = ["MockTokenWorker"]


class _EchoWithKvEvents(AsyncEngine):
    """Echo engine that mimics a paged engine's prefix-cache events: each
    prompt's full blocks are published as stored (chained hashes). Tracks
    live in-flight streams so the worker's scraped ForwardPassMetrics show
    real occupancy — the planner's drain-wait and scale signals read it."""

    def __init__(self, publisher: KvEventPublisher, block_size: int,
                 spec_k: int = 0, spec_acceptance: float = 0.75,
                 delay_fn=None):
        # optional per-request service delay (BehaviorProfile slow-start
        # / latency inflation — sim/profiles.py, shared with the fleet
        # simulator's worker model)
        self.delay_fn = delay_fn
        self.inner = EchoEngineCore()
        self.publisher = publisher
        self.block_size = block_size
        self.requests_served = 0
        self.active = 0
        # synthetic speculative-decoding counters: each request "drafts"
        # spec_k tokens and "accepts" the configured fraction, so the
        # nv_llm_spec_* metrics path (engine/spec/ → stats payload →
        # MetricsAggregatorService) is exercisable with zero hardware
        self.spec_k = spec_k
        self.spec_acceptance = spec_acceptance
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_steps = 0
        # every (seq_hash, tokens_hash, parent) ever announced, in parent
        # order — replayed by reannounce() after a transient lease expiry
        # (KNOWN_ISSUES kv-router staleness fix)
        self._announced: dict = {}

    def reannounce(self) -> int:
        """Re-publish every stored block (pool-side re-announce hook)."""
        for sh, (bid, th, parent) in self._announced.items():
            self.publisher.publish_stored(bid, sh, th, parent)
        return len(self._announced)

    async def generate(self, request: SingleIn) -> ManyOut:
        pre: PreprocessedRequest = request.data
        self.requests_served += 1
        if self.delay_fn is not None:
            d = self.delay_fn()
            if d > 0:
                await asyncio.sleep(d)
        if self.spec_k > 0:
            self.spec_steps += 1
            self.spec_drafted += self.spec_k
            self.spec_accepted += round(self.spec_k * self.spec_acceptance)
        seq = TokenBlockSequence(self.block_size, pre.token_ids)
        parent = None
        for i, (sh, bh) in enumerate(zip(seq.sequence_hashes,
                                         seq.block_hashes)):
            self.publisher.publish_stored(i, sh, bh, parent)
            self._announced[sh] = (i, bh, parent)
            parent = seq.sequence_hashes[i]
        stream = await self.inner.generate(request)
        self.active += 1

        async def tracked():
            try:
                async for item in stream:
                    yield item
            finally:
                self.active -= 1

        from ..runtime.engine import ResponseStream
        return ResponseStream(tracked(), request.ctx)


class MockTokenWorker:
    """Embeddable fixture: serve a token-protocol endpoint with synthetic
    metrics + KV events."""

    # class-level defaults so partially-constructed fixtures (the
    # __new__-then-assign shape some stats tests use) still have a
    # coherent profile/_stats surface
    block_size = 16
    _started_mono = 0.0

    def __init__(self, runtime: DistributedRuntime, endpoint_path: str,
                 block_size: int = 16,
                 metrics: Optional[ForwardPassMetrics] = None,
                 spec_k: int = 0, spec_acceptance: float = 0.75,
                 publish_traces: bool = True,
                 synthetic_trace_interval: float = 0.0,
                 profile=None, tenants: int = 0):
        self.runtime = runtime
        self.endpoint = Endpoint.parse_path(runtime, endpoint_path)
        self.block_size = block_size
        # synthetic behavior profile (sim/profiles.py — the SAME
        # vocabulary the fleet simulator's worker model runs, so a
        # scenario rehearsed in simulation replays against this live
        # fixture): slow-start/latency inflate service delays,
        # crash-at-T stops the worker cold, drain-ignore makes it deaf
        # to the planner's drain key (the drain-timeout path).
        from ..sim.profiles import BehaviorProfile
        if isinstance(profile, str):
            profile = BehaviorProfile.parse(profile)
        self.profile = profile or BehaviorProfile()
        self._started_mono: float = 0.0
        self._crash_task = None
        self.crashed = False
        self.metrics = metrics or ForwardPassMetrics(
            request_active_slots=0, request_total_slots=8,
            kv_active_blocks=0, kv_total_blocks=1024)
        self.spec_k = spec_k
        self.spec_acceptance = spec_acceptance
        self.engine: Optional[_EchoWithKvEvents] = None
        self.server = None
        # fleet tracing fixture (components/trace_collector.py): served
        # requests already produce REAL worker traces (ingress opens
        # one per request); publish_traces ships them over the
        # trace_events subject like a real worker would, and
        # synthetic_trace_interval > 0 additionally fabricates plausible
        # traces on a timer — collector + Grafana "Tracing" panels are
        # testable with zero engines AND zero traffic
        # synthetic multi-tenant feed (--tenants N): per-tenant
        # admitted/throttled/kv_blocks/hit_rate stats shaped exactly
        # like a tenancy-enabled EngineCore's tenant_stats payload, so
        # the nv_llm_tenant_* labeled-gauge path and the Grafana
        # "Tenants" row run with zero engines
        self.tenants = tenants
        self.publish_traces = publish_traces
        self.synthetic_trace_interval = synthetic_trace_interval
        self._trace_pub = None
        self._synth_task = None
        self.synthetic_traces_emitted = 0

    @property
    def worker_id(self) -> int:
        return self.server.lease_id

    async def start(self) -> "MockTokenWorker":
        component = self.runtime.namespace(
            self.endpoint.namespace).component(self.endpoint.component)
        lease = await self.runtime.primary_lease()

        async def sink(ev) -> None:
            await component.publish_event("kv_events", ev)

        publisher = KvEventPublisher(worker_id=lease.id, sink=sink)
        import time as _time
        self._started_mono = _time.monotonic()

        def _delay() -> float:
            return self.profile.service_delay_s(
                _time.monotonic() - self._started_mono)

        self.engine = _EchoWithKvEvents(publisher, self.block_size,
                                        spec_k=self.spec_k,
                                        spec_acceptance=self.spec_acceptance,
                                        delay_fn=_delay)
        # transient lease reclaim (daemon blip) → replay the radix index
        # for this worker (KNOWN_ISSUES kv-router staleness fix)
        prev = getattr(self.runtime.store, "on_lease_reclaimed", None)

        def reclaimed(lease_id: int) -> None:
            if prev is not None:
                prev(lease_id)
            if lease_id == lease.id and self.engine is not None:
                n = self.engine.reannounce()
                logger.info("mock worker %x re-announced %d blocks after "
                            "lease reclaim", lease_id, n)

        if hasattr(self.runtime.store, "on_lease_reclaimed"):
            self.runtime.store.on_lease_reclaimed = reclaimed
        self.server = await self.endpoint.serve(
            self.engine,
            decode_req=lambda raw: PreprocessedRequest.from_dict(
                json.loads(raw)),
            encode_resp=encode_annotated_json,
            stats_handler=self._stats,
            stats_interval=0.2)
        if self.publish_traces:
            from .trace_collector import wire_trace_publisher
            self._trace_pub = wire_trace_publisher(component)
        if self.synthetic_trace_interval > 0:
            self._synth_task = asyncio.get_running_loop().create_task(
                self._synthetic_trace_loop(), name="mock-synth-traces")
        if self.profile.drain_ignore:
            # deaf to the planner's drain key: kill the server's drain
            # watch so only the planner's drain-timeout path can retire
            # this worker
            if self.server._drain_task is not None:
                self.server._drain_task.cancel()
                self.server._drain_task = None
        if self.profile.crash_at_s > 0:
            self._crash_task = asyncio.get_running_loop().create_task(
                self._crash_after(self.profile.crash_at_s),
                name="mock-crash-at")
        return self

    async def _crash_after(self, delay_s: float) -> None:
        await asyncio.sleep(delay_s)
        self.crashed = True
        self._crash_task = None     # don't self-cancel inside stop()
        logger.info("mock worker %x crashing (profile crash-at:%g)",
                    self.worker_id, delay_s)
        await self.stop()

    async def _synthetic_trace_loop(self) -> None:
        """Fabricate plausible finished worker traces on a timer — they
        flow through the REAL tracer (ring, sampling, publisher), so the
        whole collector/histogram/Grafana path exercises without any
        traffic at all."""
        import random
        import time as _time

        from ..runtime.tracing import Trace, tracer
        while True:
            await asyncio.sleep(self.synthetic_trace_interval)
            self.synthetic_traces_emitted += 1
            t = Trace(f"synthetic-{self.worker_id:x}-"
                      f"{self.synthetic_traces_emitted}", role="worker")
            now = _time.monotonic()
            queue_ms = random.uniform(0.1, 3.0)
            ttft_ms = queue_ms + random.uniform(5.0, 60.0)
            total_ms = ttft_ms + random.uniform(20.0, 400.0)
            t.start = now - total_ms / 1e3
            t.start_epoch = _time.time() - total_ms / 1e3
            t.origin_ts = t.start_epoch
            t.add_span("engine.queue_wait", t.start,
                       t.start + queue_ms / 1e3)
            t.add_span("engine.accept", t.start, t.start + 1e-3)
            first = t.start + ttft_ms / 1e3
            t.add_span("first_response", first, first)
            t.add_span("respond", t.start + 2e-3, now,
                       synthetic=True)
            tracer.finish(t)

    def _stats(self) -> dict:
        """Base synthetic metrics overlaid with LIVE occupancy, so the
        planner's signals (queue depth, slot pressure, drain-idle) are
        real even against the echo engine."""
        d = self.metrics.to_dict()
        # server._inflight outlives engine.active by the response tail
        # (sentinel + finish), so a drain-wait on these stats can't retire
        # the worker with a stream mid-delivery
        live = max(self.engine.active,
                   len(self.server._inflight) if self.server else 0)
        d["request_active_slots"] = (self.metrics.request_active_slots
                                     + live)
        eng = self.engine
        if eng is not None and eng.spec_drafted > 0:
            # live synthetic speculation counters (see _EchoWithKvEvents)
            # — shaped exactly like a real EngineCore.metrics() payload
            d["spec_drafted_total"] = eng.spec_drafted
            d["spec_accepted_total"] = eng.spec_accepted
            d["spec_acceptance_rate"] = eng.spec_accepted / eng.spec_drafted
            d["spec_accepted_per_step"] = (eng.spec_accepted
                                           / max(eng.spec_steps, 1))
        if eng is not None and not d.get("kv_contiguity_ratio"):
            # synthetic KV-layout gauges (docs/kv_layout.md): a healthy
            # contiguous pool — one free run, every alloc one run, two
            # DMA copies per wave (k + v) — so the nv_llm_kv_frag_* /
            # _attn_dma_* scrape path runs with zero hardware
            d["kv_frag_ratio"] = 0.0
            d["kv_contig_runs"] = 1
            d["kv_contiguity_ratio"] = 1.0
            d["attn_dma_copies_per_wave"] = 2.0
        if eng is not None and not d.get("ragged_fill_ratio"):
            # synthetic ragged-dispatch gauges (docs/ragged_attention.md):
            # a healthy unified-dispatch engine — ~70% token fill, a
            # third of dispatches mixing prefill chunks into the decode
            # batch, saved dispatches growing with served requests — so
            # the nv_llm_ragged_* scrape path and the Grafana "Ragged
            # dispatch" panels run with zero hardware
            d["ragged_fill_ratio"] = 0.7
            d["ragged_mixed_ratio"] = 0.33
            d["ragged_dispatches_saved_total"] = eng.requests_served
            # round 11: a healthy prefetch chain (most first waves
            # covered by a predecessor) and spec draft rows riding the
            # ragged batch, growing with traffic
            d["ragged_prefetch_hit_ratio"] = 0.8
            d["ragged_spec_rows_total"] = 3 * eng.requests_served
        if eng is not None and not d.get("remote_link_gbps"):
            # synthetic KV-fabric gauges (docs/kv_fabric.md): a healthy
            # fabric — some object-tier residency, a ~10 GB/s / 1 ms
            # measured link, zero failures — so the nv_llm_kv_remote_*
            # scrape path and the router's NetKV scoring inputs
            # (kv_bytes_per_block / prefill_tok_per_s) are exercisable
            # with zero hardware
            d["remote_used_blocks"] = eng.requests_served
            d["remote_peer_blocks"] = 4 * eng.requests_served
            d["remote_hit_rate"] = 0.5
            d["remote_link_gbps"] = 10.0
            d["remote_link_rtt_s"] = 1e-3
            d["kv_bytes_per_block"] = 1 << 20
            d["kv_block_size"] = self.block_size
            d["prefill_tok_per_s"] = 5e4
            # round 12: a healthy native dataplane (every fetch rides
            # it, zero JSON fallbacks) and a prefill-publish worker
            # steadily feeding the object tier
            d["remote_dataplane_fetches_total"] = 2 * eng.requests_served
            d["remote_dataplane_fallbacks_total"] = 0
            d["prefill_published_blocks_total"] = 3 * eng.requests_served
        if eng is not None and not d.get("requests_cancelled_total"):
            # round 13: synthetic graceful-degradation counters
            # (docs/chaos.md) — a lightly-chaotic fleet: a few cancels
            # and deadline misses growing with traffic, one tripped peer
            # that recovered (trips > open), a handful of shed spill
            # writes — so the nv_llm_requests_cancelled_total /
            # nv_llm_kv_remote_breaker_* / nv_llm_kv_disk_spill_shed_*
            # scrape path and the Grafana "Degradation" row run with
            # zero engines
            d["requests_cancelled_total"] = max(eng.requests_served // 4,
                                                1)
            d["requests_deadline_exceeded_total"] = \
                eng.requests_served // 8
            d["netstore_deadline_exceeded_total"] = 0
            d["remote_breaker_open_peers"] = 0
            d["remote_breaker_trips_total"] = 1
            d["disk_spill_shed_total"] = eng.requests_served // 6
        if eng is not None and not d.get("disk_capacity_blocks"):
            # synthetic tier-ladder + worker-health gauges: a healthy
            # host/disk ladder (steady stores, warm hit rates, no
            # dropped jobs), a quiet loop-lag probe, and cost-model
            # inputs — every remaining gauge-table field fed so the
            # zero-TPU fixture lights EVERY Grafana panel (the DL010
            # closure: a field the mock can't feed is a panel no
            # no-hardware test can ever prove works)
            served = eng.requests_served
            d["num_requests_waiting"] = max(live - 4, 0)
            d["gpu_cache_usage_perc"] = min(0.1 + 0.01 * live, 0.9)
            d["gpu_prefix_cache_hit_rate"] = 0.45
            d["host_stored_total"] = 2 * served
            d["host_evicted_total"] = served // 2
            d["host_hit_rate"] = 0.55
            d["offload_dropped_jobs_total"] = 0
            d["disk_used_blocks"] = served
            d["disk_capacity_blocks"] = 4096
            d["disk_stored_total"] = served
            d["disk_evicted_total"] = served // 4
            d["disk_hit_rate"] = 0.35
            d["disk_bytes_used"] = served * (1 << 20)
            d["disk_spill_dropped_total"] = 0
            d["remote_capacity_blocks"] = 1 << 16
            d["remote_stored_total"] = 3 * served
            d["remote_fetch_failures_total"] = 0
            d["remote_admission_rejects_total"] = served // 10
            d["kv_defrag_moves_total"] = served // 8
            # a mildly-interleaved pipeline profile (pp=2, K=4 →
            # utilization K·pp/(K·pp+pp-1) = 8/9)
            d["pp_stages"] = 2
            d["pp_microbatch"] = 4
            d["pp_utilization"] = 8 / 9
            d["pp_bubble_fraction"] = 1 / 9
            d["trace_dropped_log_lines_total"] = served // 3
            d["loop_lag_ms"] = 0.4
            d["loop_lag_max_ms"] = 2.5
            d["netstore_retries_total"] = 0
        if eng is not None and not d.get("disagg_stream_layers_total"):
            # round 15: synthetic streaming-handoff gauges (docs/
            # kv_fabric.md "Streaming handoff") — a healthy plane: a
            # 32-layer measured pipeline depth, layers growing with
            # traffic, the occasional degraded stream, transfer mostly
            # hidden — so the nv_llm_disagg_stream_* scrape path and
            # the Grafana "Disagg streaming" panels run with zero
            # hardware
            d["disagg_stream_layers_total"] = 32 * eng.requests_served
            d["disagg_stream_fallbacks_total"] = eng.requests_served // 16
            d["disagg_stream_overlap_ratio"] = 0.85
            d["disagg_stream_layers"] = 32
        tenants = getattr(self, "tenants", 0)
        if eng is not None and tenants > 0:
            # round 14: synthetic per-tenant stats — a Zipf-ish spread
            # where tenant 0 floods (and is the only one throttled),
            # everyone else's hit rate holds (the fair-share story the
            # Grafana "Tenants" row should show)
            served = max(eng.requests_served, 1)
            d["tenant_stats"] = {
                f"t{i:02d}": {
                    "admitted": max(served // (i + 1), 1),
                    "throttled": served // 2 if i == 0 else 0,
                    "kv_blocks": 64 // (i + 1),
                    "hit_rate": 0.3 if i == 0 else 0.6,
                } for i in range(tenants)}
        profile = getattr(self, "profile", None)
        if profile is not None and (profile.slow_start_s > 0
                                    or profile.latency_factor != 1.0):
            # young/slow worker: the published prefill rate tracks the
            # profile's speed factor, so the router's NetKV recompute
            # model and the planner's crossover stats see the ramp
            import time as _time
            f = profile.speed_factor(
                _time.monotonic() - self._started_mono)
            if d.get("prefill_tok_per_s"):
                d["prefill_tok_per_s"] = d["prefill_tok_per_s"] * f
        return d

    @property
    def draining(self) -> bool:
        return self.server is not None and self.server.draining

    async def drain(self) -> None:
        await self.server.set_draining(True)

    async def stop(self) -> None:
        if self._crash_task is not None:
            self._crash_task.cancel()
            self._crash_task = None
        if self._synth_task is not None:
            self._synth_task.cancel()
            self._synth_task = None
        if self._trace_pub is not None:
            # detach from the process tracer (it is a singleton; a
            # dangling hook would publish other fixtures' traces)
            self._trace_pub.close()
            self._trace_pub = None
        if self.server is not None:
            await self.server.stop()


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-mock-worker")
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--endpoint", default="dyn://dynamo/worker/generate")
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--spec-k", type=int, default=0,
                   help="synthetic speculation: drafts per request "
                        "(exercises the nv_llm_spec_* metrics path)")
    p.add_argument("--spec-acceptance", type=float, default=0.75)
    p.add_argument("--synthetic-trace-interval", type=float, default=0.0,
                   help="emit a fabricated worker trace every N seconds "
                        "(exercises the trace collector + Grafana "
                        "'Tracing' row with zero traffic)")
    p.add_argument("--profile", default="",
                   help="synthetic behavior profile (sim/profiles.py), "
                        "e.g. 'slow-start:30', 'crash-at:120', "
                        "'drain-ignore', 'latency:2.5' — comma-joined")
    p.add_argument("--tenants", type=int, default=0,
                   help="publish synthetic per-tenant stats for N "
                        "tenants (exercises the nv_llm_tenant_* "
                        "labeled gauges + Grafana 'Tenants' row with "
                        "zero engines)")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging()
    runtime = await DistributedRuntime.connect(args.runtime_server)
    worker = await MockTokenWorker(
        runtime, args.endpoint, block_size=args.kv_block_size,
        spec_k=args.spec_k, spec_acceptance=args.spec_acceptance,
        synthetic_trace_interval=args.synthetic_trace_interval,
        profile=args.profile, tenants=args.tenants).start()
    logger.info("mock worker %x serving %s", worker.worker_id, args.endpoint)
    try:
        await asyncio.Event().wait()
    finally:
        await worker.stop()
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
