"""Prefill-as-a-Service: a dedicated prefill fleet over the object tier.

PAPERS.md "Prefill-as-a-Service: KVCache of Next-Generation Models Could
Go Cross-Datacenter" argues the subsystem this module implements: prefix
KV is worth computing ONCE, close to cheap compute, and serving to
decode fleets anywhere — across regions — through a durable KV store,
admitted only when the measured fetch beats the measured recompute.

This repo already had every ingredient:

- the **object tier** (llm/kv/remotestore.py ObjectKvBackend) — a
  content-addressed, durable, fleet-shared block store keyed by the
  same chained hashes every KV tier uses;
- the **admission economics** (llm/kv/fabric.AdmissionGate) — decode
  workers price a remote hit with their own measured link + prefill
  rate and recompute when fetching loses;
- the **prefill queue** shape (llm/disagg.PrefillQueue) — at-least-once
  work distribution over the bus.

:class:`PrefillService` is the missing role: ``run.py --role
prefill-publish`` workers pull :class:`PrefillPublishRequest` items
from the ``prefill_publish`` work queue (and answer the same op over a
direct endpoint RPC), run prefill on their own engine, and publish the
prompt's full prefix blocks to the object tier
(EngineCore.publish_prefix_to_remote). There is NO per-request decode
sink and NO handoff stream — the handoff IS the durable store, which is
what makes the role cross-region viable: the publish and the admit may
be minutes and continents apart.

Contrast with the existing disagg ``PrefillWorker`` (llm/disagg.py):
that role serves one decode worker's in-flight request over a dialed
stream (latency-coupled); this role warms a SHARED tier for whole
fleets (latency-decoupled). The planner scales both through the same
``role="prefill"`` actuator (components/planner.py).
"""

from __future__ import annotations

import asyncio
import logging
from collections import OrderedDict
from typing import Optional

from ..engine.core import FINISH_SENTINEL, EngineRequest
from ..engine.sampling import SlotSampling
from ..llm.disagg import PrefillQueue
from ..llm.protocols.disagg import PrefillPublishRequest
from ..runtime.engine import AsyncEngine, ManyOut, ResponseStream

logger = logging.getLogger("dynamo_tpu.components.prefill_service")

__all__ = ["PrefillService", "PREFILL_PUBLISH_QUEUE",
           "PREFILL_PUBLISH_ENDPOINT"]

PREFILL_PUBLISH_QUEUE = "prefill_publish"
PREFILL_PUBLISH_ENDPOINT = "prefill_publish"


class PrefillService(AsyncEngine):
    """One prefill-publish worker: queue consumer + direct RPC server.

    Ops (request = one JSON dict, response = one JSON dict):
    - ``publish``: {"token_ids": [...], "sampling": {...}} → run
      prefill, publish the prefix to the object tier, reply
      {"hashes": [...], "published": n, "first_token": t}. The reply's
      hashes let the caller route follow-up decodes at workers whose
      radix index (or shared object root) already holds the prefix.
    - ``status``: queue depth + cumulative publish counters — the
      prefill-queue signal a planner embedding this service scrapes.
    """

    MAX_DELIVERIES = 3
    # bounded prompt-sighting ledger: beyond this the coldest entries
    # age out (their counts restart — a genuinely hot prompt re-earns
    # its publish in two sightings)
    MAX_TRACKED_PROMPTS = 4096

    def __init__(self, core, runtime,
                 queue: Optional[PrefillQueue] = None,
                 publish_min_hits: int = 2):
        if core.remote_store is None or core.remote_store.object is None:
            raise ValueError(
                "--role prefill-publish needs the durable object tier — "
                "start with --kv-remote-dir pointing at the fleet-shared "
                "root")
        self.core = core
        self.runtime = runtime
        self.queue = queue or PrefillQueue(runtime,
                                           name=PREFILL_PUBLISH_QUEUE)
        # queue-path publish POLICY (direct publish()/RPC calls are an
        # explicit ask and always run): a prompt earns its durable
        # publish on its publish_min_hits-th sighting — EXACTLY that
        # sighting, counter-gated and deterministic (no sampling). The
        # default of 2 skips one-shot prompts (a prefix nobody re-asks
        # for is pure object-tier churn: a prefill + N puts that no
        # decode fleet will ever admit), and the exactly-once trigger
        # plus the in-flight dedupe set keep a thundering herd of
        # identical enqueues from stampeding the engine with duplicate
        # prefills — the herd's first qualifying item publishes, the
        # rest skip (the content-addressed store makes the one publish
        # serve them all).
        self.publish_min_hits = max(int(publish_min_hits), 1)
        self._prompt_hits: "OrderedDict[int, int]" = OrderedDict()
        self._publishing: set = set()
        self._task: Optional[asyncio.Task] = None
        self._inflight: set = set()
        self._stopping = False
        self.publishes_done = 0
        self.publishes_failed = 0
        self.blocks_published = 0
        self.publish_skips = 0

    # --------------------------------------------------------------- core
    async def publish(self, token_ids, sampling: Optional[dict] = None,
                      rid: str = "publish") -> dict:
        """Run prefill for ``token_ids`` on the local engine and publish
        the prompt's full prefix blocks to the object tier. The engine's
        prefix cache makes re-publishing a warm chain nearly free (full
        device hit → no prefill dispatch, content-addressed puts skip)."""
        req = EngineRequest(
            rid=rid, prompt=[int(t) for t in token_ids],
            sampling=SlotSampling(**(sampling or {})),
            max_new_tokens=1, eos_ids=frozenset())
        await self.core.submit(req)
        first_token = None
        while True:
            # bounded receive (DL007): a wedged engine fails the publish
            # RPC to its caller instead of pinning this worker's slot
            out, _ = await asyncio.wait_for(req.out_queue.get(),
                                            timeout=600.0)
            if out is FINISH_SENTINEL:
                break
            first_token = out
        if req.seq is None:
            raise RuntimeError(f"publish request {rid} was never admitted")
        n = await self.core.publish_prefix_to_remote(req.seq)
        self.blocks_published += n
        return {"ok": True,
                "hashes": [int(h) for h in req.seq.sequence_hashes],
                "published": n,
                "first_token": first_token,
                "prefix_hit_tokens": req.prefix_hit_tokens}

    # ------------------------------------------------------ direct RPC op
    async def _handle(self, d: dict) -> dict:
        op = d.get("op", "publish")
        if op == "publish":
            from ..runtime.tracing import Trace, use_trace
            tctx = d.get("trace")
            try:
                if tctx:
                    with use_trace(Trace.from_wire(
                            tctx, tctx.get("trace_id", "?"),
                            role="prefill_publish")) as ptrace:
                        with ptrace.span("prefill.publish",
                                         tokens=len(d.get("token_ids",
                                                          ()))):
                            r = await self.publish(
                                d.get("token_ids", []),
                                d.get("sampling"),
                                rid=d.get("request_id", "publish"))
                else:
                    r = await self.publish(d.get("token_ids", []),
                                           d.get("sampling"),
                                           rid=d.get("request_id",
                                                     "publish"))
                self.publishes_done += 1
                return r
            except Exception as e:  # noqa: BLE001 — per-request isolation
                self.publishes_failed += 1
                logger.exception("prefill publish failed")
                return {"ok": False, "error": str(e)}
        if op == "status":
            try:
                depth = await self.queue.depth()
            except Exception:  # noqa: BLE001 — queue may not exist yet
                depth = 0
            return {"ok": True, "queue_depth": depth, **self.stats()}
        return {"ok": False, "error": f"unknown prefill op {op!r}"}

    async def generate(self, request) -> ManyOut:
        resp = await self._handle(request.data)
        return ResponseStream.from_iterable([resp], request.ctx)

    # ------------------------------------------------------ queue consumer
    async def start(self) -> "PrefillService":
        self._stopping = False
        self._task = asyncio.get_running_loop().create_task(
            self._loop(), name="prefill-publish")
        return self

    async def _loop(self) -> None:
        from ..runtime.tracing import detach_trace
        detach_trace()
        backoff = 0.5
        while not self._stopping:
            try:
                item = await self.queue.dequeue(timeout=0.5)
                backoff = 0.5
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — transient bus errors
                logger.warning("prefill-publish dequeue failed (%s); "
                               "retrying in %.1fs", e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
                continue
            if item is None:
                continue
            t = asyncio.get_running_loop().create_task(
                self._handle_item(item),
                name=f"prefill-publish-{item.id}")
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    def _publish_decision(self, token_ids) -> tuple:
        """Counter-gated queue-path policy (see __init__): returns
        (publish?, key). Deterministic — the publish_min_hits-th
        sighting of a prompt publishes, every other sighting skips
        (earlier: one-shot/too-rare; later: already durable; in-flight:
        herd duplicate)."""
        key = hash(tuple(int(t) for t in token_ids))
        hits = self._prompt_hits.pop(key, 0) + 1
        self._prompt_hits[key] = hits
        while len(self._prompt_hits) > self.MAX_TRACKED_PROMPTS:
            self._prompt_hits.popitem(last=False)
        if key in self._publishing:
            return False, key              # herd duplicate: one in flight
        return hits == self.publish_min_hits, key

    async def _handle_item(self, item) -> None:
        try:
            ppr = PrefillPublishRequest.from_json(item.payload)
        except Exception:  # noqa: BLE001
            logger.exception("undecodable prefill-publish item %d", item.id)
            await self.queue.ack(item.id)
            return
        publish, key = self._publish_decision(ppr.token_ids)
        if not publish:
            self.publish_skips += 1
            await self.queue.ack(item.id)
            return
        self._publishing.add(key)
        try:
            await self._handle({"op": "publish",
                                "request_id": ppr.request_id,
                                "token_ids": ppr.token_ids,
                                "sampling": ppr.sampling,
                                "trace": ppr.trace})
            await self.queue.ack(item.id)
        except Exception as e:  # noqa: BLE001 — engine-level failure
            logger.warning("prefill-publish item %d failed (%s)",
                           item.id, e)
            if item.deliveries >= self.MAX_DELIVERIES:
                await self.queue.ack(item.id)   # bounded: drop poison work
            else:
                await self.queue.nack(item.id)
        finally:
            self._publishing.discard(key)

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {"prefill_publishes_done": self.publishes_done,
                "prefill_publishes_failed": self.publishes_failed,
                "prefill_published_blocks_total": self.blocks_published,
                "prefill_publish_skipped_total": self.publish_skips,
                "inflight": len(self._inflight)}

    async def drain(self) -> None:
        """Planner drain: stop pulling NEW queue items, finish in-flight
        publishes (durable puts are never cut mid-write — the object
        store's tmp→fsync→rename keeps partial work invisible)."""
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._inflight:
            await asyncio.gather(*list(self._inflight),
                                 return_exceptions=True)

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in list(self._inflight):
            t.cancel()
