"""Standalone metrics aggregation service.

Reference: components/metrics (components/metrics/src/main.rs:26-210,
src/lib.rs) — a service that (a) subscribes the routers' KV-hit-rate event
subject, (b) scrapes every worker instance's ForwardPassMetrics stats, and
(c) exposes the merged picture as Prometheus text for Grafana/alerting
(deploy/metrics/{grafana.json,prometheus.yml}). Runs with zero TPUs against
the mock worker (SURVEY.md §4's no-GPU fixture).

Usage (module CLI)::

    python -m dynamo_tpu.components.metrics dyn://ns/component/endpoint \
        --daemon 127.0.0.1:5600 --port 9091
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Dict, Optional, Set

from prometheus_client import (CollectorRegistry, Counter, Gauge,
                               generate_latest)

from ..llm.kv_router.protocols import (KV_HIT_RATE_SUBJECT,
                                       ForwardPassMetrics)
from ..runtime.distributed import DistributedRuntime, Endpoint

logger = logging.getLogger("dynamo_tpu.components.metrics")

PREFIX = "nv_llm_kv"

_GAUGE_FIELDS = (
    "request_active_slots", "request_total_slots", "kv_active_blocks",
    "kv_total_blocks", "num_requests_waiting", "gpu_cache_usage_perc",
    "gpu_prefix_cache_hit_rate",
)

# speculative decoding (engine/spec/): ForwardPassMetrics field →
# exported metric name (the nv_llm_spec_* family the planner and the
# Grafana speculation panel scrape)
_SPEC_GAUGES = {
    "spec_acceptance_rate": "nv_llm_spec_acceptance_rate",
    "spec_accepted_per_step": "nv_llm_spec_accepted_per_step",
    "spec_drafted_total": "nv_llm_spec_drafted_tokens",
    "spec_accepted_total": "nv_llm_spec_accepted_tokens",
}

# contiguity-aware KV layout (llm/kv/pool.py run-tracking allocator +
# engine/attention.py run-coalesced DMA; docs/kv_layout.md):
# ForwardPassMetrics field → exported metric name. The Grafana "KV
# layout" row plots frag_ratio against dma-copies-per-wave so a
# fragmenting pool (rising copies, coalescing losing its DMA win) is
# visible before it costs step time; defrag_moves_total confirms the
# compaction pass is actually reclaiming contiguity.
_LAYOUT_GAUGES = {
    "kv_frag_ratio": "nv_llm_kv_frag_ratio",
    "kv_contig_runs": "nv_llm_kv_contig_runs",
    "kv_contiguity_ratio": "nv_llm_kv_contiguity_ratio",
    "kv_defrag_moves_total": "nv_llm_kv_defrag_moves_total",
    "attn_dma_copies_per_wave": "nv_llm_kv_attn_dma_copies_per_wave",
}

# pipeline parallelism (parallel/pipeline_parallel.py):
# ForwardPassMetrics field → exported metric name. Stage count and
# microbatch slots are topology facts; utilization/bubble are the
# dispatch-level interleave model (K·pp/(K·pp+pp-1) and complement) —
# the Grafana "Pipeline" row plots them so a misconfigured K (deep
# bubble) is visible at a glance.
_PP_GAUGES = {
    "pp_stages": "nv_llm_pp_stages",
    "pp_microbatch": "nv_llm_pp_microbatch_slots",
    "pp_utilization": "nv_llm_pp_steady_state_utilization",
    "pp_bubble_fraction": "nv_llm_pp_bubble_fraction",
}

# KV tier ladder (host DRAM tier + persistent disk G3 tier):
# ForwardPassMetrics field → exported metric name. The host counters
# were previously module-local only (llm/kv/offload.py stats); now they
# ride the same scrape as everything else, next to the disk gauges and
# the two backpressure drop counters (offload write-back queue + disk
# spill queue) the Grafana "KV tiers" row alerts on.
_TIER_GAUGES = {
    "host_stored_total": "nv_llm_kv_host_stored_blocks_total",
    "host_evicted_total": "nv_llm_kv_host_evicted_blocks_total",
    "host_hit_rate": "nv_llm_kv_host_hit_rate",
    "offload_dropped_jobs_total": "nv_llm_kv_host_offload_dropped_jobs_total",
    "disk_used_blocks": "nv_llm_kv_disk_used_blocks",
    "disk_capacity_blocks": "nv_llm_kv_disk_capacity_blocks",
    "disk_stored_total": "nv_llm_kv_disk_stored_blocks_total",
    "disk_evicted_total": "nv_llm_kv_disk_evicted_blocks_total",
    "disk_hit_rate": "nv_llm_kv_disk_hit_rate",
    "disk_bytes_used": "nv_llm_kv_disk_bytes_used",
    "disk_spill_dropped_total": "nv_llm_kv_disk_spill_dropped_jobs_total",
}

# unified ragged dispatch (engine/ragged.py + docs/ragged_attention.md):
# ForwardPassMetrics field → exported metric name. The Grafana "Ragged
# dispatch" panel plots fill ratio (how close each unified dispatch
# runs to its compiled token capacity — LOW fill under load means the
# capacity is oversized or admissions are starving) next to the
# mixed-batch ratio (prefill chunks actually riding decode dispatches —
# the batch-boundary bubbles being eliminated) and the cumulative
# split-path dispatches the packing saved. Round 11 adds the
# cross-sequence wave-prefetch hit ratio (first waves a predecessor's
# last wave already covered — LOW under load means dispatches carry too
# few concurrent spans to chain) and the cumulative draft rows that
# rode ragged dispatches as speculative spans.
_RAGGED_GAUGES = {
    "ragged_fill_ratio": "nv_llm_ragged_fill_ratio",
    "ragged_mixed_ratio": "nv_llm_ragged_mixed_batch_ratio",
    "ragged_dispatches_saved_total": "nv_llm_ragged_dispatches_saved_total",
    "ragged_prefetch_hit_ratio": "nv_llm_ragged_prefetch_hit_ratio",
    "ragged_spec_rows_total": "nv_llm_ragged_spec_rows_total",
}

# fleet tracing + engine flight recorder (runtime/tracing.py sampling
# counter + engine/flight_recorder.py loop-lag probe): dropped log
# lines rise by design when sampling is on; loop lag rising means the
# ENGINE loop is being blocked (sync I/O, long host glue) — the most
# actionable single gauge on a slow worker. The latency HISTOGRAMS
# (TTFT/ITL/queue-wait with trace_id exemplars) live on the trace
# collector, not here — they are fed per trace, not per scrape.
_TRACE_GAUGES = {
    "trace_dropped_log_lines_total": "nv_llm_trace_dropped_log_lines_total",
    "loop_lag_ms": "nv_llm_engine_loop_lag_ms",
    "loop_lag_max_ms": "nv_llm_engine_loop_lag_max_ms",
}

# remote (G4) fleet KV fabric (llm/kv/remotestore.py + fabric.py):
# ForwardPassMetrics field → exported metric name. The Grafana "KV
# fabric" row plots tier occupancy and hit rate next to the MEASURED
# link model (decay-averaged peer gbps/rtt) and the two health signals
# worth alerting on: fetch failures (peers vanishing mid-fetch — the
# engine recomputes, but rising failures mean churn) and admission
# rejects (the latency gate refusing hits — expected on slow links,
# suspicious on fast ones). netstore retries ride along: the same
# daemon link the fabric's discovery depends on.
_REMOTE_GAUGES = {
    "remote_used_blocks": "nv_llm_kv_remote_used_blocks",
    "remote_capacity_blocks": "nv_llm_kv_remote_capacity_blocks",
    "remote_peer_blocks": "nv_llm_kv_remote_peer_blocks",
    "remote_stored_total": "nv_llm_kv_remote_stored_blocks_total",
    "remote_hit_rate": "nv_llm_kv_remote_hit_rate",
    "remote_fetch_failures_total": "nv_llm_kv_remote_fetch_failures_total",
    "remote_admission_rejects_total":
        "nv_llm_kv_remote_admission_rejects_total",
    "remote_link_gbps": "nv_llm_kv_remote_link_gbps",
    "remote_link_rtt_s": "nv_llm_kv_remote_link_rtt_seconds",
    # native KV dataplane + prefill-as-a-service (round 12): fetches
    # riding the C++ data plane vs the base64-over-JSON fallback, and
    # prefix blocks published to the object tier by prefill-publish
    # workers (components/prefill_service.py)
    "remote_dataplane_fetches_total":
        "nv_llm_kv_remote_dataplane_fetches_total",
    "remote_dataplane_fallbacks_total":
        "nv_llm_kv_remote_dataplane_fallbacks_total",
    "prefill_published_blocks_total":
        "nv_llm_kv_remote_prefill_published_blocks_total",
    "netstore_retries_total": "nv_llm_netstore_retries_total",
}

# chaos-hardening / graceful degradation (runtime/faults.py failpoints,
# end-to-end deadlines/cancellation, fabric circuit breaker —
# docs/chaos.md): ForwardPassMetrics field → exported metric name. The
# Grafana "Degradation" row plots cancelled + deadline-exceeded next to
# the breaker state (open peers / cumulative trips) and the two "the
# fleet is shedding instead of hanging" signals: spill writes shed on
# disk pressure and netstore calls that burned their whole deadline
# against a partitioned daemon.
_DEGRADE_GAUGES = {
    "requests_cancelled_total": "nv_llm_requests_cancelled_total",
    "requests_deadline_exceeded_total":
        "nv_llm_requests_deadline_exceeded_total",
    "netstore_deadline_exceeded_total":
        "nv_llm_netstore_deadline_exceeded_total",
    "remote_breaker_open_peers": "nv_llm_kv_remote_breaker_open_peers",
    "remote_breaker_trips_total":
        "nv_llm_kv_remote_breaker_trips_total",
    "disk_spill_shed_total": "nv_llm_kv_disk_spill_shed_writes_total",
}


# fetch-vs-recompute cost model (kv_router/scoring.py
# network_adjusted_overlap / crossover_tokens): the three fields the
# router and planner price candidates with. Exporting them closes the
# metrics plane (DL010): the crossover inputs are debuggable per worker
# next to the link gauges instead of living only inside routing
# decisions — a worker advertising kv_block_size=0 (old payload) or a
# wildly-off prefill rate is visible at a glance.
_COST_GAUGES = {
    "kv_bytes_per_block": "nv_llm_kv_bytes_per_block",
    "prefill_tok_per_s": "nv_llm_prefill_tok_per_s",
    "kv_block_size": "nv_llm_kv_block_size_tokens",
}


# streaming layer-wise KV handoff (llm/kv/stream.py; docs/kv_fabric.md
# "Streaming handoff"): ForwardPassMetrics field → exported metric name.
# The Grafana "Disagg streaming" panels plot the cumulative layers this
# decode worker progressively scattered and the degradations (torn frame
# → monolithic fill, dead stream → cold recompute; rising fallbacks mean
# a flaky handoff plane) next to the two pricing inputs: the measured
# overlap ratio (fraction of stream-onboard wall time spent on hidden
# prep/scatter work rather than exposed wire waiting — near 1.0 means
# the transfer is fully hidden behind compute) and the measured
# streaming depth the router's overlap credit divides by.
_DISAGG_STREAM_GAUGES = {
    "disagg_stream_layers_total": "nv_llm_disagg_stream_layers_total",
    "disagg_stream_fallbacks_total":
        "nv_llm_disagg_stream_fallbacks_total",
    "disagg_stream_overlap_ratio": "nv_llm_disagg_stream_overlap_ratio",
    "disagg_stream_layers": "nv_llm_disagg_stream_layers",
}


# multi-tenant serving plane (llm/tenancy.py; docs/multi_tenant.md):
# ForwardPassMetrics.tenant_stats {tenant: {field: value}} → one series
# per (worker, tenant). The Grafana "Tenants" row plots per-tenant
# admitted vs throttled (a flooding tenant shows throttles rising while
# everyone else's admissions hold — the fair-share contract visualized)
# next to per-tenant resident KV blocks (quota headroom) and prefix hit
# rate (the isolation guarantee: one tenant's eviction storm must not
# crater another's curve).
_TENANT_GAUGES = {
    "admitted": "nv_llm_tenant_admitted_total",
    "throttled": "nv_llm_tenant_throttled_total",
    "kv_blocks": "nv_llm_tenant_kv_blocks",
    "hit_rate": "nv_llm_tenant_hit_rate",
}


class MetricsAggregatorService:
    """Aggregates worker load + router hit-rate into one Prometheus registry.

    One instance watches one logical endpoint (namespace/component/endpoint);
    workers appear/disappear with their leases and their gauge series follow.
    """

    def __init__(self, endpoint: Endpoint, scrape_interval: float = 1.0,
                 registry: Optional[CollectorRegistry] = None,
                 collector=None):
        self.endpoint = endpoint
        self.scrape_interval = scrape_interval
        self.registry = registry or CollectorRegistry()
        # fleet trace collector (components/trace_collector.py): fed by
        # the trace_events subscription, serves /traces/{id} (stitched
        # tree + Perfetto export) and owns the TTFT/ITL/queue-wait
        # histograms whose buckets carry trace_id exemplars
        if collector is None:
            from .trace_collector import TraceCollector
            collector = TraceCollector(registry=self.registry)
        self.collector = collector
        labels = ["component", "endpoint", "worker_id"]
        self._gauges: Dict[str, Gauge] = {
            f: Gauge(f"{PREFIX}_{f}", f"worker {f} (scraped stats)",
                     labels, registry=self.registry)
            for f in _GAUGE_FIELDS}
        self._spec_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"speculative decoding: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _SPEC_GAUGES.items()}
        self._pp_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"pipeline parallelism: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _PP_GAUGES.items()}
        self._tier_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"KV tier ladder: worker {f} (scraped stats)",
                     labels, registry=self.registry)
            for f, name in _TIER_GAUGES.items()}
        self._layout_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"KV layout/contiguity: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _LAYOUT_GAUGES.items()}
        self._remote_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"KV fabric (remote tier): worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _REMOTE_GAUGES.items()}
        self._ragged_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"ragged dispatch: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _RAGGED_GAUGES.items()}
        self._trace_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"fleet tracing: worker {f} (scraped stats)",
                     labels, registry=self.registry)
            for f, name in _TRACE_GAUGES.items()}
        self._degrade_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"graceful degradation: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _DEGRADE_GAUGES.items()}
        self._cost_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"fetch-vs-recompute cost model: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _COST_GAUGES.items()}
        self._disagg_stream_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"streaming KV handoff: worker {f} "
                     "(scraped stats)", labels, registry=self.registry)
            for f, name in _DISAGG_STREAM_GAUGES.items()}
        self._tenant_gauges: Dict[str, Gauge] = {
            f: Gauge(name, f"multi-tenant serving: per-tenant {f} "
                     "(scraped stats)", labels + ["tenant"],
                     registry=self.registry)
            for f, name in _TENANT_GAUGES.items()}
        self._seen_tenants: Dict[int, Set[str]] = {}
        self.hit_isl_blocks = Counter(
            f"{PREFIX}_hit_rate_isl_blocks_total",
            "Routing decisions: total request blocks (ISL)",
            labels, registry=self.registry)
        self.hit_overlap_blocks = Counter(
            f"{PREFIX}_hit_rate_overlap_blocks_total",
            "Routing decisions: blocks already held by the chosen worker",
            labels, registry=self.registry)
        self._seen_workers: Set[int] = set()
        self._client = None
        self._sub = None
        self._trace_sub = None
        self._tasks: list = []
        self.events_received = 0
        self.pushes = 0
        self.latest: Dict[int, ForwardPassMetrics] = {}
        # planner observability (components/planner.py): decision counters
        # + live signals scraped from the planner/status/* keys, exported
        # per namespace; /planner serves the raw snapshots
        self.planner_status: Dict[str, dict] = {}
        self._planner_decisions = Gauge(
            f"{PREFIX}_planner_decisions", "Planner decision counters "
            "(scraped from planner status)", ["namespace", "action"],
            registry=self.registry)
        self._planner_signal = Gauge(
            f"{PREFIX}_planner_signal", "Planner fleet signals",
            ["namespace", "signal"], registry=self.registry)
        self._planner_workers = Gauge(
            f"{PREFIX}_planner_workers", "Planner worker counts",
            ["namespace", "state"], registry=self.registry)
        self._planner_paused = Gauge(
            f"{PREFIX}_planner_paused", "1 when the planner is paused",
            ["namespace"], registry=self.registry)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "MetricsAggregatorService":
        from .trace_collector import TRACE_EVENTS_SUBJECT
        ep = self.endpoint
        self._client = ep.client()
        await self._client.start()
        self._sub = await ep.parent_component().subscribe_event(
            KV_HIT_RATE_SUBJECT)
        self._trace_sub = await ep.parent_component().subscribe_event(
            TRACE_EVENTS_SUBJECT)
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._scrape_loop(), name="metrics-scrape"),
            loop.create_task(self._hit_rate_loop(), name="metrics-hitrate"),
            loop.create_task(self._trace_loop(), name="metrics-traces"),
        ]
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._trace_sub is not None:
            self._trace_sub.close()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._client is not None:
            await self._client.close()

    # ----------------------------------------------------------------- feeds
    def _labels(self, worker_id: int):
        return (self.endpoint.component, self.endpoint.name,
                f"{worker_id:x}")

    async def _scrape_loop(self) -> None:
        # long-lived task: shed whatever ambient trace the spawning
        # context carried (runtime/tracing.py detach_trace contract)
        from ..runtime.tracing import detach_trace
        detach_trace()
        while True:
            try:
                stats = await self._client.collect_stats()
                self._apply_stats(stats)
            except Exception:  # noqa: BLE001
                logger.exception("stats scrape failed")
            try:
                await self._scrape_planner()
            except Exception:  # noqa: BLE001
                logger.exception("planner status scrape failed")
            await asyncio.sleep(self.scrape_interval)

    async def _scrape_planner(self) -> None:
        from ..llm.slo import PLANNER_PREFIX
        rt = self.endpoint.runtime
        prefix = f"{PLANNER_PREFIX}status/"
        snapshot: Dict[str, dict] = {}
        for e in await rt.store.kv_get_prefix(prefix):
            try:
                snapshot[e.key[len(prefix):]] = json.loads(e.value)
            except Exception:  # noqa: BLE001
                continue
        self.planner_status = snapshot
        for ns, s in snapshot.items():
            for action, n in (s.get("counters") or {}).items():
                self._planner_decisions.labels(ns, action).set(n)
            sig = s.get("signals") or {}
            for name in ("queue_depth", "slot_util", "kv_util",
                         "prefill_queue_depth"):
                if sig.get(name) is not None:
                    self._planner_signal.labels(ns, name).set(sig[name])
            if sig.get("ttft_p90_ms") is not None:
                self._planner_signal.labels(ns, "ttft_p90_ms").set(
                    sig["ttft_p90_ms"])
            self._planner_signal.labels(ns, "disagg_threshold").set(
                s.get("disagg_threshold", 0))
            workers = s.get("workers") or {}
            self._planner_workers.labels(ns, "live").set(
                len(workers.get("live", [])))
            self._planner_workers.labels(ns, "draining").set(
                len(workers.get("draining", [])))
            self._planner_paused.labels(ns).set(
                1 if s.get("paused") else 0)

    def _apply_stats(self, stats: Dict[int, dict]) -> None:
        present = set(stats)
        for wid, raw in stats.items():
            m = (raw if isinstance(raw, ForwardPassMetrics)
                 else ForwardPassMetrics.from_dict(raw))
            self.latest[wid] = m
            lbl = self._labels(wid)
            for f in _GAUGE_FIELDS:
                self._gauges[f].labels(*lbl).set(getattr(m, f))
            for f, g in self._spec_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._pp_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._tier_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._layout_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._remote_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._ragged_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._trace_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._degrade_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._cost_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            for f, g in self._disagg_stream_gauges.items():
                g.labels(*lbl).set(getattr(m, f))
            # per-tenant labeled series (llm/tenancy.py tenant_stats)
            tenants = m.tenant_stats or {}
            for t, stats in tenants.items():
                if not isinstance(stats, dict):
                    continue
                for f, g in self._tenant_gauges.items():
                    g.labels(*lbl, t).set(stats.get(f, 0))
            for gone_t in self._seen_tenants.get(wid, set()) - set(tenants):
                for g in self._tenant_gauges.values():
                    try:
                        g.remove(*lbl, gone_t)
                    except KeyError:
                        pass
            self._seen_tenants[wid] = set(tenants)
        # drop series for workers whose leases died (the watcher pruned them)
        for gone in self._seen_workers - present:
            self.latest.pop(gone, None)
            lbl = self._labels(gone)
            for gone_t in self._seen_tenants.pop(gone, set()):
                for g in self._tenant_gauges.values():
                    try:
                        g.remove(*lbl, gone_t)
                    except KeyError:
                        pass
            for g in (list(self._gauges.values())
                      + list(self._spec_gauges.values())
                      + list(self._pp_gauges.values())
                      + list(self._tier_gauges.values())
                      + list(self._layout_gauges.values())
                      + list(self._remote_gauges.values())
                      + list(self._ragged_gauges.values())
                      + list(self._trace_gauges.values())
                      + list(self._degrade_gauges.values())
                      + list(self._cost_gauges.values())
                      + list(self._disagg_stream_gauges.values())):
                try:
                    g.remove(*lbl)
                except KeyError:
                    pass
        self._seen_workers = present

    async def _hit_rate_loop(self) -> None:
        async for msg in self._sub:
            try:
                d = json.loads(msg.payload)
                lbl = self._labels(int(d["worker_id"]))
                self.hit_isl_blocks.labels(*lbl).inc(int(d["isl_blocks"]))
                self.hit_overlap_blocks.labels(*lbl).inc(
                    int(d["overlap_blocks"]))
                self.events_received += 1
            except Exception:  # noqa: BLE001
                logger.exception("bad hit-rate event dropped")

    async def _trace_loop(self) -> None:
        """Completed trace dicts published by workers/frontends
        (trace_events subject) → the collector's tree store + latency
        histograms (components/trace_collector.py)."""
        from ..runtime.tracing import detach_trace
        detach_trace()
        async for msg in self._trace_sub:
            try:
                self.collector.feed(json.loads(msg.payload))
            except Exception:  # noqa: BLE001
                logger.exception("bad trace event dropped")

    # ----------------------------------------------------------------- serve
    def render(self) -> bytes:
        return generate_latest(self.registry)

    def render_openmetrics(self) -> bytes:
        """OpenMetrics exposition — the format that CARRIES exemplars
        (classic Prometheus text silently drops them). Grafana's
        exemplar-click-through needs this negotiated via the Accept
        header, which serve_http honors."""
        from prometheus_client.openmetrics.exposition import (
            generate_latest as generate_openmetrics)
        return generate_openmetrics(self.registry)

    async def serve_push(self, gateway: str,
                         job: str = "dynamo_tpu_metrics",
                         interval: float = 2.0) -> asyncio.Task:
        """Push mode (reference MetricsMode::Push,
        components/metrics/src/lib.rs:104-296): periodically PUT the whole
        registry to a Prometheus PushGateway instead of — or alongside —
        pull exposition. Returns the pushing task (cancelled by close())."""
        from prometheus_client import push_to_gateway

        async def push_loop() -> None:
            while True:
                try:
                    await asyncio.to_thread(push_to_gateway, gateway,
                                            job=job, registry=self.registry)
                    self.pushes += 1
                except Exception:  # noqa: BLE001 — gateway may flap
                    logger.exception("metrics push to %s failed", gateway)
                await asyncio.sleep(interval)

        task = asyncio.get_running_loop().create_task(
            push_loop(), name="metrics-push")
        self._tasks.append(task)
        logger.info("pushing metrics to gateway %s every %.1fs (job=%s)",
                    gateway, interval, job)
        return task

    async def serve_http(self, host: str = "0.0.0.0",
                         port: int = 9091):
        """Expose GET /metrics (Prometheus text); returns the aiohttp
        runner (caller owns cleanup)."""
        from aiohttp import web

        async def metrics(request):
            # OpenMetrics when asked for (the exemplar-carrying format
            # Grafana's trace click-through scrapes); classic text else
            if "application/openmetrics-text" in request.headers.get(
                    "Accept", ""):
                return web.Response(
                    body=self.render_openmetrics(),
                    content_type="application/openmetrics-text")
            return web.Response(body=self.render(),
                                content_type="text/plain")

        async def planner(_request):
            # introspection: the latest planner/status/* snapshots
            # (SLOs, last decision, per-actuator counters) as JSON
            return web.json_response(self.planner_status)

        async def traces(_request):
            return web.json_response(
                {"traces": self.collector.summaries(),
                 **self.collector.stats()})

        async def trace_by_id(request):
            key = request.match_info["trace_id"]
            tid = self.collector.find(key)
            if tid is None:
                return web.json_response(
                    {"error": f"unknown trace {key!r}"}, status=404)
            if request.query.get("format") == "perfetto":
                return web.json_response(self.collector.perfetto(tid))
            return web.json_response(self.collector.tree(tid))

        app = web.Application()
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/planner", planner)
        app.router.add_get("/traces", traces)
        app.router.add_get("/traces/{trace_id}", trace_by_id)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        logger.info("metrics exposition on http://%s:%d/metrics", host, port)
        return runner


async def amain(argv=None) -> None:
    import argparse
    p = argparse.ArgumentParser(
        description="KV metrics aggregation service (Prometheus exposition)")
    p.add_argument("endpoint", help="dyn://ns/component/endpoint to watch")
    p.add_argument("--daemon", default="127.0.0.1:5600",
                   help="discovery daemon host:port")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9091)
    p.add_argument("--scrape-interval", type=float, default=1.0)
    p.add_argument("--push-gateway",
                   help="Prometheus PushGateway address (host:port or URL); "
                        "enables push mode alongside pull exposition "
                        "(reference MetricsMode::Push)")
    p.add_argument("--push-job", default="dynamo_tpu_metrics")
    p.add_argument("--push-interval", type=float, default=2.0)
    p.add_argument("--no-pull", action="store_true",
                   help="push mode only: skip the /metrics HTTP listener")
    args = p.parse_args(argv)
    if args.no_pull and not args.push_gateway:
        raise SystemExit("--no-pull requires --push-gateway")

    rt = await DistributedRuntime.connect(args.daemon)
    ep = Endpoint.parse_path(rt, args.endpoint)
    svc = await MetricsAggregatorService(
        ep, scrape_interval=args.scrape_interval).start()
    runner = None
    if not args.no_pull:
        runner = await svc.serve_http(args.host, args.port)
    if args.push_gateway:
        await svc.serve_push(args.push_gateway, job=args.push_job,
                             interval=args.push_interval)
    try:
        await asyncio.Event().wait()
    finally:
        if runner is not None:
            await runner.cleanup()
        await svc.close()
        await rt.shutdown()


def main() -> None:
    from ..runtime.log import setup_logging
    setup_logging()
    asyncio.run(amain())


if __name__ == "__main__":
    main()
