"""Fleet trace collector: assembles per-request trace TREES from the
completed trace dicts workers publish over the event plane, serves them
stitched, and exports Chrome-trace-event/Perfetto JSON.

The propagation half lives in runtime/tracing.py (TraceContext on the
request-plane control message, the disagg handoff, and kv_fabric RPCs);
this module is the aggregation half, wired into the metrics service
(components/metrics.py) the way the KV hit-rate subscription already is:

- Workers (and frontends) attach a :class:`TracePublisher` to the
  process tracer via :func:`wire_trace_publisher`; every finished trace
  dict rides the component's ``trace_events`` subject.
- The collector keys members by ``trace_id``, stitches parent/child
  edges on ``parent_span`` → ``span_id``, and serves ``/traces/{id}``
  as a tree plus ``?format=perfetto`` as Chrome trace-event JSON
  (load it at ui.perfetto.dev or chrome://tracing).
- **Tail-based retention**: the interesting traces are the slow, the
  errored, and the preempted — so when the tree store fills, those are
  protected and the fast/boring majority is evicted first (plus an
  every-Nth survivor so the baseline shape stays observable). The
  TTFT/ITL/queue-wait HISTOGRAMS are fed from every trace regardless
  of retention, each observation carrying a ``trace_id`` exemplar — a
  Grafana latency spike clicks through to the exact trace.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from ..runtime.tracing import (TRACE_EVENTS_SUBJECT, TracePublisher,
                               tracer as process_tracer)

logger = logging.getLogger("dynamo_tpu.components.trace_collector")

__all__ = ["TraceCollector", "wire_trace_publisher",
           "TRACE_EVENTS_SUBJECT"]

# spans/events that mark a trace tree worth keeping in full
_RETAIN_EVENTS = frozenset({"engine.preempted"})


def wire_trace_publisher(component, tracer=None,
                         topic: str = TRACE_EVENTS_SUBJECT) -> TracePublisher:
    """Attach a publisher to the (process-global) tracer that ships every
    finished trace dict over ``component``'s event subject — the same
    pattern the KV event publisher uses. Call ``.close()`` to detach
    (tests share the process tracer)."""
    tracer = tracer or process_tracer

    async def sink(trace_dict: dict) -> None:
        await component.publish_event(topic, trace_dict)

    return TracePublisher(sink, tracer_=tracer)


class TraceCollector:
    """Holds recent per-request trace trees + fleet latency histograms.

    ``keep_trees`` bounds the store; ``sample_every`` keeps every Nth
    boring tree when evicting (the baseline-shape survivors);
    ``slow_fraction`` protects the slowest tail (default: the top 1% —
    "keep full trees for the slowest p99" in tail-sampling terms)."""

    def __init__(self, keep_trees: int = 512, sample_every: int = 8,
                 slow_fraction: float = 0.01, registry=None):
        self.keep_trees = keep_trees
        self.sample_every = max(int(sample_every), 1)
        self.slow_fraction = slow_fraction
        # trace_id → {"members": {span_id: trace_dict}, "last_at": float,
        #             "protected": bool, "seq": int}
        self._trees: "OrderedDict[str, dict]" = OrderedDict()
        self._seq = 0
        # rolling root latency window for the slow-tail threshold
        self._totals: deque = deque(maxlen=1024)
        # percentile feeds (fed on EVERY trace, independent of retention)
        self._ttft_ms: deque = deque(maxlen=1024)
        self._itl_ms: deque = deque(maxlen=1024)
        self._queue_wait_ms: deque = deque(maxlen=1024)
        self.received = 0
        self.evicted = 0
        self.protected_kept = 0
        self._make_histograms(registry)

    # ------------------------------------------------------------ histograms
    def _make_histograms(self, registry) -> None:
        """TTFT/ITL/queue-wait HISTOGRAMS (not gauges) with a trace_id
        exemplar per observation. Rendered with exemplars under the
        OpenMetrics exposition (components/metrics.py render_openmetrics);
        classic Prometheus text simply omits them."""
        if registry is None:
            self.ttft_hist = self.itl_hist = self.queue_wait_hist = None
            return
        from prometheus_client import Histogram
        self.ttft_hist = Histogram(
            "nv_llm_trace_ttft_seconds",
            "Fleet TTFT from collected worker traces (exemplar: trace_id)",
            registry=registry,
            buckets=(.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0,
                     10.0))
        self.itl_hist = Histogram(
            "nv_llm_trace_itl_seconds",
            "Fleet decode-tail latency after first token (exemplar: "
            "trace_id)", registry=registry,
            buckets=(.002, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5))
        self.queue_wait_hist = Histogram(
            "nv_llm_trace_queue_wait_seconds",
            "Engine admission queue wait (exemplar: trace_id)",
            registry=registry,
            buckets=(.001, .005, .01, .05, .1, .25, .5, 1.0, 2.5, 5.0))

    def _observe(self, d: dict) -> None:
        spans = {s["name"]: s for s in d.get("spans", ())}
        ex = {"trace_id": d.get("trace_id", "")[:64]} \
            if d.get("trace_id") else None
        first = spans.get("first_response")
        if first is not None:
            ttft = first["at_ms"]
            self._ttft_ms.append(ttft)
            if self.ttft_hist is not None:
                self.ttft_hist.observe(ttft / 1e3, exemplar=ex)
            respond = spans.get("respond")
            if respond is not None:
                tail = respond["at_ms"] + respond["ms"] - first["at_ms"]
                if tail >= 0:
                    self._itl_ms.append(tail)
                    if self.itl_hist is not None:
                        self.itl_hist.observe(tail / 1e3, exemplar=ex)
        qw = spans.get("engine.queue_wait")
        if qw is not None:
            self._queue_wait_ms.append(qw["ms"])
            if self.queue_wait_hist is not None:
                self.queue_wait_hist.observe(qw["ms"] / 1e3, exemplar=ex)

    # ------------------------------------------------------------------ feed
    def feed(self, trace_dict: dict) -> None:
        """One finished per-process trace dict (runtime/tracing.py
        Trace.to_dict shape). Members dedupe on span_id, so re-delivery
        is harmless."""
        tid = trace_dict.get("trace_id")
        sid = trace_dict.get("span_id")
        if not tid or not sid:
            return
        self.received += 1
        self._observe(trace_dict)
        tree = self._trees.get(tid)
        if tree is None:
            self._seq += 1
            tree = {"members": {}, "last_at": 0.0, "protected": False,
                    "seq": self._seq}
            self._trees[tid] = tree
        tree["members"][sid] = trace_dict
        tree["last_at"] = time.time()
        self._trees.move_to_end(tid)
        if self._is_interesting(trace_dict):
            tree["protected"] = True
        if trace_dict.get("parent_span") is None:
            # roots carry the request's end-to-end latency
            self._totals.append(trace_dict.get("total_ms", 0.0))
        self._retain()

    def _is_interesting(self, d: dict) -> bool:
        if d.get("error"):
            return True
        if any(s["name"] in _RETAIN_EVENTS for s in d.get("spans", ())):
            return True
        if self._totals and d.get("parent_span") is None:
            xs = sorted(self._totals)
            k = max(int(len(xs) * (1.0 - self.slow_fraction)) - 1, 0)
            # STRICTLY greater: in a uniform-latency workload the p99
            # threshold equals the common value and >= would protect
            # everything (no tail = nothing to keep)
            if d.get("total_ms", 0.0) > xs[min(k, len(xs) - 1)]:
                return True
        return False

    def _retain(self) -> None:
        """Tail-based retention: over capacity, evict boring trees first
        (oldest-first), keeping every ``sample_every``-th of them as a
        baseline sample; protected (slow/errored/preempted) trees go
        only when even they exceed capacity."""
        while len(self._trees) > self.keep_trees:
            victim = None
            for tid, tree in self._trees.items():     # oldest first
                if tree["protected"]:
                    continue
                if tree["seq"] % self.sample_every == 0:
                    continue                          # baseline survivor
                victim = tid
                break
            if victim is None:
                # no plain-boring tree left: baseline samples go next;
                # protected (slow/errored/preempted) trees only as the
                # true last resort
                victim = next((tid for tid, tr in self._trees.items()
                               if not tr["protected"]), None)
            if victim is None:
                victim = next(iter(self._trees))
            self.evicted += 1
            self._trees.pop(victim, None)
        self.protected_kept = sum(
            1 for t in self._trees.values() if t["protected"])

    # ----------------------------------------------------------------- reads
    def find(self, key: str) -> Optional[str]:
        """Resolve a trace_id OR request id to a trace_id."""
        if key in self._trees:
            return key
        for tid in reversed(self._trees):
            for m in self._trees[tid]["members"].values():
                if m.get("request_id") == key:
                    return tid
        return None

    def tree(self, trace_id: str) -> Optional[dict]:
        """The stitched fleet tree: members nested on parent_span →
        span_id edges; processes whose parent never arrived (lost event,
        sampling) attach under the root as orphans rather than vanish."""
        t = self._trees.get(trace_id)
        if t is None:
            return None
        members = dict(t["members"])
        children: Dict[str, List[dict]] = {}
        roots, orphans = [], []
        for m in members.values():
            ps = m.get("parent_span")
            if ps is None:
                roots.append(m)
            elif ps in members:
                children.setdefault(ps, []).append(m)
            else:
                orphans.append(m)

        def node(m: dict) -> dict:
            kids = sorted(children.get(m["span_id"], ()),
                          key=lambda x: x.get("origin_offset_ms", 0.0))
            return {**m, "children": [node(k) for k in kids]}

        roots.sort(key=lambda x: x.get("origin_offset_ms", 0.0))
        orphans.sort(key=lambda x: x.get("origin_offset_ms", 0.0))
        root = node(roots[0]) if roots else None
        if root is not None and orphans:
            root["children"].extend(node(o) for o in orphans)
        out = {
            "trace_id": trace_id,
            "request_id": (roots[0] if roots else
                           next(iter(members.values())))["request_id"],
            "n_processes": len(members),
            "roles": sorted({m.get("role", "") for m in members.values()}),
            "protected": t["protected"],
            "root": root if root is not None else
            {"children": [node(o) for o in orphans]},
        }
        return out

    def summaries(self, n: int = 64) -> List[dict]:
        out = []
        for tid in list(reversed(self._trees))[:n]:
            t = self._trees[tid]
            root = next((m for m in t["members"].values()
                         if m.get("parent_span") is None), None)
            any_m = root or next(iter(t["members"].values()))
            out.append({
                "trace_id": tid,
                "request_id": any_m.get("request_id"),
                "roles": sorted({m.get("role", "")
                                 for m in t["members"].values()}),
                "total_ms": (root or {}).get("total_ms"),
                "error": any(m.get("error")
                             for m in t["members"].values()),
                "protected": t["protected"],
            })
        return out

    # -------------------------------------------------------------- perfetto
    def perfetto(self, trace_id: str) -> Optional[dict]:
        """Chrome-trace-event JSON (the Perfetto/chrome://tracing load
        format): one complete-event ("ph": "X") per span, processes
        keyed by role, all timestamps on the ORIGIN's wall clock in
        microseconds. Loadable shape: {"traceEvents": [...]} with
        name/ph/ts/dur/pid/tid on every slice."""
        t = self._trees.get(trace_id)
        if t is None:
            return None
        members = list(t["members"].values())
        origin = min((m.get("origin_ts", 0.0) for m in members),
                     default=0.0)
        events: List[dict] = []
        pids = {}
        for m in sorted(members,
                        key=lambda x: x.get("origin_offset_ms", 0.0)):
            role = m.get("role") or "process"
            pid = pids.setdefault(role, len(pids) + 1)
            base_us = (m.get("start_epoch", origin) - origin) * 1e6
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{role} ({m.get('request_id', '')})"}})
            events.append({
                "name": f"{role}:{m.get('request_id', '')}",
                "cat": role, "ph": "X",
                "ts": round(base_us, 1),
                "dur": round(m.get("total_ms", 0.0) * 1e3, 1),
                "pid": pid, "tid": 1,
                "args": {"trace_id": trace_id,
                         "span_id": m.get("span_id"),
                         "parent_span": m.get("parent_span"),
                         **({"error": m["error"]} if m.get("error")
                            else {})},
            })
            for s in m.get("spans", ()):
                events.append({
                    "name": s["name"], "cat": role, "ph": "X",
                    "ts": round(base_us + s.get("at_ms", 0.0) * 1e3, 1),
                    "dur": round(s.get("ms", 0.0) * 1e3, 1),
                    "pid": pid, "tid": 2,
                    "args": dict(s.get("attrs", {})),
                })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"trace_id": trace_id}}

    # ------------------------------------------------------------ percentile
    def latency_percentiles(self, p: float = 90.0) -> dict:
        """Fleet-wide TTFT/ITL percentiles out of every collected worker
        trace — the planner's collector-backed SLO source (llm/slo.py
        latency_percentiles falls back to the frontend-local ring when
        this is empty)."""
        from ..llm.slo import percentile
        return {"ttft_p_ms": percentile(list(self._ttft_ms), p),
                "itl_p_ms": percentile(list(self._itl_ms), p),
                "n_traces": float(len(self._ttft_ms))}

    def stats(self) -> dict:
        return {"received": self.received, "trees": len(self._trees),
                "evicted": self.evicted,
                "protected": self.protected_kept,
                "ttft_window": len(self._ttft_ms)}
