"""Standalone OpenAI HTTP frontend: discovers served models from the KV
store and routes to their dyn:// worker endpoints.

Reference: components/http (src/main.rs:49-110) — a model-agnostic axum
frontend whose model list is driven entirely by etcd ModelEntry watchers;
workers publish entries (llmctl or self-registration) and the frontend
adds/removes them live. Run:

    python -m dynamo_tpu.components.http_frontend \
        --runtime-server HOST:PORT --port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import logging

logger = logging.getLogger("dynamo_tpu.components.http")


async def amain(argv=None) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu-http")
    p.add_argument("--runtime-server", required=True)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--router-mode", choices=["random", "round_robin"],
                   default="random")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)

    from ..llm.discovery import ModelWatcher
    from ..llm.http import HttpService
    from ..runtime.distributed import DistributedRuntime

    runtime = await DistributedRuntime.connect(args.runtime_server)
    svc = HttpService(port=args.port, host=args.host)
    watcher = await ModelWatcher(runtime, svc.manager,
                                 router_mode=args.router_mode).start()
    await svc.start()
    logger.info("http frontend on %s:%d (models from discovery)",
                args.host, args.port)
    try:
        await svc.run_forever()
    finally:
        await watcher.stop()
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
