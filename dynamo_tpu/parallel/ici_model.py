"""Analytic ICI collective costs for TP decode on v5e.

Why a model and not a measurement: this rig has ONE real chip (the
multi-chip path is validated on a virtual CPU mesh, which says nothing
about ICI time), so the TP-8 north-star number (BASELINE.md config 4)
must price the per-layer collectives explicitly. The reference faces the
same structural cost in NCCL all-reduces inside its engines' TP groups
(SURVEY.md §2.3 parallelism inventory); here the collectives are XLA
psums over ICI inserted by the row-parallel matmul pspecs
(parallel/sharding.py param_pspecs).

Link assumptions (public v5e specs, the same source as bench.py's
DEVICE_PEAKS): 1600 Gbps ICI per chip aggregate across 4 links = 200 GB/s
bidirectional. A ring all-reduce moves 2·(N-1)/N·bytes per chip; with
bidirectional links the effective per-chip throughput is half the
aggregate. We take 100 GB/s effective and add a per-collective latency
term (~5 us: a few hops of us-scale link latency + dispatch). Both are
deliberately conservative: XLA's async collectives overlap much of this
with the next layer's compute on real meshes, and a 2D torus can ride two
axes at once — the model books the FULL serial cost.

What TP-8 decode pays per step (Megatron layout, per param_pspecs):
  - per layer: 2 all-reduces of the [B, D] bf16 activations (after the
    row-parallel attention out-proj and MLP down-proj);
  - embed: 1 all-reduce of [B, D] (vocab-sharded table gather + psum);
  - sampling over the vocab-sharded logits: per-shard top-k/Gumbel then a
    max-reduce of (value, index) pairs — O(B·k) bytes, booked in the
    latency term (it is orders of magnitude below the [B, D] psums).
"""

from __future__ import annotations

V5E_ICI_EFFECTIVE_GBPS = 100e9      # per-chip effective all-reduce GB/s
COLLECTIVE_LATENCY_S = 5e-6         # per-collective fixed cost


def allreduce_s(nbytes: int, n_chips: int,
                eff_bw: float = V5E_ICI_EFFECTIVE_GBPS,
                latency_s: float = COLLECTIVE_LATENCY_S) -> float:
    """Ring all-reduce wall time for one [nbytes] buffer over n_chips."""
    if n_chips <= 1:
        return 0.0
    return (2.0 * nbytes * (n_chips - 1) / n_chips / eff_bw + latency_s)


def tp_decode_step_s(batch: int, hidden: int, num_layers: int,
                     n_chips: int, act_itemsize: int = 2,
                     eff_bw: float = V5E_ICI_EFFECTIVE_GBPS,
                     latency_s: float = COLLECTIVE_LATENCY_S) -> float:
    """Total modeled ICI time one TP-sharded decode step spends in
    collectives: 2 [B, D] psums per layer + 1 for the embedding."""
    per = allreduce_s(batch * hidden * act_itemsize, n_chips,
                      eff_bw=eff_bw, latency_s=latency_s)
    return (2 * num_layers + 1) * per


# Sensitivity grid for the gate metric: the single-point 100 GB/s + 5 us
# assumption is conservative, but a one-point model invites "what if the
# link is worse" — so the bench publishes the NET tok/s over the full
# bw × latency cross product and the gate is judged at the CONSERVATIVE
# corner (50 GB/s effective, 10 us/collective), not the nominal point.
SENSITIVITY_BW_GBPS = (50e9, 100e9, 150e9)
SENSITIVITY_LATENCY_S = (2e-6, 5e-6, 10e-6)


def tp_decode_sensitivity(batch: int, hidden: int, num_layers: int,
                          n_chips: int, device_tok_per_s: float,
                          act_itemsize: int = 2) -> dict:
    """Net per-chip tok/s across the bw × latency grid.

    Returns {"band": {"<bw_gbps>GBps/<us>us": net_tok_per_s, ...},
             "worst": <conservative-corner net>, "best": ...} given the
    measured compute+HBM-only device throughput.
    """
    base_step_s = batch / device_tok_per_s if device_tok_per_s > 0 else 0.0
    band = {}
    for bw in SENSITIVITY_BW_GBPS:
        for lat in SENSITIVITY_LATENCY_S:
            ici = tp_decode_step_s(batch, hidden, num_layers, n_chips,
                                   act_itemsize, eff_bw=bw, latency_s=lat)
            net = batch / (base_step_s + ici) if base_step_s > 0 else 0.0
            band[f"{int(bw / 1e9)}GBps/{int(lat * 1e6)}us"] = round(net, 1)
    # nominal computed DIRECTLY at the default constants (ADVICE r5): the
    # sweep grid need not contain the nominal point — tuning either
    # constant off-grid must not silently zero bench.py's headline
    nominal_ici = tp_decode_step_s(batch, hidden, num_layers, n_chips,
                                   act_itemsize)
    nominal = (batch / (base_step_s + nominal_ici)
               if base_step_s > 0 else 0.0)
    return {"band": band,
            "nominal": nominal,
            "worst": min(band.values()) if band else 0.0,
            "best": max(band.values()) if band else 0.0}


# ---------------------------------------------------------------------------
# Pipeline parallelism over DCN — the boundary-cost model the bench's
# --pp mode publishes (bench.py run_pp_bench). Why PP is the cross-host
# axis: TP books 2 psums/layer of [B, D]; PP moves ONE [B/pp, D]
# microbatch activation per boundary per tick. At the PERF.md reference
# point ([128, 8192] bf16 = 2 MB over a 25 Gb/s NIC) a boundary costs
# ~0.65 ms — vs ~95 ms/step for 80 layers of TP collectives.
# ---------------------------------------------------------------------------

DCN_EFFECTIVE_GBPS = 3.1e9          # 25 Gb/s NIC ≈ 3.1 GB/s effective
DCN_BOUNDARY_LATENCY_S = 100e-6     # per-hop fixed cost (RPC + NIC)
# sensitivity grid, same shape as the TP tables: judged at the
# conservative corner, published across the band
PP_SENSITIVITY_BW_GBPS = (1.5e9, 3.1e9, 6.0e9)
PP_SENSITIVITY_LATENCY_S = (50e-6, 100e-6, 250e-6)


def pp_boundary_s(batch: int, hidden: int, pp: int,
                  act_itemsize: int = 2,
                  eff_bw: float = DCN_EFFECTIVE_GBPS,
                  latency_s: float = DCN_BOUNDARY_LATENCY_S) -> float:
    """Wall time for ONE stage-boundary hop of the token-interleaved
    ring: a [B/pp, D] activation (the microbatch, not the full batch —
    interleaving shrinks each hop by pp while adding pp hops per full
    step, so total bytes/step stay one [B, D] activation)."""
    if pp <= 1:
        return 0.0
    nbytes = (batch // pp) * hidden * act_itemsize
    return nbytes / eff_bw + latency_s


def pp_step_model(batch: int, hidden: int, pp: int, K: int,
                  device_tick_s: float,
                  act_itemsize: int = 2) -> dict:
    """Modeled interleaved-decode step economics over DCN boundaries.

    ``device_tick_s`` is the measured per-tick compute time (one stage
    over one microbatch — bench.py derives it from the interleaved
    dispatch slope). The model books the FULL serial boundary cost per
    tick (XLA overlaps much of it with the next tick's compute on real
    links — same conservatism as the TP tables). Returns per-step wall
    time, net tok/s across the bw×latency band, utilization and bubble
    fraction of the K-step dispatch schedule."""
    from .pipeline_parallel import (pp_bubble_fraction,
                                    pp_dispatch_ticks,
                                    pp_dispatch_utilization)
    ticks = pp_dispatch_ticks(pp, K)
    band = {}
    for bw in PP_SENSITIVITY_BW_GBPS:
        for lat in PP_SENSITIVITY_LATENCY_S:
            tick_s = device_tick_s + pp_boundary_s(
                batch, hidden, pp, act_itemsize, eff_bw=bw, latency_s=lat)
            step_s = tick_s * ticks / K     # one full-batch step = pp
            # ticks + the amortized ramp
            band[f"{bw / 1e9:g}GBps/{int(lat * 1e6)}us"] = round(
                batch / step_s, 1) if step_s > 0 else 0.0
    nominal_tick = device_tick_s + pp_boundary_s(batch, hidden, pp,
                                                 act_itemsize)
    nominal_step = nominal_tick * ticks / K
    return {
        "boundary_ms": round(1e3 * pp_boundary_s(batch, hidden, pp,
                                                 act_itemsize), 3),
        "boundary_bytes": (batch // max(pp, 1)) * hidden * act_itemsize,
        "dispatch_ticks": ticks,
        "utilization": round(pp_dispatch_utilization(pp, K), 4),
        "bubble_fraction": round(pp_bubble_fraction(pp, K), 4),
        "nominal_step_ms": round(1e3 * nominal_step, 3),
        "nominal_tok_per_s": round(batch / nominal_step, 1)
        if nominal_step > 0 else 0.0,
        "dcn_sensitivity": band,
        "worst_corner_tok_per_s": min(band.values()) if band else 0.0,
        "dcn_model": f"1 [B/pp, D] hop per tick, pp={pp} hops/step @ "
                     f"{DCN_EFFECTIVE_GBPS / 1e9:g} GB/s effective + "
                     f"{DCN_BOUNDARY_LATENCY_S * 1e6:.0f}us/hop",
    }
