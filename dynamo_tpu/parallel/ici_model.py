"""Analytic ICI collective costs for TP decode on v5e.

Why a model and not a measurement: this rig has ONE real chip (the
multi-chip path is validated on a virtual CPU mesh, which says nothing
about ICI time), so the TP-8 north-star number (BASELINE.md config 4)
must price the per-layer collectives explicitly. The reference faces the
same structural cost in NCCL all-reduces inside its engines' TP groups
(SURVEY.md §2.3 parallelism inventory); here the collectives are XLA
psums over ICI inserted by the row-parallel matmul pspecs
(parallel/sharding.py param_pspecs).

Link assumptions (public v5e specs, the same source as bench.py's
DEVICE_PEAKS): 1600 Gbps ICI per chip aggregate across 4 links = 200 GB/s
bidirectional. A ring all-reduce moves 2·(N-1)/N·bytes per chip; with
bidirectional links the effective per-chip throughput is half the
aggregate. We take 100 GB/s effective and add a per-collective latency
term (~5 us: a few hops of us-scale link latency + dispatch). Both are
deliberately conservative: XLA's async collectives overlap much of this
with the next layer's compute on real meshes, and a 2D torus can ride two
axes at once — the model books the FULL serial cost.

What TP-8 decode pays per step (Megatron layout, per param_pspecs):
  - per layer: 2 all-reduces of the [B, D] bf16 activations (after the
    row-parallel attention out-proj and MLP down-proj);
  - embed: 1 all-reduce of [B, D] (vocab-sharded table gather + psum);
  - sampling over the vocab-sharded logits: per-shard top-k/Gumbel then a
    max-reduce of (value, index) pairs — O(B·k) bytes, booked in the
    latency term (it is orders of magnitude below the [B, D] psums).
"""

from __future__ import annotations

V5E_ICI_EFFECTIVE_GBPS = 100e9      # per-chip effective all-reduce GB/s
COLLECTIVE_LATENCY_S = 5e-6         # per-collective fixed cost


def allreduce_s(nbytes: int, n_chips: int,
                eff_bw: float = V5E_ICI_EFFECTIVE_GBPS,
                latency_s: float = COLLECTIVE_LATENCY_S) -> float:
    """Ring all-reduce wall time for one [nbytes] buffer over n_chips."""
    if n_chips <= 1:
        return 0.0
    return (2.0 * nbytes * (n_chips - 1) / n_chips / eff_bw + latency_s)


def tp_decode_step_s(batch: int, hidden: int, num_layers: int,
                     n_chips: int, act_itemsize: int = 2,
                     eff_bw: float = V5E_ICI_EFFECTIVE_GBPS,
                     latency_s: float = COLLECTIVE_LATENCY_S) -> float:
    """Total modeled ICI time one TP-sharded decode step spends in
    collectives: 2 [B, D] psums per layer + 1 for the embedding."""
    per = allreduce_s(batch * hidden * act_itemsize, n_chips,
                      eff_bw=eff_bw, latency_s=latency_s)
    return (2 * num_layers + 1) * per


# Sensitivity grid for the gate metric: the single-point 100 GB/s + 5 us
# assumption is conservative, but a one-point model invites "what if the
# link is worse" — so the bench publishes the NET tok/s over the full
# bw × latency cross product and the gate is judged at the CONSERVATIVE
# corner (50 GB/s effective, 10 us/collective), not the nominal point.
SENSITIVITY_BW_GBPS = (50e9, 100e9, 150e9)
SENSITIVITY_LATENCY_S = (2e-6, 5e-6, 10e-6)


def tp_decode_sensitivity(batch: int, hidden: int, num_layers: int,
                          n_chips: int, device_tok_per_s: float,
                          act_itemsize: int = 2) -> dict:
    """Net per-chip tok/s across the bw × latency grid.

    Returns {"band": {"<bw_gbps>GBps/<us>us": net_tok_per_s, ...},
             "worst": <conservative-corner net>, "best": ...} given the
    measured compute+HBM-only device throughput.
    """
    base_step_s = batch / device_tok_per_s if device_tok_per_s > 0 else 0.0
    band = {}
    for bw in SENSITIVITY_BW_GBPS:
        for lat in SENSITIVITY_LATENCY_S:
            ici = tp_decode_step_s(batch, hidden, num_layers, n_chips,
                                   act_itemsize, eff_bw=bw, latency_s=lat)
            net = batch / (base_step_s + ici) if base_step_s > 0 else 0.0
            band[f"{int(bw / 1e9)}GBps/{int(lat * 1e6)}us"] = round(net, 1)
    # nominal computed DIRECTLY at the default constants (ADVICE r5): the
    # sweep grid need not contain the nominal point — tuning either
    # constant off-grid must not silently zero bench.py's headline
    nominal_ici = tp_decode_step_s(batch, hidden, num_layers, n_chips,
                                   act_itemsize)
    nominal = (batch / (base_step_s + nominal_ici)
               if base_step_s > 0 else 0.0)
    return {"band": band,
            "nominal": nominal,
            "worst": min(band.values()) if band else 0.0,
            "best": max(band.values()) if band else 0.0}
