"""Topology-aware static placement of serving roles onto TPU devices.

The reference ships no working planner (its "Planner" is aspirational;
SURVEY.md §7 stage 8 scopes ours as a static placer reading the real
topology). This module turns `jax.devices()` into a host/coords snapshot and
assigns prefill/decode/router roles to chip groups such that:

- a worker's chips are ICI-contiguous (same host, adjacent coords) so its
  tp/sp collectives never cross DCN;
- different roles are packed from opposite ends of the host list, so
  prefill and decode fleets land on disjoint hosts when capacity allows
  (the disaggregation win depends on them not stealing each other's HBM
  bandwidth);
- the result is serializable and feeds the SDK allocator's
  `TPU_VISIBLE_CHIPS` env contract (sdk/allocator.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

__all__ = ["DeviceInfo", "Topology", "Placement", "snapshot_topology",
           "plan_placement"]


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    id: int
    process_index: int
    coords: Optional[tuple] = None   # TPU (x, y, z) when exposed
    local_index: int = 0             # position within its host


@dataclasses.dataclass
class Topology:
    devices: List[DeviceInfo]

    @property
    def hosts(self) -> Dict[int, List[DeviceInfo]]:
        out: Dict[int, List[DeviceInfo]] = {}
        for d in self.devices:
            out.setdefault(d.process_index, []).append(d)
        for devs in out.values():
            devs.sort(key=lambda d: (d.coords or (d.id,), d.id))
        return out


@dataclasses.dataclass
class Placement:
    role: str
    index: int                       # replica number within the role
    process_index: int
    devices: List[DeviceInfo]

    def env(self) -> Dict[str, str]:
        """Per-process env pinning this worker to its chips (same contract
        as sdk/allocator.py Allocation.env: both variables, so multiple
        engine processes can subslice one host's chips)."""
        if not self.devices:
            return {}
        return {"TPU_VISIBLE_CHIPS": ",".join(
                    str(d.local_index) for d in self.devices),
                "TPU_CHIPS_PER_PROCESS_BOUNDS":
                    f"1,1,{len(self.devices)}"}

    def device_ids(self) -> List[int]:
        return [d.id for d in self.devices]


def snapshot_topology(devices: Optional[Sequence] = None) -> Topology:
    """Build a Topology from live `jax.devices()` (or any objects with
    `.id` / `.process_index` / optional `.coords`)."""
    if devices is None:
        import jax
        devices = jax.devices()
    per_host_counter: Dict[int, int] = {}
    infos = []
    for d in sorted(devices, key=lambda d: (d.process_index, d.id)):
        li = per_host_counter.get(d.process_index, 0)
        per_host_counter[d.process_index] = li + 1
        infos.append(DeviceInfo(
            id=d.id, process_index=d.process_index,
            coords=tuple(getattr(d, "coords", ()) or ()) or None,
            local_index=li))
    return Topology(infos)


def plan_placement(topology: Topology,
                   roles: Sequence[dict]) -> List[Placement]:
    """Assign chip groups to roles.

    ``roles``: [{"role": "decode", "count": 2, "chips": 4}, ...] in
    priority order. Raises when a worker can't get an ICI-contiguous group
    (a group never spans hosts) or capacity runs out.

    Packing: the first role fills hosts front-to-back, the second
    back-to-front, alternating — so e.g. decode and prefill fleets occupy
    disjoint hosts whenever the chip math allows.
    """
    hosts = topology.hosts
    host_order = sorted(hosts)
    free: Dict[int, List[DeviceInfo]] = {h: list(hosts[h])
                                         for h in host_order}
    placements: List[Placement] = []
    for role_i, spec in enumerate(roles):
        role, count = spec["role"], int(spec.get("count", 1))
        chips = int(spec.get("chips", 1))
        order = host_order if role_i % 2 == 0 else list(reversed(host_order))
        for idx in range(count):
            placed = False
            if chips == 0:
                placements.append(Placement(role, idx, -1, []))
                continue
            for h in order:
                if len(free[h]) >= chips:
                    take, free[h] = free[h][:chips], free[h][chips:]
                    placements.append(Placement(role, idx, h, take))
                    placed = True
                    break
            if not placed:
                biggest = max((len(v) for v in free.values()), default=0)
                raise ValueError(
                    f"cannot place {role}[{idx}]: needs {chips} contiguous "
                    f"chips on one host, largest free host block is "
                    f"{biggest} (groups never span hosts — ICI only)")
    return placements
