"""Ring attention: sequence-parallel causal attention over the "sp" mesh
axis for long-context prefill.

The reference has NO sequence/context parallelism (SURVEY.md §2.3: no hits
for ring/ulysses/context_parallel) — its long-context levers stop at KV
paging + conditional disagg. This is the TPU build's designed-fresh
capability (SURVEY.md §5.7): shard the prompt over `sp`, keep each device's
KV chunk resident, and rotate KV around the ring with `lax.ppermute` so
every query chunk sees every KV chunk while per-device memory stays
O(seq_len / sp). Softmax is accumulated online (flash-style m/l/acc
carries), so the result is exact — not an approximation.

Communication pattern: n-1 ppermute steps of [S/n, KVH, Dh] chunks ride the
ICI ring concurrently with the local chunk matmuls (XLA overlaps the
collective-permute with compute when the chunk math is large enough —
the classic ring-attention latency-hiding schedule).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention_local", "ring_attention"]


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         *, axis_name: str, scale: float,
                         q_offset: Optional[jax.Array] = None,
                         kv_len: Optional[jax.Array] = None,
                         impl: str = "dense") -> jax.Array:
    """Per-shard body (call inside shard_map over `axis_name`).

    q: [Tl, H, Dh] — this device's query chunk (global sequence is the
    concatenation over the axis, in axis order).
    k/v: [Sl, KVH, Dh] — this device's resident KV chunk.
    q_offset: global position of q[0] (default: axis_index * Tl).
    kv_len: total valid kv length (default: axis_size * Sl) — positions
    beyond it are masked (padded final chunk).

    impl: per-hop attention body. "dense" materializes [KVH, g, Tl, Sl]
    scores — fine for moderate chunks, O((T/sp)²) memory at long context.
    "flash"/"flash_interpret" streams each hop through the Pallas partial
    kernel (engine/attention.flash_prefill_partial): O(TQ·SC) live memory
    per hop, so per-device memory stays O(T/sp) end to end — the long-
    context configuration.

    Returns [Tl, H, Dh].
    """
    Tl, H, Dh = q.shape
    Sl, KVH, _ = k.shape
    g = H // KVH
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    if q_offset is None:
        q_offset = me * Tl
    total = n * Sl if kv_len is None else kv_len
    perm = [(i, (i + 1) % n) for i in range(n)]

    if impl.startswith("flash"):
        from ..engine.attention import flash_prefill_partial
        interpret = impl == "flash_interpret"

        m0 = jnp.full((Tl, H), NEG_INF, jnp.float32)
        l0 = jnp.zeros((Tl, H), jnp.float32)
        acc0 = jnp.zeros((Tl, H, Dh), jnp.float32)

        def step(carry, s):
            k_c, v_c, m, l, acc = carry
            src = (me - s) % n             # who computed this chunk
            # hop combine: the kernel returns this chunk's partial
            # (acc_c, m_c, l_c); merge with the carried state via the
            # online-softmax recurrence
            acc_c, m_c, l_c = flash_prefill_partial(
                q, k_c, v_c, scale=scale,
                start_pos=q_offset - src * Sl,
                seq_len=jnp.clip(total - src * Sl, 0, Sl),
                interpret=interpret)
            m_new = jnp.maximum(m, m_c)
            a_old = jnp.exp(m - m_new)
            a_new = jnp.exp(m_c - m_new)
            l = l * a_old + l_c * a_new
            acc = acc * a_old[..., None] + acc_c * a_new[..., None]
            k_n = jax.lax.ppermute(k_c, axis_name, perm)
            v_n = jax.lax.ppermute(v_c, axis_name, perm)
            return (k_n, v_n, m_new, l, acc), None

        (_, _, m, l, acc), _ = jax.lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(n))
        out = acc / jnp.maximum(l, 1e-20)[..., None]           # [Tl,H,Dh]
        return out.astype(q.dtype)

    qg = (q.astype(jnp.float32) * scale).reshape(Tl, KVH, g, Dh)
    qpos = q_offset + jnp.arange(Tl, dtype=jnp.int32)          # [Tl]

    m0 = jnp.full((KVH, g, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((KVH, g, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((KVH, g, Tl, Dh), jnp.float32)

    def step(carry, s):
        k_c, v_c, m, l, acc = carry
        src = (me - s) % n                     # who computed this chunk
        kpos = src * Sl + jnp.arange(Sl, dtype=jnp.int32)      # [Sl]
        scores = jnp.einsum("tkgd,skd->kgts", qg,
                            k_c.astype(jnp.float32))           # [KVH,g,Tl,Sl]
        mask = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < total)
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(scores - m_new)
        # fully-masked rows: m_new stays NEG_INF and p would be exp(0)=1 —
        # zero them so padded chunks contribute nothing
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("kgts,skd->kgtd", p,
                                       v_c.astype(jnp.float32))
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        return (k_n, v_n, m_new, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-20)                          # [KVH,g,Tl,Dh]
    return out.transpose(2, 0, 1, 3).reshape(Tl, H, Dh).astype(q.dtype)


def _default_impl(num_heads: int, num_kv_heads: int, head_dim: int) -> str:
    from ..engine.attention import _on_tpu, flash_prefill_supported
    return ("flash" if _on_tpu()
            and flash_prefill_supported(num_heads, num_kv_heads, head_dim)
            else "dense")


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   *, scale: float, axis_name: str = "sp",
                   tp_axis: Optional[str] = "tp",
                   kv_len: Optional[jax.Array] = None,
                   impl: str = "auto") -> jax.Array:
    """Global entry: q [T, H, Dh], k/v [S, KVH, Dh] with the sequence axis
    sharded over `axis_name` (and heads optionally over `tp_axis`). T and S
    must divide by the axis size. Returns [T, H, Dh], same shardings.

    impl: "auto" picks the Pallas flash hop body on TPU (per-device memory
    O(T/sp) at any context length), dense einsum elsewhere."""
    if impl == "auto":
        impl = _default_impl(q.shape[1], k.shape[1], q.shape[2])
    head_ax = tp_axis if (tp_axis and tp_axis in mesh.shape) else None
    spec_q = P(axis_name, head_ax, None)
    spec_kv = P(axis_name, head_ax, None)
    kv_spec = None if kv_len is None else P()

    def body(q_l, k_l, v_l, *rest):
        kvl = rest[0] if rest else None
        return ring_attention_local(q_l, k_l, v_l, axis_name=axis_name,
                                    scale=scale, kv_len=kvl, impl=impl)

    args = (q, k, v) + ((kv_len,) if kv_len is not None else ())
    in_specs = (spec_q, spec_kv, spec_kv) + (
        (kv_spec,) if kv_len is not None else ())
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=spec_q, check_rep=False)(*args)
