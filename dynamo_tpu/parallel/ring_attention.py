"""Ring attention: sequence-parallel causal attention over the "sp" mesh
axis for long-context prefill.

The reference has NO sequence/context parallelism (SURVEY.md §2.3: no hits
for ring/ulysses/context_parallel) — its long-context levers stop at KV
paging + conditional disagg. This is the TPU build's designed-fresh
capability (SURVEY.md §5.7): shard the prompt over `sp`, keep each device's
KV chunk resident, and rotate KV around the ring with `lax.ppermute` so
every query chunk sees every KV chunk while per-device memory stays
O(seq_len / sp). Softmax is accumulated online (flash-style m/l/acc
carries), so the result is exact — not an approximation.

Communication pattern: n-1 ppermute steps of [S/n, KVH, Dh] chunks ride the
ICI ring concurrently with the local chunk matmuls (XLA overlaps the
collective-permute with compute when the chunk math is large enough —
the classic ring-attention latency-hiding schedule).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

# MLA hop bodies stream the resident row chunk in sub-chunks of this
# many tokens so the live [H, Tl, sub] score buffer stays bounded at
# long context (chunks not divisible by it run as one piece)
RING_SUB_CHUNK = 1024

__all__ = ["ring_attention_local", "ring_attention",
           "ring_attention_mla_local", "ring_attention_mla"]


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         *, axis_name: str, scale: float,
                         q_offset: Optional[jax.Array] = None,
                         kv_len: Optional[jax.Array] = None,
                         impl: str = "dense") -> jax.Array:
    """Per-shard body (call inside shard_map over `axis_name`).

    q: [Tl, H, Dh] — this device's query chunk (global sequence is the
    concatenation over the axis, in axis order).
    k/v: [Sl, KVH, Dh] — this device's resident KV chunk.
    q_offset: global position of q[0] (default: axis_index * Tl).
    kv_len: total valid kv length (default: axis_size * Sl) — positions
    beyond it are masked (padded final chunk).

    impl: per-hop attention body. "dense" materializes [KVH, g, Tl, Sl]
    scores — fine for moderate chunks, O((T/sp)²) memory at long context.
    "flash"/"flash_interpret" streams each hop through the Pallas partial
    kernel (engine/attention.flash_prefill_partial): O(TQ·SC) live memory
    per hop, so per-device memory stays O(T/sp) end to end — the long-
    context configuration.

    Returns [Tl, H, Dh].
    """
    Tl, H, Dh = q.shape
    Sl, KVH, _ = k.shape
    g = H // KVH
    n = jax.lax.psum(1, axis_name)   # axis_size is jax>=0.5; psum(1) is portable
    me = jax.lax.axis_index(axis_name)
    if q_offset is None:
        q_offset = me * Tl
    total = n * Sl if kv_len is None else kv_len
    perm = [(i, (i + 1) % n) for i in range(n)]

    if impl.startswith("flash"):
        from ..engine.attention import flash_prefill_partial
        interpret = impl == "flash_interpret"

        m0 = jnp.full((Tl, H), NEG_INF, jnp.float32)
        l0 = jnp.zeros((Tl, H), jnp.float32)
        acc0 = jnp.zeros((Tl, H, Dh), jnp.float32)

        def step(carry, s):
            k_c, v_c, m, l, acc = carry
            src = (me - s) % n             # who computed this chunk
            # hop combine: the kernel returns this chunk's partial
            # (acc_c, m_c, l_c); merge with the carried state via the
            # online-softmax recurrence
            acc_c, m_c, l_c = flash_prefill_partial(
                q, k_c, v_c, scale=scale,
                start_pos=q_offset - src * Sl,
                seq_len=jnp.clip(total - src * Sl, 0, Sl),
                interpret=interpret)
            m_new = jnp.maximum(m, m_c)
            a_old = jnp.exp(m - m_new)
            a_new = jnp.exp(m_c - m_new)
            l = l * a_old + l_c * a_new
            acc = acc * a_old[..., None] + acc_c * a_new[..., None]
            k_n = jax.lax.ppermute(k_c, axis_name, perm)
            v_n = jax.lax.ppermute(v_c, axis_name, perm)
            return (k_n, v_n, m_new, l, acc), None

        (_, _, m, l, acc), _ = jax.lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(n))
        out = acc / jnp.maximum(l, 1e-20)[..., None]           # [Tl,H,Dh]
        return out.astype(q.dtype)

    qg = (q.astype(jnp.float32) * scale).reshape(Tl, KVH, g, Dh)
    qpos = q_offset + jnp.arange(Tl, dtype=jnp.int32)          # [Tl]

    m0 = jnp.full((KVH, g, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((KVH, g, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((KVH, g, Tl, Dh), jnp.float32)

    def step(carry, s):
        k_c, v_c, m, l, acc = carry
        src = (me - s) % n                     # who computed this chunk
        kpos = src * Sl + jnp.arange(Sl, dtype=jnp.int32)      # [Sl]
        scores = jnp.einsum("tkgd,skd->kgts", qg,
                            k_c.astype(jnp.float32))           # [KVH,g,Tl,Sl]
        mask = (kpos[None, :] <= qpos[:, None]) & (kpos[None, :] < total)
        scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(scores - m_new)
        # fully-masked rows: m_new stays NEG_INF and p would be exp(0)=1 —
        # zero them so padded chunks contribute nothing
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("kgts,skd->kgtd", p,
                                       v_c.astype(jnp.float32))
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        return (k_n, v_n, m_new, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-20)                          # [KVH,g,Tl,Dh]
    return out.transpose(2, 0, 1, 3).reshape(Tl, H, Dh).astype(q.dtype)


def ring_attention_mla_local(q_lat: jax.Array, q_pe: jax.Array,
                             rows: jax.Array, *, axis_name: str,
                             scale: float, rank: int,
                             kv_len: Optional[jax.Array] = None
                             ) -> jax.Array:
    """Per-shard MLA ring body (models/mla.py absorbed attention, call
    inside shard_map over ``axis_name``).

    The ring payload is the LATENT ROW chunk [Sl, rank+rope] — e.g. 576
    lanes per token TOTAL, vs llama's per-head 2·KVH·Dh — and the
    accumulator lives in latent space: scores contract q_lat·c + q_pe·
    k_pe per hop and the context accumulates p·c as [Tl, H, rank]; the
    caller applies w_v ONCE after the ring. That is the absorbed-decode
    trick lifted to sequence-parallel prefill: per-hop compute is three
    rank-space matmuls while the ICI hop moves only compressed rows.

    q_lat: [Tl, H, rank] (queries already dropped into latent space via
    w_k), q_pe: [Tl, H, dr] (post-rope), rows: [Sl, rank+dr] (post-norm
    c_kv | post-rope k_pe). Returns the latent context [Tl, H, rank].

    Transient memory: the hop body streams the resident chunk in
    RING_SUB_CHUNK-row sub-chunks through the same online-softmax
    recurrence, so the live score buffer is [H, Tl, sub] — not
    [H, Tl, Sl] — and per-hop transients stay bounded at long context
    (the state itself, q_lat/acc [Tl, H, rank], is the absorbed form's
    inherent footprint)."""
    Tl, H, R = q_lat.shape
    Sl = rows.shape[0]
    n = jax.lax.psum(1, axis_name)   # axis_size is jax>=0.5; psum(1) is portable
    me = jax.lax.axis_index(axis_name)
    q_offset = me * Tl
    total = n * Sl if kv_len is None else kv_len
    perm = [(i, (i + 1) % n) for i in range(n)]
    qpos = q_offset + jnp.arange(Tl, dtype=jnp.int32)          # [Tl]

    ql = q_lat.astype(jnp.float32) * scale                     # [Tl,H,R]
    qp = q_pe.astype(jnp.float32) * scale

    sub = RING_SUB_CHUNK if Sl % RING_SUB_CHUNK == 0 else Sl
    n_sub = Sl // sub

    m0 = jnp.full((H, Tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, Tl, 1), jnp.float32)
    acc0 = jnp.zeros((H, Tl, R), jnp.float32)

    def step(carry, s):
        rows_c, m, l, acc = carry
        src = (me - s) % n                     # who computed this chunk

        def sub_step(carry2, j):
            m, l, acc = carry2
            rows_s = jax.lax.dynamic_slice_in_dim(rows_c, j * sub, sub)
            c = rows_s[:, :rank].astype(jnp.float32)           # [sub,R]
            pe = rows_s[:, rank:].astype(jnp.float32)          # [sub,dr]
            kpos = src * Sl + j * sub + jnp.arange(sub, dtype=jnp.int32)
            scores = (jnp.einsum("thr,sr->hts", ql, c)
                      + jnp.einsum("thd,sd->hts", qp, pe))     # [H,Tl,sub]
            mask = ((kpos[None, :] <= qpos[:, None])
                    & (kpos[None, :] < total))
            scores = jnp.where(mask[None, :, :], scores, NEG_INF)
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(scores - m_new)
            # fully-masked rows: exp(NEG_INF - NEG_INF) = 1 — zero them
            # so padded chunks contribute nothing
            p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
            alpha = jnp.exp(m - m_new)
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jnp.einsum("hts,sr->htr", p, c)
            return (m_new, l, acc), None

        (m, l, acc), _ = jax.lax.scan(sub_step, (m, l, acc),
                                      jnp.arange(n_sub))
        rows_n = jax.lax.ppermute(rows_c, axis_name, perm)
        return (rows_n, m, l, acc), None

    (_, m, l, acc), _ = jax.lax.scan(step, (rows, m0, l0, acc0),
                                     jnp.arange(n))
    ctx = acc / jnp.maximum(l, 1e-20)                          # [H,Tl,R]
    return ctx.transpose(1, 0, 2).astype(q_lat.dtype)


def ring_attention_mla(q_lat: jax.Array, q_pe: jax.Array,
                       rows: jax.Array, mesh: Mesh, *, scale: float,
                       rank: int, axis_name: str = "sp",
                       tp_axis: Optional[str] = "tp",
                       kv_len: Optional[jax.Array] = None) -> jax.Array:
    """Global entry: q_lat [T, H, rank] / q_pe [T, H, dr] with the
    sequence axis sharded over ``axis_name`` (heads optionally over
    ``tp_axis``); rows [S, rank+dr] sequence-sharded and REPLICATED over
    tp (every head reads the same latent rows). T and S must divide by
    the axis size. Returns the latent context [T, H, rank]."""
    head_ax = tp_axis if (tp_axis and tp_axis in mesh.shape) else None
    spec_q = P(axis_name, head_ax, None)
    spec_rows = P(axis_name, None)
    kv_spec = None if kv_len is None else P()

    def body(ql, qp, r, *rest):
        kvl = rest[0] if rest else None
        return ring_attention_mla_local(ql, qp, r, axis_name=axis_name,
                                        scale=scale, rank=rank,
                                        kv_len=kvl)

    args = (q_lat, q_pe, rows) + ((kv_len,) if kv_len is not None else ())
    in_specs = (spec_q, spec_q, spec_rows) + (
        (kv_spec,) if kv_len is not None else ())
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=spec_q, check_rep=False)(*args)


def _default_impl(num_heads: int, num_kv_heads: int, head_dim: int) -> str:
    from ..engine.attention import _on_tpu, flash_prefill_supported
    return ("flash" if _on_tpu()
            and flash_prefill_supported(num_heads, num_kv_heads, head_dim)
            else "dense")


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   *, scale: float, axis_name: str = "sp",
                   tp_axis: Optional[str] = "tp",
                   kv_len: Optional[jax.Array] = None,
                   impl: str = "auto") -> jax.Array:
    """Global entry: q [T, H, Dh], k/v [S, KVH, Dh] with the sequence axis
    sharded over `axis_name` (and heads optionally over `tp_axis`). T and S
    must divide by the axis size. Returns [T, H, Dh], same shardings.

    impl: "auto" picks the Pallas flash hop body on TPU (per-device memory
    O(T/sp) at any context length), dense einsum elsewhere."""
    if impl == "auto":
        impl = _default_impl(q.shape[1], k.shape[1], q.shape[2])
    head_ax = tp_axis if (tp_axis and tp_axis in mesh.shape) else None
    spec_q = P(axis_name, head_ax, None)
    spec_kv = P(axis_name, head_ax, None)
    kv_spec = None if kv_len is None else P()

    def body(q_l, k_l, v_l, *rest):
        kvl = rest[0] if rest else None
        return ring_attention_local(q_l, k_l, v_l, axis_name=axis_name,
                                    scale=scale, kv_len=kvl, impl=impl)

    args = (q, k, v) + ((kv_len,) if kv_len is not None else ())
    in_specs = (spec_q, spec_kv, spec_kv) + (
        (kv_spec,) if kv_len is not None else ())
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=spec_q, check_rep=False)(*args)
