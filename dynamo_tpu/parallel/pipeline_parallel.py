"""Pipeline parallelism: layer-partitioned decode over a ``pp`` mesh axis.

Why PP exists here (VERDICT r4 item 6; reference analog: the vLLM
engines' ``pipeline_parallel_size=num_nodes``,
lib/llm/src/engines/vllm/subprocess.rs:41): tensor parallelism needs two
[B, D] all-reduces PER LAYER, which is only affordable over ICI — across
hosts on DCN (25 Gb/s) an 80-layer model would spend ~107 ms/step in
collectives (tools/bandwidth_model.py rates). Pipeline parallelism moves
ONE [B, D] activation per stage boundary per step — the only viable
cross-host axis, and the capacity enabler for checkpoints that exceed a
host's HBM (DeepSeek-V3 int8 ≈ 336 GB > any single v5e/v5p host).

Design (v1, deliberately minimal and correct):

- stacked layer params and the paged KV pool shard their leading L axis
  over ``pp`` (P("pp", ...)) — each rank OWNS its layer slice and the KV
  written by those layers; nothing else moves.
- the forward is a shard_map stage loop: every rank runs its local
  ``llama._run_layers`` each stage; only the rank whose turn it is has
  the real activation, and the chain hands it to the next rank with one
  ppermute per boundary. Off-turn ranks compute garbage at full speed
  (the classic un-microbatched bubble: utilization 1/pp) and their KV
  writes are masked to dead slots (scatter mode="drop"), so the pool
  stays exact.
- embed runs replicated before the loop; final norm + lm head replicate
  and run after the last stage's activation is broadcast (psum of a
  rank-masked copy).

Deliberate v1 limits (documented, loud):
- no microbatched prefill / token-pipelined decode yet — the bubble
  makes pp=k cost ~k× a single stage's time, so v1 is the CAPACITY and
  cross-host-topology axis, not a same-host throughput axis (PERF.md
  "Round 5: pipeline parallelism" has the measured arithmetic; on one
  host TP+SP strictly dominates and remains the default).
- pp composes with nothing else in-engine yet (mesh must factor other
  axes at 1); tp×pp needs in-stage collectives under shard_map.
- sliding-window families refuse: the global layer index decides each
  layer's window flag, and v1 statics are built per-slice.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..engine.models import llama


def pp_split_config(statics, pp: int):
    """Per-stage statics: the local stack is num_layers/pp deep."""
    cfg = statics.cfg
    if cfg.num_layers % pp != 0:
        raise ValueError(
            f"pp={pp} must divide num_layers={cfg.num_layers}")
    if cfg.sliding_window is not None:
        raise NotImplementedError(
            "pp with sliding-window layer patterns is not implemented — "
            "the window flag depends on the GLOBAL layer index (v1 "
            "statics are per-slice)")
    local_cfg = dataclasses.replace(cfg,
                                    num_layers=cfg.num_layers // pp)
    return dataclasses.replace(statics, cfg=local_cfg)


def pp_decode_forward(params: Dict[str, jax.Array], kv, tokens, positions,
                      block_tables, statics, mesh) -> Tuple[jax.Array, dict]:
    """Batched single-token decode over a pp-sharded layer stack.

    Same contract as llama.decode_forward; params' ``layers.*`` stacks
    and the kv pools must be sharded P("pp") on their leading axis (the
    caller places them — pp_param_pspecs/pp_kv_pspecs)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = statics.cfg
    pp = mesh.shape["pp"]
    local_statics = pp_split_config(statics, pp)
    local_cfg = local_statics.cfg
    B = tokens.shape[0]
    bsz = statics.block_size
    scale = llama._attn_scale(cfg)
    slots = (block_tables[jnp.arange(B), positions // bsz] * bsz
             + positions % bsz)
    seq_lens = positions + 1

    stacks = {k: v for k, v in params.items() if k.startswith("layers.")}
    x0 = llama._embed(params, tokens, cfg)            # [B, D], replicated

    ring = [(i, (i + 1) % pp) for i in range(pp)]

    def stage_fn(stacks_l, kv_l, x, positions, slots, seq_lens,
                 block_tables):
        r = jax.lax.axis_index("pp")

        def attn(q, _k, _v, k_flat, v_flat, li, sliding):
            num_blocks = k_flat.shape[0] // (local_cfg.num_layers * bsz)
            return llama.paged_attention(
                q, k_flat, v_flat, block_tables + li * num_blocks,
                seq_lens, block_size=bsz, scale=scale,
                impl=local_statics.attn_impl,
                softcap=local_cfg.attn_logit_softcap,
                kv_heads=local_cfg.num_kv_heads)

        for s in range(pp):
            if s:
                x = jax.lax.ppermute(x, "pp", ring)
            my_turn = r == s
            # off-turn ranks run the same program on garbage input (the
            # un-microbatched bubble) — their KV scatters are masked to
            # index NTOK, which is genuinely OUT OF BOUNDS and dropped
            # by mode="drop". (-1 would NOT work: advanced-index
            # scatter normalizes negatives first, so -1 silently
            # overwrites the pool's LAST row — round-5 review catch.)
            ntok = kv_l["k"].shape[1]
            slots_eff = jnp.where(my_turn, slots, ntok)
            x2, kv_l = llama._run_layers(stacks_l, kv_l, x, positions,
                                         slots_eff, local_cfg, attn,
                                         final_norm=False)
            x = jnp.where(my_turn, x2, x)
        # rank pp-1 holds the final activation; hand it around the ring
        # once and psum a rank-0 mask so every rank returns the same x
        x = jax.lax.ppermute(x, "pp", ring)
        x = jax.lax.psum(
            jnp.where(jax.lax.axis_index("pp") == 0, x, 0.0), "pp")
        return x, kv_l

    stack_specs = {k: P("pp") for k in stacks}
    kv_specs = {k: P("pp") for k in kv}
    fn = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(stack_specs, kv_specs, P(), P(), P(), P(), P()),
        out_specs=(P(), kv_specs),
        check_rep=False)
    x, kv_new = fn(stacks, kv, x0, positions, slots, seq_lens,
                   block_tables)
    x = llama.rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                       cfg.norm_plus_one)
    return llama._logits(params, x, cfg), kv_new


def pp_param_pspecs(cfg) -> Dict[str, "jax.sharding.PartitionSpec"]:
    """Layer stacks sharded on L over pp; everything else replicated."""
    from jax.sharding import PartitionSpec as P
    from ..engine.models.llama import param_shapes
    out = {}
    for k in param_shapes(cfg):
        out[k] = P("pp") if k.startswith("layers.") else P()
    return out


def pp_kv_pspecs() -> Dict[str, "jax.sharding.PartitionSpec"]:
    from jax.sharding import PartitionSpec as P
    return {"k": P("pp"), "v": P("pp")}


def make_pp_mesh(pp: int, devices=None):
    import numpy as np
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    if pp > len(devices):
        raise ValueError(f"pp={pp} > {len(devices)} devices")
    return Mesh(np.array(devices[:pp]), ("pp",))
