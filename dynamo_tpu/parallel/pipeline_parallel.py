"""Pipeline parallelism: layer-partitioned serving over a ``pp`` mesh axis.

Why PP exists here (VERDICT r4 item 6; reference analog: the vLLM
engines' ``pipeline_parallel_size=num_nodes``,
lib/llm/src/engines/vllm/subprocess.rs:41): tensor parallelism needs two
[B, D] all-reduces PER LAYER, which is only affordable over ICI — across
hosts on DCN (25 Gb/s) an 80-layer model would spend ~95 ms/step in
collectives (tools/bandwidth_model.py rates). Pipeline parallelism moves
ONE [B, D] activation per stage boundary per step — the only viable
cross-host axis, and the capacity enabler for checkpoints that exceed a
host's HBM (DeepSeek-V3 int8 ≈ 336 GB > any single v5e/v5p host).

v2 (this round): PP is a THROUGHPUT axis, not just a capacity axis.

- **Token-interleaved decode** (`pp_decode_k_forward`): the decode batch
  B splits into ``pp`` microbatches of B/pp rows and round-robins them
  through the stage ring. At tick t, rank r runs step ``(t-r)//pp`` of
  microbatch ``(t-r) % pp`` through its local layer slice, then hands
  the [B/pp, D] activation to rank r+1 over one ppermute. The last rank
  additionally norms, projects, SAMPLES the microbatch's next token and
  sends the EMBEDDED next-step input back into the ring — so the
  sampled-token → next-step dependency rides the same boundary hop and
  every rank computes a LIVE microbatch every tick. A K-step dispatch
  runs ``K*pp + (pp-1)`` ticks: steady-state utilization
  K·pp/(K·pp+pp-1) → ~1 (vs the v1 bubbled loop's 1/pp), with the
  (pp-1)-tick fill/drain ramp amortized over the dispatch.
- **Microbatched prefill** (`pp_prefill_forward`): a padded [T] prompt
  chunk splits into pp sequential C=T/pp sub-chunks pipelined through
  the same schedule (chunk m at stage r on tick m+r, 2·pp-1 ticks) —
  chunked prefill FILLS the pipe instead of bubbling it. Each sub-chunk
  is exactly a ``_chunked_prefill`` continuation (start_pos + m·C
  against the KV earlier chunks already wrote), so the math matches the
  engine's sequential chunk walk.
- **tp×pp composition**: the stage ring composes with in-stage tensor
  parallelism for the split-matmul (unfused) llama dense path — layer
  stacks shard ("pp" on L, "tp" on the Megatron column/row axes), the
  KV pool shards ("pp" on L, "tp" on head lanes), and
  `llama._run_layers(reduce_axis="tp")` psums the row-parallel
  outputs inside the stage. Embed / final-norm / lm_head stay
  replicated (the last stage samples locally). ``fuse_stacked_matmuls``
  must stay OFF under ANY mesh — tp because the fused out axis cannot
  carry the column permutation, pp because the stage loop shards the
  unfused per-tensor layout (EngineCore gates on ``mesh is None``).

Exactness contract: per-microbatch KV scatters, positions, and sampling
keys are the SAME per-slot values the single-device decode_k scan uses
(make_slot_keys(seed, seeds[slot], steps0[slot]+k) — row-local, batch-
size-independent), so pp=k token streams are bit-exact vs single-device
(tests/test_pipeline_parallel.py asserts token equality over chained
dispatches, incl. through the EngineCore serving path and across a
preemption landing mid-stream).

Off-schedule (ramp) ticks compute garbage at full speed; their KV
scatters are masked to index NTOK, which is genuinely OUT OF BOUNDS and
dropped by mode="drop". (-1 would NOT work: advanced-index scatter
normalizes negatives first, so -1 silently overwrites the pool's LAST
row — round-5 review catch.)

Remaining v2 limits (refused loudly by EngineCore, not silently wrong):
weight/KV quantization (QuantizedArray leaves under the stage shard_map
are unvalidated), MLA, speculative decoding (the verify program has no
interleaved form yet), sp composition, and sliding-window families (the
window flag depends on the GLOBAL layer index; statics are per-slice).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..engine.models import llama


# ------------------------------------------------------------------ schedule
def pp_dispatch_ticks(pp: int, K: int) -> int:
    """Ticks one K-step interleaved dispatch runs: K rounds of pp ticks
    plus the (pp-1)-tick fill/drain ramp."""
    return K * pp + (pp - 1)


def pp_dispatch_utilization(pp: int, K: int) -> float:
    """Fraction of a rank's ticks spent on a live microbatch: each rank
    idles exactly pp-1 ramp ticks per dispatch."""
    if pp <= 1:
        return 1.0
    return K * pp / pp_dispatch_ticks(pp, K)


def pp_bubble_fraction(pp: int, K: int) -> float:
    return 1.0 - pp_dispatch_utilization(pp, K)


def pp_split_config(statics, pp: int):
    """Per-stage statics: the local stack is num_layers/pp deep."""
    cfg = statics.cfg
    if cfg.num_layers % pp != 0:
        raise ValueError(
            f"pp={pp} must divide num_layers={cfg.num_layers}")
    if cfg.sliding_window is not None:
        raise NotImplementedError(
            "pp with sliding-window layer patterns is not implemented — "
            "the window flag depends on the GLOBAL layer index (statics "
            "are per-slice)")
    local_cfg = dataclasses.replace(cfg,
                                    num_layers=cfg.num_layers // pp)
    return dataclasses.replace(statics, cfg=local_cfg)


def _local_cfg_for(statics, pp: int, tp: int):
    """Per-rank model config: L/pp layers, and H/tp + KVH/tp heads when
    tensor parallelism runs inside the stage."""
    local_statics = pp_split_config(statics, pp)
    local_cfg = local_statics.cfg
    if tp > 1:
        cfg = statics.cfg
        if cfg.num_heads % tp or cfg.num_kv_heads % tp:
            raise ValueError(
                f"tp={tp} inside a pp stage must divide both head counts "
                f"(H={cfg.num_heads}, KVH={cfg.num_kv_heads})")
        if cfg.num_experts > 0:
            raise NotImplementedError(
                "tp×pp with MoE expert grids is not implemented (the "
                "in-stage reduce covers the dense split-matmul path)")
        local_cfg = dataclasses.replace(
            local_cfg, num_heads=cfg.num_heads // tp,
            num_kv_heads=cfg.num_kv_heads // tp)
    return dataclasses.replace(local_statics, cfg=local_cfg)


# ------------------------------------------------------------- v1 (bubbled)
def pp_decode_forward(params: Dict[str, jax.Array], kv, tokens, positions,
                      block_tables, statics, mesh) -> Tuple[jax.Array, dict]:
    """v1 bubbled single-step decode over a pp-sharded layer stack — kept
    as the regression/bench baseline the interleaved loop is judged
    against (`bench.py --pp` measures both under one protocol).

    Same contract as llama.decode_forward; params' ``layers.*`` stacks
    and the kv pools must be sharded P("pp") on their leading axis (the
    caller places them — pp_param_pspecs/pp_kv_pspecs). Every rank runs
    its local stack each of the pp stage iterations; only the rank whose
    turn it is has the real activation (utilization 1/pp — the bubble
    pp_decode_k_forward removes)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = statics.cfg
    pp = mesh.shape["pp"]
    local_statics = pp_split_config(statics, pp)
    local_cfg = local_statics.cfg
    B = tokens.shape[0]
    bsz = statics.block_size
    scale = llama._attn_scale(cfg)
    slots = (block_tables[jnp.arange(B), positions // bsz] * bsz
             + positions % bsz)
    seq_lens = positions + 1

    stacks = {k: v for k, v in params.items() if k.startswith("layers.")}
    x0 = llama._embed(params, tokens, cfg)            # [B, D], replicated

    ring = [(i, (i + 1) % pp) for i in range(pp)]

    def stage_fn(stacks_l, kv_l, x, positions, slots, seq_lens,
                 block_tables):
        r = jax.lax.axis_index("pp")

        def attn(q, _k, _v, k_flat, v_flat, li, sliding):
            num_blocks = k_flat.shape[0] // (local_cfg.num_layers * bsz)
            return llama.paged_attention(
                q, k_flat, v_flat, block_tables + li * num_blocks,
                seq_lens, block_size=bsz, scale=scale,
                impl=local_statics.attn_impl,
                softcap=local_cfg.attn_logit_softcap,
                kv_heads=local_cfg.num_kv_heads,
                coalesce=local_statics.kv_coalesce)

        for s in range(pp):
            if s:
                x = jax.lax.ppermute(x, "pp", ring)
            my_turn = r == s
            # off-turn ranks run the same program on garbage input (the
            # un-microbatched bubble) — their KV scatters are masked to
            # index NTOK (OOB, dropped by mode="drop"; see module
            # docstring on why -1 would corrupt the pool's last row)
            ntok = kv_l["k"].shape[1]
            slots_eff = jnp.where(my_turn, slots, ntok)
            x2, kv_l = llama._run_layers(stacks_l, kv_l, x, positions,
                                         slots_eff, local_cfg, attn,
                                         final_norm=False)
            x = jnp.where(my_turn, x2, x)
        # rank pp-1 holds the final activation; hand it around the ring
        # once and psum a rank-0 mask so every rank returns the same x
        x = jax.lax.ppermute(x, "pp", ring)
        x = jax.lax.psum(
            jnp.where(jax.lax.axis_index("pp") == 0, x, 0.0), "pp")
        return x, kv_l

    stack_specs = {k: P("pp") for k in stacks}
    kv_specs = {k: P("pp") for k in kv}
    fn = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(stack_specs, kv_specs, P(), P(), P(), P(), P()),
        out_specs=(P(), kv_specs),
        check_rep=False)
    x, kv_new = fn(stacks, kv, x0, positions, slots, seq_lens,
                   block_tables)
    x = llama.rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                       cfg.norm_plus_one)
    return llama._logits(params, x, cfg), kv_new


# --------------------------------------------------- v2: token interleaving
def pp_decode_k_forward(params, kv, tokens, positions, block_tables,
                        seeds, steps0, temperature, top_k, top_p,
                        planned, planned_mask, statics, mesh, K: int,
                        seed: int) -> Tuple[jax.Array, jax.Array, dict]:
    """Token-interleaved K-step decode over a pp(×tp) mesh — the SAME
    contract as the engine's fused decode_k scan: returns
    (toks [K, B] int32, logprobs [K, B] f32, kv), with per-(seed,
    key_step) sampling keys lockstep with single-device decode.

    Schedule (module docstring): microbatch m runs its k-th step through
    stage r at tick t = m + k·pp + r. The last stage samples and sends
    the embedded next-step input into the ring, so the token dependency
    crosses exactly one boundary per step — every rank is live every
    steady-state tick. ``planned``/``planned_mask`` [K, B] feed
    lane-prefill planned tokens exactly as the single-device scan does:
    step 0 inputs override at the rank-0 fresh embed, later steps at the
    last stage's next-token selection.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..engine.sampling import make_slot_keys, sample_tokens

    cfg = statics.cfg
    pp = mesh.shape["pp"]
    tp = mesh.shape["tp"] if "tp" in mesh.axis_names else 1
    B = tokens.shape[0]
    if B % pp:
        raise ValueError(f"decode batch {B} must divide by pp={pp} "
                         f"(one microbatch per stage)")
    mb = B // pp
    local_statics = _local_cfg_for(statics, pp, tp)
    local_cfg = local_statics.cfg
    bsz = statics.block_size
    scale = llama._attn_scale(cfg)
    T_ticks = pp_dispatch_ticks(pp, K)
    ring = [(i, (i + 1) % pp) for i in range(pp)]

    stacks = {k: v for k, v in params.items() if k.startswith("layers.")}
    rest = {k: v for k, v in params.items()
            if not k.startswith("layers.")}
    specs = pp_param_pspecs(cfg, tp=tp)
    stack_specs = {k: specs.get(k, P("pp")) for k in stacks}
    rest_specs = {k: P() for k in rest}
    kv_specs = {k: v for k, v in pp_kv_pspecs(tp=tp).items() if k in kv}

    def stage_fn(stacks_l, rest_p, kv_l, tokens, positions, block_tables,
                 seeds, steps0, temperature, top_k, top_p, planned,
                 pmask):
        r = jax.lax.axis_index("pp")
        ntok = kv_l["k"].shape[1]
        num_blocks = ntok // bsz
        act_dtype = rest_p["final_norm"].dtype

        def mb_slice(a, m):
            return jax.lax.dynamic_slice_in_dim(a, m * mb, mb, axis=0)

        def tick(t, carry):
            x, kvk, kvv, toks_out, lps_out = carry
            km = t - r
            m = jnp.remainder(km, pp)
            k = jnp.floor_divide(km, pp)
            live = jnp.logical_and(km >= 0, km < K * pp)
            # a fresh microbatch enters the ring at rank 0, step 0: its
            # input token is host-fed (with the step-0 planned override,
            # exactly the single-device scan's where(pm, pt, tokens))
            tok0 = jnp.where(mb_slice(pmask[0], m),
                             mb_slice(planned[0], m), mb_slice(tokens, m))
            fresh = jnp.logical_and(r == 0,
                                    jnp.logical_and(live, k == 0))
            x = jnp.where(fresh, llama._embed(rest_p, tok0, cfg), x)

            pos_mb = mb_slice(positions, m) + k
            tables_mb = mb_slice(block_tables, m)
            slots = (tables_mb[jnp.arange(mb), pos_mb // bsz] * bsz
                     + pos_mb % bsz)
            slots = jnp.where(live, slots, ntok)   # ramp: OOB-dropped
            seq_lens = pos_mb + 1

            def attn(q, _k, _v, k_flat, v_flat, li, sliding):
                return llama.paged_attention(
                    q, k_flat, v_flat, tables_mb + li * num_blocks,
                    seq_lens, block_size=bsz, scale=scale,
                    impl=local_statics.attn_impl,
                    softcap=local_cfg.attn_logit_softcap,
                    kv_heads=local_cfg.num_kv_heads,
                    coalesce=local_statics.kv_coalesce)

            y, kv_new = llama._run_layers(
                stacks_l, {"k": kvk, "v": kvv}, x, pos_mb, slots,
                local_cfg, attn, final_norm=False,
                reduce_axis="tp" if tp > 1 else None)
            kvk, kvv = kv_new["k"], kv_new["v"]

            is_last = jnp.logical_and(r == pp - 1, live)
            kc = jnp.clip(k, 0, K - 1)

            def last_stage(y):
                # the finishing stage: norm + head + SAMPLE this
                # microbatch's step-k token, then send the EMBEDDED
                # next-step input into the ring (rank 0 consumes it next
                # tick). lax.cond keeps the head off the pp-1 other
                # ranks' ticks — it has no collectives, so the dynamic
                # branch is safe under shard_map.
                xn = llama.rms_norm(y, rest_p["final_norm"],
                                    cfg.rms_norm_eps, cfg.norm_plus_one)
                logits = llama._logits(rest_p, xn, cfg)
                keys = make_slot_keys(seed, mb_slice(seeds, m),
                                      mb_slice(steps0, m) + kc)
                toks, lps = sample_tokens(
                    logits, keys, mb_slice(temperature, m),
                    mb_slice(top_k, m), mb_slice(top_p, m))
                kn = jnp.clip(kc + 1, 0, K - 1)
                pl_row = jax.lax.dynamic_slice(planned, (kn, m * mb),
                                               (1, mb))[0]
                pm_row = jax.lax.dynamic_slice(pmask, (kn, m * mb),
                                               (1, mb))[0]
                tok_next = jnp.where(
                    jnp.logical_and(pm_row, kc + 1 < K), pl_row, toks)
                return toks, lps, llama._embed(rest_p, tok_next, cfg)

            def mid_stage(y):
                return (jnp.zeros((mb,), jnp.int32),
                        jnp.zeros((mb,), jnp.float32),
                        y.astype(act_dtype))

            toks_mb, lps_mb, x_send = jax.lax.cond(
                is_last, last_stage, mid_stage, y.astype(act_dtype))

            upd_t = jax.lax.dynamic_update_slice(
                toks_out, toks_mb[None], (kc, m * mb))
            upd_l = jax.lax.dynamic_update_slice(
                lps_out, lps_mb[None], (kc, m * mb))
            toks_out = jnp.where(is_last, upd_t, toks_out)
            lps_out = jnp.where(is_last, upd_l, lps_out)

            x = jax.lax.ppermute(x_send, "pp", ring)
            return (x, kvk, kvv, toks_out, lps_out)

        init = (jnp.zeros((mb, cfg.hidden_size), dtype=act_dtype),
                kv_l["k"], kv_l["v"],
                jnp.zeros((K, B), jnp.int32),
                jnp.zeros((K, B), jnp.float32))
        _, kvk, kvv, toks_out, lps_out = jax.lax.fori_loop(
            0, T_ticks, tick, init)
        # only rank pp-1 wrote its (live) rows; the rest hold zeros — the
        # pp psum replicates the harvest (tp ranks computed identical
        # replicated values, so no reduction over "tp")
        toks_out = jax.lax.psum(toks_out, "pp")
        lps_out = jax.lax.psum(lps_out, "pp")
        return toks_out, lps_out, {"k": kvk, "v": kvv}

    fn = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(stack_specs, rest_specs, kv_specs,
                  P(), P(), P(), P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), kv_specs),
        check_rep=False)
    return fn(stacks, rest, kv, tokens, positions, block_tables,
              seeds, steps0, temperature, top_k, top_p,
              planned, planned_mask)


def pp_prefill_forward(params, kv, tokens, block_table, start_pos,
                       true_len, statics, mesh
                       ) -> Tuple[jax.Array, dict]:
    """Microbatched single-sequence prefill over a pp(×tp) mesh — same
    contract as llama.prefill_forward (returns (logits_last [V], kv)).

    The padded [T] chunk splits into pp sequential C=T/pp sub-chunks;
    sub-chunk m runs stage r at tick m+r (2·pp-1 ticks total), so the
    pipe fills instead of every rank bubbling through the whole chunk.
    Each sub-chunk is mathematically the engine's ``_chunked_prefill``
    continuation: positions start_pos + m·C.., attention over the KV the
    earlier sub-chunks already wrote (chunk m-1 left rank r one tick
    before chunk m arrives — causality holds by the schedule). Pad
    positions scatter to the trash slot 0 exactly like prefill_forward;
    ramp ticks mask to the OOB NTOK drop."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = statics.cfg
    pp = mesh.shape["pp"]
    tp = mesh.shape["tp"] if "tp" in mesh.axis_names else 1
    T = tokens.shape[0]
    if T % pp:
        raise ValueError(f"prefill chunk length {T} must divide by "
                         f"pp={pp} (one sub-chunk per stage)")
    C = T // pp
    local_statics = _local_cfg_for(statics, pp, tp)
    local_cfg = local_statics.cfg
    bsz = statics.block_size
    scale = llama._attn_scale(cfg)
    ring = [(i, (i + 1) % pp) for i in range(pp)]
    use_flash = llama._prefill_flash_impl(local_statics)

    stacks = {k: v for k, v in params.items() if k.startswith("layers.")}
    rest = {k: v for k, v in params.items()
            if not k.startswith("layers.")}
    specs = pp_param_pspecs(cfg, tp=tp)
    stack_specs = {k: specs.get(k, P("pp")) for k in stacks}
    rest_specs = {k: P() for k in rest}
    kv_specs = {k: v for k, v in pp_kv_pspecs(tp=tp).items() if k in kv}

    def stage_fn(stacks_l, rest_p, kv_l, tokens, block_table, start_pos,
                 true_len):
        r = jax.lax.axis_index("pp")
        ntok = kv_l["k"].shape[1]
        act_dtype = rest_p["final_norm"].dtype

        def tick(t, carry):
            x, kvk, kvv, hbuf = carry
            m = t - r
            live = jnp.logical_and(m >= 0, m < pp)
            mc = jnp.clip(m, 0, pp - 1)
            toks_m = jax.lax.dynamic_slice_in_dim(tokens, mc * C, C)
            fresh = jnp.logical_and(r == 0, live)
            x = jnp.where(fresh, llama._embed(rest_p, toks_m, cfg), x)

            sp_m = start_pos + mc * C
            positions = sp_m + jnp.arange(C, dtype=jnp.int32)
            tl_m = jnp.clip(true_len - mc * C, 0, C)
            valid = jnp.arange(C, dtype=jnp.int32) < tl_m
            slots = jnp.where(
                valid,
                block_table[positions // bsz] * bsz + positions % bsz,
                0)
            slots = jnp.where(live, slots, ntok)   # ramp: OOB-dropped
            seq_len = sp_m + tl_m

            def attn(q, _k, _v, k_flat, v_flat, li, sliding):
                # the chunk attends the whole table (prefix + itself);
                # layer li's rows sit at offset li*NTOK in the local pool
                idx = (llama.flat_token_indices(
                    block_table[None, :], bsz)[0] + li * ntok)
                S = idx.shape[0]
                ks = jnp.take(k_flat, idx, axis=0).reshape(
                    S, local_cfg.num_kv_heads, cfg.head_dim)
                vs = jnp.take(v_flat, idx, axis=0).reshape(
                    S, local_cfg.num_kv_heads, cfg.head_dim)
                if use_flash:
                    return llama.flash_prefill(
                        q, ks, vs, scale=scale, start_pos=sp_m,
                        seq_len=seq_len, sliding=sliding,
                        window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap or None,
                        interpret=(use_flash == "interpret"))
                g = local_cfg.num_heads // local_cfg.num_kv_heads
                qg = q.reshape(C, local_cfg.num_kv_heads, g, cfg.head_dim)
                scores = jnp.einsum("tkgd,skd->kgts", qg, ks).astype(
                    jnp.float32) * scale
                if cfg.attn_logit_softcap:
                    scores = llama._softcap(scores,
                                            cfg.attn_logit_softcap)
                kv_pos = jnp.arange(S, dtype=jnp.int32)
                mask = (kv_pos[None, :] <= positions[:, None]) & (
                    kv_pos[None, :] < seq_len)
                scores = jnp.where(mask[None, None, :, :], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1).astype(vs.dtype)
                return jnp.einsum("kgts,skd->tkgd", probs, vs).reshape(
                    C, local_cfg.num_heads, cfg.head_dim)

            y, kv_new = llama._run_layers(
                stacks_l, {"k": kvk, "v": kvv}, x, positions, slots,
                local_cfg, attn, final_norm=False,
                reduce_axis="tp" if tp > 1 else None)
            y = y.astype(act_dtype)
            upd = jax.lax.dynamic_update_slice_in_dim(hbuf, y, mc * C,
                                                      axis=0)
            hbuf = jnp.where(jnp.logical_and(r == pp - 1, live),
                             upd, hbuf)
            x = jax.lax.ppermute(y, "pp", ring)
            return (x, kv_new["k"], kv_new["v"], hbuf)

        init = (jnp.zeros((C, cfg.hidden_size), dtype=act_dtype),
                kv_l["k"], kv_l["v"],
                jnp.zeros((T, cfg.hidden_size), dtype=act_dtype))
        _, kvk, kvv, hbuf = jax.lax.fori_loop(0, 2 * pp - 1, tick, init)
        hbuf = jax.lax.psum(hbuf, "pp")
        return hbuf, {"k": kvk, "v": kvv}

    fn = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(stack_specs, rest_specs, kv_specs, P(), P(), P(), P()),
        out_specs=(P(), kv_specs),
        check_rep=False)
    hbuf, kv_new = fn(stacks, rest, kv, tokens, block_table,
                      jnp.asarray(start_pos, jnp.int32),
                      jnp.asarray(true_len, jnp.int32))
    last = hbuf[jnp.maximum(true_len - 1, 0)]
    last = llama.rms_norm(last, params["final_norm"], cfg.rms_norm_eps,
                          cfg.norm_plus_one)
    return llama._logits(params, last, cfg), kv_new


# -------------------------------------------------------------- placement
def pp_param_pspecs(cfg, tp: int = 1
                    ) -> Dict[str, "jax.sharding.PartitionSpec"]:
    """Layer stacks sharded on L over "pp" (composed with the Megatron
    "tp" column/row placement in-stage when tp > 1); embed / final_norm
    / lm_head stay REPLICATED — the last stage norms, projects and
    samples locally, so there is no vocab-sharded head to re-gather."""
    from jax.sharding import PartitionSpec as P

    from ..engine.models.llama import param_shapes
    from .sharding import param_pspecs
    base = param_pspecs(cfg) if tp > 1 else {}
    out = {}
    for k in param_shapes(cfg):
        if not k.startswith("layers."):
            out[k] = P()
            continue
        spec = base.get(k)
        if tp > 1 and spec is not None and len(spec) > 1:
            out[k] = P("pp", *tuple(spec)[1:])
        else:
            out[k] = P("pp")
    return out


def pp_kv_pspecs(tp: int = 1) -> Dict[str, "jax.sharding.PartitionSpec"]:
    """KV pools shard their leading L axis over "pp"; with in-stage tp
    the head-lane axis additionally shards over "tp" (each rank's pool
    rows carry only its own heads' lanes, like kv_pspecs)."""
    from jax.sharding import PartitionSpec as P
    if tp > 1:
        return {"k": P("pp", None, "tp"), "v": P("pp", None, "tp")}
    return {"k": P("pp"), "v": P("pp")}


def place_pp(params: dict, kv: dict, mesh, cfg) -> Tuple[dict, dict]:
    """Device-put params and KV pools under the pp(×tp) layout."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    tp = mesh.shape["tp"] if "tp" in mesh.axis_names else 1
    specs = pp_param_pspecs(cfg, tp=tp)
    params = {k: jax.device_put(v, NamedSharding(mesh,
                                                 specs.get(k, P())))
              for k, v in params.items()}
    kvs = pp_kv_pspecs(tp=tp)
    kv = {k: jax.device_put(v, NamedSharding(mesh, kvs[k]))
          for k, v in kv.items()}
    return params, kv


def make_pp_mesh(pp: int, tp: int = 1, devices=None):
    """Mesh with axes ("pp", "tp") — the stage ring crosses "pp" (the
    DCN-viable axis); in-stage collectives reduce over "tp" (ICI)."""
    import numpy as np
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    if pp * tp > len(devices):
        raise ValueError(f"pp*tp={pp * tp} > {len(devices)} devices")
    return Mesh(np.array(devices[:pp * tp]).reshape(pp, tp),
                ("pp", "tp"))
