"""Device-mesh sharding layouts for the engine.

The reference reaches TP/PP by passing flags to external engines
(SURVEY.md §2.3 parallelism inventory: --tensor-parallel-size wired into
vLLM/SGLang; multinode via Ray/torch-distributed). TPU-native, parallelism is
a compiler problem: pick a `jax.sharding.Mesh`, annotate params/KV/batch with
PartitionSpecs, and XLA inserts the collectives over ICI.

Mesh axes:
- "dp": data parallel — batch slots split across replicas
- "tp": tensor parallel — attention heads / MLP intermediate / KV heads split
- "sp": sequence parallel — ring-attention prefill for long context
- "ep": expert parallel — MoE experts split (models with num_experts > 0)
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.config import ModelConfig

logger = logging.getLogger("dynamo_tpu.parallel.sharding")

AXES = ("dp", "tp", "sp", "ep")


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * tp * sp * ep
    if need > len(devices):
        raise ValueError(f"mesh dp*tp*sp*ep={need} > {len(devices)} devices")
    arr = np.array(devices[:need]).reshape(dp, tp, sp, ep)
    return Mesh(arr, AXES)


def param_pspecs(cfg: ModelConfig) -> Dict[str, P]:
    """Megatron-style TP layout: column-parallel qkv/gate/up, row-parallel
    o/down (XLA inserts the psum on the row-parallel matmul output);
    vocab-sharded embedding + lm_head."""
    specs = {
        "embed": P("tp", None),           # vocab-sharded
        "final_norm": P(),
        "layers.ln1": P(None, None),
        "layers.ln2": P(None, None),
        "layers.wq": P(None, None, "tp"),
        "layers.wk": P(None, None, "tp"),
        "layers.wv": P(None, None, "tp"),
        "layers.wo": P(None, "tp", None),
    }
    if cfg.num_experts == 0:
        specs.update({
            "layers.gate": P(None, None, "tp"),
            "layers.up": P(None, None, "tp"),
            "layers.down": P(None, "tp", None),
        })
    # untied checkpoints ship a real lm_head; tied QUANTIZED params carry a
    # materialized pre-transposed head (engine/quant.py) with the same
    # [D, V] orientation — the spec is harmless when the key is absent
    specs["lm_head"] = P(None, "tp")
    if cfg.kv_lora_rank > 0:
        # MLA (models/mla.py): heads shard over tp on the LANE axis of
        # the head-structured projections — wq/wq_b [L, ., H*(dn+dr)],
        # wkv_b [L, rank, H*(dn+dv)] — and wo stays row-parallel. The
        # latent path (wkv_a/kv_norm, wq_a/q_a_norm) produces the
        # MQA-shaped rows EVERY head expands from: replicated, like the
        # latent pool itself (kv_pspecs "kv")
        specs.update({
            "layers.wq_a": P(), "layers.q_a_norm": P(),
            "layers.wq_b": P(None, None, "tp"),
            "layers.wkv_a": P(), "layers.kv_norm": P(),
            "layers.wkv_b": P(None, None, "tp"),
        })
        if cfg.num_experts > 0 and cfg.first_k_dense > 0:
            # deepseek hybrid: the dense-prefix stacks take the plain
            # dense-MLP tp layout
            specs.update({
                "layers.dense_gate": P(None, None, "tp"),
                "layers.dense_up": P(None, None, "tp"),
                "layers.dense_down": P(None, "tp", None),
            })
    if cfg.attention_bias:
        # biases follow their projection's column sharding
        specs.update({"layers.bq": P(None, "tp"),
                      "layers.bk": P(None, "tp"),
                      "layers.bv": P(None, "tp")})
    if cfg.num_experts > 0:
        specs.update({
            "layers.router": P(None, None, None),
            "layers.moe_gate": P(None, "ep", None, "tp"),
            "layers.moe_up": P(None, "ep", None, "tp"),
            "layers.moe_down": P(None, "ep", "tp", None),
        })
        if cfg.shared_expert_size > 0:
            # qwen2_moe shared expert: dense-MLP tp layout; the sigmoid
            # gate vector replicates. Only when the family HAS one — a
            # spec for an absent leaf breaks explicit in_shardings trees
            specs.update({
                "layers.sh_gate": P(None, None, "tp"),
                "layers.sh_up": P(None, None, "tp"),
                "layers.sh_down": P(None, "tp", None),
                "layers.sh_router": P(),
            })
    return specs


def kv_pspecs() -> Dict[str, P]:
    # KV heads split over tp — in the block-major pool [L, NTOK, KVH*Dh]
    # head vectors are contiguous lane groups, so sharding the last axis
    # keeps each head's pool wholly on one chip and paged-attention DMA
    # never crosses chips. int8 pools widen each tp shard's section with
    # its own IN-ROW scale group (llama.init_kv_cache kv_shards), so the
    # same lane-axis sharding gives every shard whole (values, scales)
    # sections.
    # llama-family pools only — MLA latent pools ({"kv"}) take the
    # replicated fallback in shard_kv; adding the key HERE would break
    # callers that pass this dict as an explicit in_shardings tree for
    # {"k","v"} pools
    return {"k": P(None, None, "tp"), "v": P(None, None, "tp")}


def batch_pspecs() -> Dict[str, P]:
    return {
        "tokens": P("dp"),
        "positions": P("dp"),
        "block_tables": P("dp", None),
        "seq_lens": P("dp"),
    }


def _spec_fits(shape, spec: P, mesh: Mesh) -> bool:
    """Every sharded dim must divide by the product of its axis sizes."""
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        axes = (axes,) if isinstance(axes, str) else axes
        size = int(np.prod([mesh.shape[a] for a in axes]))
        if dim % size != 0:
            return False
    return True


def fit_or_replicate(name: str, shape, spec: P, mesh: Mesh,
                     itemsize: int) -> P:
    """The one replication-fallback policy: return ``spec`` when it
    divides the mesh, else warn (with the per-device byte cost) and
    return the replicated spec. Used by shard_params AND the sharded
    checkpoint loader so the two can't drift."""
    if spec == P() or _spec_fits(shape, spec, mesh):
        return spec
    logger.warning(
        "param %s shape %s does not divide mesh axes for spec %s — "
        "replicating (costs %d bytes per extra device copy)",
        name, tuple(shape), spec, int(np.prod(shape)) * itemsize)
    return P()


def shard_params(params: dict, mesh: Mesh, cfg: ModelConfig) -> dict:
    """Place params under their TP layout; params whose dims don't divide
    the mesh axes (e.g. an odd vocab size) are replicated instead.

    int8-quantized leaves (engine/quant.QuantizedArray) shard their q
    tensor with the weight's spec and their scale with the same spec where
    it fits — per-output-channel scales follow column-parallel weights,
    while row-parallel weights' scales (broadcast dim 1 on the sharded
    axis) fall back to replication, which is also the correct layout."""
    from ..engine.quant import QuantizedArray

    specs = param_pspecs(cfg)

    def put(arr, spec):
        if not _spec_fits(arr.shape, spec, mesh):
            spec = P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    out = {}
    for k, v in params.items():
        spec = specs.get(k, P())
        if isinstance(v, QuantizedArray):
            # shared fallback policy for the q tensor (scale fallback
            # stays silent inside put(): for row-parallel weights
            # replication IS the scale's correct layout; a grouped scale
            # [L, D/g, F] shards alongside q on either axis since the
            # group width divides every per-shard span)
            q_spec = fit_or_replicate(k, v.q.shape, spec, mesh,
                                      v.q.dtype.itemsize)
            out[k] = QuantizedArray(put(v.q, q_spec), put(v.scale, spec),
                                    group=v.group, packed4=v.packed4,
                                    # pallas_call has no GSPMD rule:
                                    # sharded packed leaves take the XLA
                                    # grouped path after unpack_params
                                    no_kernel=(v.no_kernel
                                               or mesh.size > 1))
            continue
        spec = fit_or_replicate(k, v.shape, spec, mesh, v.dtype.itemsize)
        out[k] = put(v, spec)
    return out


def shard_kv(kv: dict, mesh: Mesh) -> dict:
    # MLA latent pools ("kv", [L, NTOK, rank+rope]) REPLICATE: the
    # latent row is the MQA-shaped read shared by every head — no head
    # structure on the lane axis to split — and each tp rank scatters
    # identical rows (wkv_a is replicated)
    specs = kv_pspecs()
    return {k: jax.device_put(v, NamedSharding(mesh, specs.get(k, P())))
            for k, v in kv.items()}


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
