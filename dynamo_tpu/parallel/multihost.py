"""Multi-host bootstrap: one engine spanning several TPU hosts.

Reference: lib/llm/src/engines.rs:33-50 ``MultiNodeConfig{num_nodes,
node_rank, leader_addr}`` — wired into Ray leader/follower for vLLM and
torch-distributed for SGLang (SURVEY.md §2.3 multi-node bootstrap). The
JAX analog is ``jax.distributed.initialize``: every host calls it with the
leader's coordinator address, after which ``jax.devices()`` spans the whole
slice and the SPMD programs (pjit over the dp/tp/sp/ep mesh) run
megascale-style across ICI/DCN with no further framework plumbing.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

logger = logging.getLogger("dynamo_tpu.parallel.multihost")

__all__ = ["MultiNodeConfig", "initialize_multihost", "is_leader"]


@dataclasses.dataclass
class MultiNodeConfig:
    """Reference MultiNodeConfig, 1:1 field mapping."""

    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: Optional[str] = None    # "host:port" of node_rank 0

    def __post_init__(self) -> None:
        if self.num_nodes > 1 and not self.leader_addr:
            raise ValueError("--leader-addr is required when num_nodes > 1")
        if not (0 <= self.node_rank < max(self.num_nodes, 1)):
            raise ValueError(
                f"node_rank {self.node_rank} out of range for "
                f"{self.num_nodes} nodes")

    @property
    def single_node(self) -> bool:
        return self.num_nodes <= 1


def is_leader(cfg: MultiNodeConfig) -> bool:
    return cfg.node_rank == 0


def initialize_multihost(cfg: MultiNodeConfig) -> None:
    """Join this process into the multi-host JAX runtime. No-op for a
    single node. Must run before any other JAX call on the process
    (jax.distributed's contract); afterwards ``jax.devices()`` is global
    and ``jax.local_devices()`` is this host's chips."""
    if cfg.single_node:
        return
    import jax
    jax.distributed.initialize(
        coordinator_address=cfg.leader_addr,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank)
    logger.info("joined multihost runtime: node %d/%d (leader %s), "
                "%d global / %d local devices",
                cfg.node_rank, cfg.num_nodes, cfg.leader_addr,
                len(jax.devices()), len(jax.local_devices()))
