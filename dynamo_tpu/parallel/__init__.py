from .sharding import (batch_pspecs, kv_pspecs, make_mesh, param_pspecs,
                       shard_kv, shard_params)

__all__ = ["make_mesh", "param_pspecs", "kv_pspecs", "batch_pspecs",
           "shard_params", "shard_kv"]
