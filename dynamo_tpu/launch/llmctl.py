"""Admin CLI: manage ModelEntry records and live disagg config in the KV
store. Reference: launch/llmctl (``llmctl http add chat-model <name>
<ns.comp.endpoint>`` → etcd ModelEntry, main.rs:81-210) plus a subcommand
for the disagg router's watched threshold (disagg_router.rs:38-140)."""

from __future__ import annotations

import argparse
import asyncio
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl")
    p.add_argument("--runtime-server", required=True,
                   help="discovery daemon host:port")
    sub = p.add_subparsers(dest="cmd", required=True)

    http = sub.add_parser("http", help="manage served models")
    hsub = http.add_subparsers(dest="http_cmd", required=True)
    add = hsub.add_parser("add")
    add.add_argument("kind", choices=["chat-model", "completion-model"])
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns/comp/ep or ns.comp.ep")
    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=["chat-model", "completion-model"])
    rm.add_argument("name")
    hsub.add_parser("list")

    dis = sub.add_parser("disagg", help="live disagg-router config")
    dsub = dis.add_subparsers(dest="disagg_cmd", required=True)
    st = dsub.add_parser("set-threshold")
    st.add_argument("model")
    st.add_argument("value", type=int)

    dep = sub.add_parser("deployment",
                         help="manage graph deployments (deploy/ control "
                              "plane — the api-server CRUD over the store)")
    dpsub = dep.add_subparsers(dest="dep_cmd", required=True)
    dc = dpsub.add_parser("create")
    dc.add_argument("name")
    dc.add_argument("graph", help="module:ServiceClass")
    dc.add_argument("--config", help="service YAML path")
    dc.add_argument("--replicas", type=int, default=1)
    dc.add_argument("--max-restarts", type=int, default=None,
                    help="crash-restart cap per replica before the "
                         "deployment is marked failed (default: "
                         "controller default)")
    ds = dpsub.add_parser("scale")
    ds.add_argument("name")
    ds.add_argument("replicas", type=int)
    dt = dpsub.add_parser("terminate")
    dt.add_argument("name")
    dd = dpsub.add_parser("delete")
    dd.add_argument("name")
    dpsub.add_parser("list")
    return p


async def amain(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..runtime.distributed import DistributedRuntime
    runtime = await DistributedRuntime.connect(args.runtime_server)
    try:
        if args.cmd == "http":
            from ..llm.discovery import (ModelEntry, list_models,
                                         register_model, remove_model)
            kind = getattr(args, "kind", "").replace("-model", "")
            if args.http_cmd == "add":
                await register_model(runtime, ModelEntry(
                    name=args.name, endpoint=args.endpoint, model_type=kind))
                print(f"added {kind} model {args.name} → {args.endpoint}")
            elif args.http_cmd == "remove":
                ok = await remove_model(runtime, kind, args.name)
                print(f"{'removed' if ok else 'not found'}: {args.name}")
                return 0 if ok else 1
            else:
                entries = await list_models(runtime)
                if not entries:
                    print("(no models)")
                for key, e in sorted(entries.items()):
                    print(f"{e.model_type:11s} {e.name:30s} {e.endpoint}")
        elif args.cmd == "disagg":
            from ..llm.disagg import disagg_config_key
            import json
            await runtime.store.kv_put(
                disagg_config_key(args.model),
                json.dumps({"max_local_prefill_length": args.value}).encode())
            print(f"disagg threshold for {args.model} → {args.value}")
        elif args.cmd == "deployment":
            return await _deployment_cmd(runtime, args)
        return 0
    finally:
        await runtime.shutdown()


async def _deployment_cmd(runtime, args) -> int:
    """Deployment CRUD straight against the store (the controller watches
    it; works whether the REST api-server is running or not). Updates go
    through the shared CAS helper — the api-server is a concurrent writer
    in another process, so plain read-modify-write would lose races."""
    import json
    import time

    from ..deploy.spec import (SPEC_PREFIX, STATUS_PREFIX, DeploymentSpec,
                               update_spec, validate_spec)

    if args.dep_cmd == "create":
        err = validate_spec(args.name, args.replicas,
                            max_restarts=args.max_restarts)
        if err:
            print(err, file=sys.stderr)
            return 1
        spec = DeploymentSpec(name=args.name, graph=args.graph,
                              config=args.config, replicas=args.replicas,
                              created_at=time.time(),
                              max_restarts=args.max_restarts)
        if not await runtime.store.kv_create(spec.key(), spec.to_json()):
            print(f"deployment {args.name!r} already exists", file=sys.stderr)
            return 1
        print(f"created deployment {args.name} ({args.graph} "
              f"x{args.replicas})")
    elif args.dep_cmd in ("scale", "terminate"):
        want = args.replicas if args.dep_cmd == "scale" else 0
        err = validate_spec(args.name, want)
        if err:
            print(err, file=sys.stderr)
            return 1

        def mutate(spec: DeploymentSpec):
            spec.replicas = want
            return None

        spec = await update_spec(runtime.store, args.name, mutate)
        if spec is None:
            print(f"not found: {args.name}", file=sys.stderr)
            return 1
        print(f"{args.dep_cmd}d {args.name} → replicas={spec.replicas}")
    elif args.dep_cmd == "delete":
        if not await runtime.store.kv_delete(SPEC_PREFIX + args.name):
            print(f"not found: {args.name}", file=sys.stderr)
            return 1
        print(f"deleted {args.name}")
    else:   # list
        specs = await runtime.store.kv_get_prefix(SPEC_PREFIX)
        statuses = {e.key[len(STATUS_PREFIX):]: json.loads(e.value)
                    for e in await runtime.store.kv_get_prefix(STATUS_PREFIX)}
        if not specs:
            print("(no deployments)")
        for e in sorted(specs, key=lambda x: x.key):
            spec = DeploymentSpec.from_json(e.value)
            status = statuses.get(spec.name, {})
            print(f"{spec.name:24s} {spec.graph:40s} "
                  f"replicas={spec.replicas} gen={spec.generation} "
                  f"state={status.get('state', '?')} "
                  f"ready={status.get('ready_replicas', '?')}")
    return 0


def main() -> None:
    sys.exit(asyncio.run(amain()))


if __name__ == "__main__":
    main()
