"""Admin CLI: manage ModelEntry records and live disagg config in the KV
store. Reference: launch/llmctl (``llmctl http add chat-model <name>
<ns.comp.endpoint>`` → etcd ModelEntry, main.rs:81-210) plus a subcommand
for the disagg router's watched threshold (disagg_router.rs:38-140)."""

from __future__ import annotations

import argparse
import asyncio
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl")
    p.add_argument("--runtime-server", required=True,
                   help="discovery daemon host:port")
    sub = p.add_subparsers(dest="cmd", required=True)

    http = sub.add_parser("http", help="manage served models")
    hsub = http.add_subparsers(dest="http_cmd", required=True)
    add = hsub.add_parser("add")
    add.add_argument("kind", choices=["chat-model", "completion-model"])
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns/comp/ep or ns.comp.ep")
    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=["chat-model", "completion-model"])
    rm.add_argument("name")
    hsub.add_parser("list")

    mdl = sub.add_parser("model", help="model registry cards "
                                       "(llm/registry.py — the multi-"
                                       "model serving plane's records)")
    msub = mdl.add_subparsers(dest="model_cmd", required=True)
    madd = msub.add_parser("add", help="register (or revise) a card; "
                                       "watching frontends start "
                                       "serving the name immediately")
    madd.add_argument("name")
    madd.add_argument("endpoint", help="dyn://ns/comp/ep or ns.comp.ep")
    madd.add_argument("--model-path", help="HF-style dir the frontend's "
                                           "preprocessor loads")
    madd.add_argument("--kv-block-size", type=int, default=16)
    madd.add_argument("--model-type", default="chat+completion",
                      choices=["chat", "completion", "chat+completion"])
    madd.add_argument("--geometry", default=None,
                      help='JSON geometry dict, e.g. \'{"tp": 8}\' — '
                           "feeds the derived program-set key")
    mrm = msub.add_parser("rm", help="remove a card; watching frontends "
                                     "drop the model (404 from then on)")
    mrm.add_argument("name")
    msub.add_parser("list")

    tn = sub.add_parser("tenant", help="multi-tenant policy admin "
                                       "(llm/tenancy.py): fair-share "
                                       "weights + per-tier KV quotas, "
                                       "applied live by watching "
                                       "workers/routers")
    tnsub = tn.add_subparsers(dest="tenant_cmd", required=True)
    tns = tnsub.add_parser("status", help="show the stored policy table")
    tns.add_argument("namespace", nargs="?")
    tnw = tnsub.add_parser("set-weight", help="fair-share weight (WDRR "
                                              "quantum scale)")
    tnw.add_argument("namespace")
    tnw.add_argument("tenant")
    tnw.add_argument("weight", type=float)
    tnq = tnsub.add_parser("set-quota", help="per-tier resident KV "
                                             "block quota (0 = "
                                             "unlimited); over-quota "
                                             "tenants' blocks evict "
                                             "first")
    tnq.add_argument("namespace")
    tnq.add_argument("tenant")
    tnq.add_argument("blocks", type=int)

    dis = sub.add_parser("disagg", help="live disagg-router config")
    dsub = dis.add_subparsers(dest="disagg_cmd", required=True)
    st = dsub.add_parser("set-threshold")
    st.add_argument("model")
    st.add_argument("value", type=int)

    pl = sub.add_parser("planner", help="dynamic planner admin "
                                        "(components/planner.py)")
    plsub = pl.add_subparsers(dest="planner_cmd", required=True)
    pst = plsub.add_parser("status", help="show planner state/decisions")
    pst.add_argument("namespace", nargs="?",
                     help="limit to one namespace (default: all)")
    pss = plsub.add_parser("set-slo", help="declare/update SLOs (merged "
                                           "into the stored record)")
    pss.add_argument("namespace")
    pss.add_argument("--ttft-p90-ms", type=float)
    pss.add_argument("--itl-p90-ms", type=float)
    pss.add_argument("--max-queue-depth", type=float)
    pss.add_argument("--slot-util-high", type=float)
    pss.add_argument("--slot-util-low", type=float)
    pss.add_argument("--kv-util-high", type=float)
    pss.add_argument("--min-decode-workers", type=int)
    pss.add_argument("--max-decode-workers", type=int)
    pss.add_argument("--max-local-prefill-length", type=int)
    pp = plsub.add_parser("pause", help="stop actuating (keep observing)")
    pp.add_argument("namespace")
    pr = plsub.add_parser("resume")
    pr.add_argument("namespace")

    sp = sub.add_parser("spec", help="speculative decoding admin "
                                     "(engine/spec/)")
    spsub = sp.add_subparsers(dest="spec_cmd", required=True)
    sps = spsub.add_parser("status", help="show stored draft budgets "
                                          "and live worker acceptance")
    sps.add_argument("namespace", nargs="?",
                     help="limit to one namespace (default: all)")
    spk = spsub.add_parser("set-k", help="set the live draft budget "
                                         "(clamped to each worker's "
                                         "compiled --spec-k maximum)")
    spk.add_argument("namespace")
    spk.add_argument("k", type=int)
    spo = spsub.add_parser("off", help="disable speculation live "
                                       "(equivalent to set-k 0)")
    spo.add_argument("namespace")

    kv = sub.add_parser("kv", help="KV tier admin (host/disk ladder; "
                                   "llm/kv/admin.py)")
    kvsub = kv.add_subparsers(dest="kv_cmd", required=True)
    kvs = kvsub.add_parser("status", help="show per-namespace host/disk "
                                          "tier occupancy and hit rates")
    kvs.add_argument("namespace", nargs="?",
                     help="limit to one namespace (default: all)")
    kvf = kvsub.add_parser("flush", help="persist host-resident KV to "
                                         "the disk tier NOW (the "
                                         "pre-restart barrier)")
    kvf.add_argument("namespace")
    kvf.add_argument("--clear", action="store_true",
                     help="drop the disk cache instead of persisting "
                          "into it")
    kvw = kvsub.add_parser(
        "set-weights",
        help="retune the router's per-tier overlap weights live "
             "(kv_router/scoring.py TIER_WEIGHTS): workers and routers "
             "watching kvtier/weights/{ns} apply the change without "
             "restart")
    kvw.add_argument("namespace")
    kvw.add_argument("--device", type=float, default=None)
    kvw.add_argument("--host", type=float, default=None)
    kvw.add_argument("--disk", type=float, default=None)
    kvw.add_argument("--remote", type=float, default=None)

    fl = sub.add_parser("faults", help="failpoint chaos drills "
                                       "(runtime/faults.py; docs/chaos.md)")
    flsub = fl.add_subparsers(dest="faults_cmd", required=True)
    fls = flsub.add_parser("set", help="arm one failpoint fleet-wide "
                                       "(merged into the stored table)")
    fls.add_argument("namespace")
    fls.add_argument("site", help="registered site, e.g. netstore.call")
    fls.add_argument("spec", help="[1-in-N,]error|delay:ms|torn|enospc")
    flc = flsub.add_parser("clear", help="disarm one site (or all with "
                                         "--all)")
    flc.add_argument("namespace")
    flc.add_argument("site", nargs="?")
    flc.add_argument("--all", action="store_true")
    flt = flsub.add_parser("status", help="show the stored failpoint "
                                          "table + the site catalog")
    flt.add_argument("namespace", nargs="?")

    tr = sub.add_parser("trace", help="fleet tracing admin "
                                      "(engine/flight_recorder.py)")
    trsub = tr.add_subparsers(dest="trace_cmd", required=True)
    trd = trsub.add_parser(
        "dump",
        help="collect every worker's engine flight-recorder ring "
             "(per-dispatch records: step kind, batch fill, device vs "
             "host-gap ms, KV tier hits, spec accept) + tracer stats")
    trd.add_argument("namespace")
    trd.add_argument("--last", type=int, default=32,
                     help="records per worker (default 32)")
    trd.add_argument("--timeout", type=float, default=5.0)
    trd.add_argument("--json", action="store_true",
                     help="print raw JSON dumps instead of a summary")

    dep = sub.add_parser("deployment",
                         help="manage graph deployments (deploy/ control "
                              "plane — the api-server CRUD over the store)")
    dpsub = dep.add_subparsers(dest="dep_cmd", required=True)
    dc = dpsub.add_parser("create")
    dc.add_argument("name")
    dc.add_argument("graph", help="module:ServiceClass")
    dc.add_argument("--config", help="service YAML path")
    dc.add_argument("--replicas", type=int, default=1)
    dc.add_argument("--max-restarts", type=int, default=None,
                    help="crash-restart cap per replica before the "
                         "deployment is marked failed (default: "
                         "controller default)")
    ds = dpsub.add_parser("scale")
    ds.add_argument("name")
    ds.add_argument("replicas", type=int)
    dt = dpsub.add_parser("terminate")
    dt.add_argument("name")
    dd = dpsub.add_parser("delete")
    dd.add_argument("name")
    dpsub.add_parser("list")
    return p


async def amain(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..runtime.distributed import DistributedRuntime
    runtime = await DistributedRuntime.connect(args.runtime_server)
    try:
        if args.cmd == "http":
            from ..llm.discovery import (ModelEntry, list_models,
                                         register_model, remove_model)
            kind = getattr(args, "kind", "").replace("-model", "")
            if args.http_cmd == "add":
                await register_model(runtime, ModelEntry(
                    name=args.name, endpoint=args.endpoint, model_type=kind))
                print(f"added {kind} model {args.name} → {args.endpoint}")
            elif args.http_cmd == "remove":
                ok = await remove_model(runtime, kind, args.name)
                print(f"{'removed' if ok else 'not found'}: {args.name}")
                return 0 if ok else 1
            else:
                entries = await list_models(runtime)
                if not entries:
                    print("(no models)")
                for key, e in sorted(entries.items()):
                    print(f"{e.model_type:11s} {e.name:30s} {e.endpoint}")
        elif args.cmd == "disagg":
            from ..llm.disagg import disagg_config_key
            import json
            await runtime.store.kv_put(
                disagg_config_key(args.model),
                json.dumps({"max_local_prefill_length": args.value}).encode())
            print(f"disagg threshold for {args.model} → {args.value}")
        elif args.cmd == "model":
            return await _model_cmd(runtime, args)
        elif args.cmd == "tenant":
            return await _tenant_cmd(runtime, args)
        elif args.cmd == "planner":
            return await _planner_cmd(runtime, args)
        elif args.cmd == "spec":
            return await _spec_cmd(runtime, args)
        elif args.cmd == "kv":
            return await _kv_cmd(runtime, args)
        elif args.cmd == "faults":
            return await _faults_cmd(runtime, args)
        elif args.cmd == "trace":
            return await _trace_cmd(runtime, args)
        elif args.cmd == "deployment":
            return await _deployment_cmd(runtime, args)
        return 0
    finally:
        await runtime.shutdown()


async def _model_cmd(runtime, args) -> int:
    """``llmctl model {add,list,rm}`` — registry cards on the kvstore
    (llm/registry.py). A frontend watching the registry starts/stops
    serving the name live; ``add`` on an existing name bumps its
    revision (frontends rebuild the pipeline)."""
    import json

    from ..llm.registry import (RegistryCard, list_cards, register_card,
                                remove_card)

    if args.model_cmd == "add":
        geometry = {}
        if args.geometry:
            try:
                geometry = json.loads(args.geometry)
            except ValueError as e:
                print(f"--geometry is not valid JSON: {e}", file=sys.stderr)
                return 1
            if not isinstance(geometry, dict):
                print("--geometry must be a JSON object", file=sys.stderr)
                return 1
        card = RegistryCard(name=args.name, endpoint=args.endpoint,
                            model_path=args.model_path,
                            model_type=args.model_type,
                            kv_block_size=args.kv_block_size,
                            geometry=geometry)
        await register_card(runtime, card)
        print(f"registered card {args.name} → {args.endpoint} "
              f"(program_set {card.program_set}, rev {card.revision})")
        return 0
    if args.model_cmd == "rm":
        ok = await remove_card(runtime, args.name)
        print(f"{'removed' if ok else 'not found'}: {args.name}")
        return 0 if ok else 1
    cards = await list_cards(runtime)
    if not cards:
        print("(no registry cards)")
    for name, c in sorted(cards.items()):
        print(f"{name:28s} {c.endpoint:32s} {c.model_type:16s} "
              f"bs={c.kv_block_size} prog={c.program_set} rev={c.revision}")
    return 0


async def _tenant_cmd(runtime, args) -> int:
    """``llmctl tenant`` — the tenant/control/{ns} policy table
    (llm/tenancy.py): every watching worker/router applies updates
    live (fair-share weights feed the WDRR admission; quotas feed the
    tiers' eviction preference)."""
    from ..llm.tenancy import TenantTable, tenant_control_key

    if args.tenant_cmd == "status":
        prefix = (tenant_control_key(args.namespace)
                  if args.namespace else "tenant/control/")
        entries = await runtime.store.kv_get_prefix(prefix)
        if not entries:
            print("(no tenant policies stored)")
            return 1
        for e in sorted(entries, key=lambda x: x.key):
            ns = e.key.rsplit("/", 1)[-1]
            try:
                table = TenantTable.from_json(e.value)
            except ValueError:
                print(f"namespace {ns}  (malformed table)")
                continue
            print(f"namespace {ns}")
            for t, pol in sorted(table.policies.items()):
                quota = (pol.kv_quota_blocks
                         if pol.kv_quota_blocks else "unlimited")
                print(f"  {t:20s} weight={pol.weight:g} "
                      f"kv_quota={quota} qos={pol.qos}")
        return 0
    key = tenant_control_key(args.namespace)
    entry = await runtime.store.kv_get(key)
    table = TenantTable()
    if entry is not None:
        try:
            table = TenantTable.from_json(entry.value)
        except ValueError:
            pass
    if args.tenant_cmd == "set-weight":
        if args.weight <= 0:
            print("weight must be > 0", file=sys.stderr)
            return 1
        pol = table.set(args.tenant, weight=args.weight)
    else:   # set-quota
        if args.blocks < 0:
            print("quota must be >= 0 (0 = unlimited)", file=sys.stderr)
            return 1
        pol = table.set(args.tenant, kv_quota_blocks=args.blocks)
    await runtime.store.kv_put(key, table.to_json())
    print(f"tenant {args.tenant} in {args.namespace}: "
          f"weight={pol.weight:g} kv_quota={pol.kv_quota_blocks} "
          f"qos={pol.qos}")
    return 0


async def _planner_cmd(runtime, args) -> int:
    """Planner admin over the planner/* KV keys (llm/slo.py layout): the
    planner watches slo/control live; status is its published snapshot."""
    import dataclasses
    import json

    from ..llm.slo import (PLANNER_PREFIX, ServiceLevelObjective,
                           control_key, slo_key)

    if args.planner_cmd == "status":
        prefix = (f"{PLANNER_PREFIX}status/{args.namespace}"
                  if args.namespace else f"{PLANNER_PREFIX}status/")
        entries = await runtime.store.kv_get_prefix(prefix)
        if not entries:
            print("(no planner status published)")
            return 1
        for e in entries:
            s = json.loads(e.value)
            ns = e.key.rsplit("/", 1)[-1]
            print(f"namespace {ns}  endpoint={s.get('endpoint')}  "
                  f"paused={s.get('paused')}")
            sig = s.get("signals") or {}
            workers = s.get("workers") or {}
            print(f"  workers: {len(workers.get('live', []))} live, "
                  f"draining={workers.get('draining', [])}")
            print(f"  signals: queue={sig.get('queue_depth', 0):.2f} "
                  f"slot_util={sig.get('slot_util', 0):.2f} "
                  f"kv_util={sig.get('kv_util', 0):.2f} "
                  f"ttft_p90={sig.get('ttft_p90_ms')}ms")
            print(f"  disagg_threshold: {s.get('disagg_threshold')}")
            print(f"  last decision: {s.get('last_decision')}")
            print(f"  counters: {s.get('counters')}")
            print(f"  slo: {s.get('slo')}")
        return 0
    if args.planner_cmd == "set-slo":
        entry = await runtime.store.kv_get(slo_key(args.namespace))
        slo = (ServiceLevelObjective.from_json(entry.value)
               if entry is not None else ServiceLevelObjective())
        for field in dataclasses.fields(ServiceLevelObjective):
            v = getattr(args, field.name, None)
            if v is not None:
                setattr(slo, field.name, v)
        await runtime.store.kv_put(slo_key(args.namespace), slo.to_json())
        print(f"slo for {args.namespace}: {dataclasses.asdict(slo)}")
        return 0
    # pause / resume
    paused = args.planner_cmd == "pause"
    await runtime.store.kv_put(
        control_key(args.namespace),
        json.dumps({"paused": paused}).encode())
    print(f"planner {args.planner_cmd}d for {args.namespace}")
    return 0


async def _spec_cmd(runtime, args) -> int:
    """Speculative-decoding admin over the spec/config/* KV keys
    (engine/spec/admin.py): workers watch their namespace's key
    (launch/run.py _wire_spec_config) and retune spec_k_live without a
    restart — mirroring the planner admin surface."""
    from ..engine.spec import SPEC_PREFIX, SpecConfig, spec_config_key

    if args.spec_cmd == "status":
        prefix = (spec_config_key(args.namespace)
                  if args.namespace else f"{SPEC_PREFIX}config/")
        entries = await runtime.store.kv_get_prefix(prefix)
        if not entries:
            print("(no spec config stored)")
            return 1
        for e in sorted(entries, key=lambda x: x.key):
            ns = e.key.rsplit("/", 1)[-1]
            try:
                cfg = SpecConfig.from_json(e.value)
            except ValueError:
                print(f"namespace {ns}  (malformed config)")
                continue
            state = "off" if cfg.k == 0 else f"k={cfg.k}"
            print(f"namespace {ns}  speculation {state}")
        return 0
    k = args.k if args.spec_cmd == "set-k" else 0
    if k < 0:
        print("k must be >= 0", file=sys.stderr)
        return 1
    await runtime.store.kv_put(spec_config_key(args.namespace),
                               SpecConfig(k=k).to_json())
    print(f"speculation for {args.namespace} → "
          f"{'off' if k == 0 else f'k={k}'}")
    return 0


async def _kv_cmd(runtime, args) -> int:
    """KV tier admin over the kvtier/* keys (llm/kv/admin.py): workers
    publish status snapshots and watch the control key; flush makes them
    persist host-resident blocks into the disk (G3) tier — the barrier
    to run before a planned restart so the warm start is complete."""
    import json
    import time

    from ..llm.kv.admin import (KV_PREFIX, KvTierStatus, kv_control_key,
                                kv_status_key, kv_weights_key)

    if args.kv_cmd == "set-weights":
        weights = {t: getattr(args, t) for t in ("device", "host", "disk",
                                                 "remote")
                   if getattr(args, t) is not None}
        if not weights:
            print("nothing to set (pass --device/--host/--disk/--remote)")
            return 1
        await runtime.store.kv_put(kv_weights_key(args.namespace),
                                   json.dumps(weights).encode())
        print(f"kv tier weights for {args.namespace} → {weights}")
        return 0
    if args.kv_cmd == "status":
        prefix = (kv_status_key(args.namespace)
                  if args.namespace else f"{KV_PREFIX}status/")
        entries = await runtime.store.kv_get_prefix(prefix)
        if not entries:
            print("(no kv tier status published)")
            return 1
        for e in sorted(entries, key=lambda x: x.key):
            try:
                s = KvTierStatus.from_json(e.value)
            except (ValueError, KeyError):
                print(f"{e.key}  (malformed status)")
                continue
            print(f"namespace {s.namespace}")
            print(f"  host:  {s.host_blocks}/{s.host_capacity} blocks  "
                  f"hit_rate={s.host_hit_rate:.3f}  "
                  f"offload_dropped={s.offload_dropped}")
            if s.disk_capacity:
                print(f"  disk:  {s.disk_blocks}/{s.disk_capacity} blocks "
                      f"({s.disk_bytes / 1e6:.1f} MB)  "
                      f"hit_rate={s.disk_hit_rate:.3f}  "
                      f"spill_dropped={s.spill_dropped}  "
                      f"onboards={s.disk_onboards}  dir={s.disk_dir}")
            else:
                print("  disk:  (tier off)")
            if s.remote_capacity or s.remote_blocks or s.remote_peer_blocks:
                print(f"  remote: {s.remote_blocks} object blocks"
                      f"{f'/{s.remote_capacity}' if s.remote_capacity else ''}"
                      f"  peers hold {s.remote_peer_blocks}  "
                      f"hit_rate={s.remote_hit_rate:.3f}  "
                      f"onboards={s.remote_onboards}  "
                      f"fetch_failures={s.remote_fetch_failures}  "
                      f"link={s.remote_link_gbps:.2f}GB/s "
                      f"rtt={s.remote_link_rtt_s * 1e3:.1f}ms")
        return 0
    # flush [--clear]
    await runtime.store.kv_put(
        kv_control_key(args.namespace),
        json.dumps({"flush": time.time(),
                    "clear": bool(args.clear)}).encode())
    print(f"kv {'clear' if args.clear else 'flush'} requested for "
          f"{args.namespace}")
    return 0


async def _faults_cmd(runtime, args) -> int:
    """``llmctl faults`` — arm/disarm deterministic failpoints
    fleet-wide over the faults/control/{ns} key (runtime/faults.py;
    every worker's watch_faults_loop applies the stored table live).
    Specs are validated HERE so a typo'd drill fails at the CLI, not
    silently fault-free on the fleet."""
    import json

    from ..runtime.faults import SITES, faults_control_key, parse_spec

    if args.faults_cmd == "status":
        prefix = (faults_control_key(args.namespace)
                  if args.namespace else "faults/control/")
        entries = await runtime.store.kv_get_prefix(prefix)
        if not entries:
            print("(no failpoints armed)")
        for e in sorted(entries, key=lambda x: x.key):
            ns = e.key.rsplit("/", 1)[-1]
            try:
                table = json.loads(e.value)
            except ValueError:
                print(f"namespace {ns}  (malformed table)")
                continue
            print(f"namespace {ns}")
            for site, spec in sorted(table.items()):
                print(f"  {site:26s} {spec}")
        print("\nregistered sites:")
        for site, desc in sorted(SITES.items()):
            print(f"  {site:26s} {desc}")
        return 0

    key = faults_control_key(args.namespace)
    entry = await runtime.store.kv_get(key)
    table = {}
    if entry is not None:
        try:
            table = json.loads(entry.value)
        except ValueError:
            table = {}
    if args.faults_cmd == "set":
        if args.site not in SITES:
            print(f"unknown site {args.site!r} (llmctl faults status "
                  f"lists the catalog)", file=sys.stderr)
            return 1
        try:
            parse_spec(args.site, args.spec)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        table[args.site] = args.spec
        await runtime.store.kv_put(key, json.dumps(table).encode())
        print(f"armed {args.site}={args.spec} for {args.namespace}")
        return 0
    # clear
    if args.all:
        table = {}
    elif args.site:
        table.pop(args.site, None)
    else:
        print("pass a site or --all", file=sys.stderr)
        return 1
    await runtime.store.kv_put(key, json.dumps(table).encode())
    print(f"faults table for {args.namespace}: {table or '(clear)'}")
    return 0


async def _trace_cmd(runtime, args) -> int:
    """``llmctl trace dump``: write the trace/control/{ns} key; every
    worker watching it (launch/run.py _wire_tracing) publishes its
    flight-recorder ring under trace/dump/{ns}/{worker:x} within its
    lease; collect and print (engine/flight_recorder.py key layout)."""
    import asyncio as _asyncio
    import json
    import time

    from ..engine.flight_recorder import trace_control_key, trace_dump_key

    requested_at = time.time()
    await runtime.store.kv_put(
        trace_control_key(args.namespace),
        json.dumps({"dump": requested_at, "last": args.last}).encode())
    prefix = trace_dump_key(args.namespace, 0).rsplit("/", 1)[0] + "/"
    deadline = time.monotonic() + args.timeout
    dumps = {}
    while time.monotonic() < deadline:
        for e in await runtime.store.kv_get_prefix(prefix):
            try:
                d = json.loads(e.value)
            except ValueError:
                continue
            if d.get("at", 0) >= requested_at:
                dumps[e.key] = d
        if dumps:
            # one settle pass so stragglers land, then report
            await _asyncio.sleep(0.3)
            for e in await runtime.store.kv_get_prefix(prefix):
                try:
                    d = json.loads(e.value)
                except ValueError:
                    continue
                if d.get("at", 0) >= requested_at:
                    dumps[e.key] = d
            break
        await _asyncio.sleep(0.1)
    if not dumps:
        print(f"(no worker answered the trace dump in {args.timeout:g}s "
              f"— is anything serving namespace {args.namespace!r}?)")
        return 1
    if args.json:
        print(json.dumps(list(dumps.values()), indent=2))
        return 0
    for key in sorted(dumps):
        d = dumps[key]
        fl = d.get("flight") or {}
        tr = d.get("tracer") or {}
        print(f"worker {d.get('worker_id')}  records={fl.get('ring', 0)}"
              f"/{fl.get('records_total', 0)}  "
              f"loop_lag={fl.get('loop_lag_ms', 0):.1f}ms "
              f"(max {fl.get('loop_lag_max_ms', 0):.1f}ms)  "
              f"traces={tr.get('completed', 0)} "
              f"log_dropped={tr.get('dropped_log_lines', 0)}")
        for r in d.get("records", []):
            extra = {k: v for k, v in r.items() if k not in ("kind", "t")}
            print(f"  {r['kind']:8s} {extra}")
    return 0


async def _deployment_cmd(runtime, args) -> int:
    """Deployment CRUD straight against the store (the controller watches
    it; works whether the REST api-server is running or not). Updates go
    through the shared CAS helper — the api-server is a concurrent writer
    in another process, so plain read-modify-write would lose races."""
    import json
    import time

    from ..deploy.spec import (SPEC_PREFIX, STATUS_PREFIX, DeploymentSpec,
                               update_spec, validate_spec)

    if args.dep_cmd == "create":
        err = validate_spec(args.name, args.replicas,
                            max_restarts=args.max_restarts)
        if err:
            print(err, file=sys.stderr)
            return 1
        spec = DeploymentSpec(name=args.name, graph=args.graph,
                              config=args.config, replicas=args.replicas,
                              created_at=time.time(),
                              max_restarts=args.max_restarts)
        if not await runtime.store.kv_create(spec.key(), spec.to_json()):
            print(f"deployment {args.name!r} already exists", file=sys.stderr)
            return 1
        print(f"created deployment {args.name} ({args.graph} "
              f"x{args.replicas})")
    elif args.dep_cmd in ("scale", "terminate"):
        want = args.replicas if args.dep_cmd == "scale" else 0
        err = validate_spec(args.name, want)
        if err:
            print(err, file=sys.stderr)
            return 1

        def mutate(spec: DeploymentSpec):
            spec.replicas = want
            return None

        spec = await update_spec(runtime.store, args.name, mutate)
        if spec is None:
            print(f"not found: {args.name}", file=sys.stderr)
            return 1
        print(f"{args.dep_cmd}d {args.name} → replicas={spec.replicas}")
    elif args.dep_cmd == "delete":
        if not await runtime.store.kv_delete(SPEC_PREFIX + args.name):
            print(f"not found: {args.name}", file=sys.stderr)
            return 1
        print(f"deleted {args.name}")
    else:   # list
        specs = await runtime.store.kv_get_prefix(SPEC_PREFIX)
        statuses = {e.key[len(STATUS_PREFIX):]: json.loads(e.value)
                    for e in await runtime.store.kv_get_prefix(STATUS_PREFIX)}
        if not specs:
            print("(no deployments)")
        for e in sorted(specs, key=lambda x: x.key):
            spec = DeploymentSpec.from_json(e.value)
            status = statuses.get(spec.name, {})
            print(f"{spec.name:24s} {spec.graph:40s} "
                  f"replicas={spec.replicas} gen={spec.generation} "
                  f"state={status.get('state', '?')} "
                  f"ready={status.get('ready_replicas', '?')}")
    return 0


def main() -> None:
    sys.exit(asyncio.run(amain()))


if __name__ == "__main__":
    main()
