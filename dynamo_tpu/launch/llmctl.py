"""Admin CLI: manage ModelEntry records and live disagg config in the KV
store. Reference: launch/llmctl (``llmctl http add chat-model <name>
<ns.comp.endpoint>`` → etcd ModelEntry, main.rs:81-210) plus a subcommand
for the disagg router's watched threshold (disagg_router.rs:38-140)."""

from __future__ import annotations

import argparse
import asyncio
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl")
    p.add_argument("--runtime-server", required=True,
                   help="discovery daemon host:port")
    sub = p.add_subparsers(dest="cmd", required=True)

    http = sub.add_parser("http", help="manage served models")
    hsub = http.add_subparsers(dest="http_cmd", required=True)
    add = hsub.add_parser("add")
    add.add_argument("kind", choices=["chat-model", "completion-model"])
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns/comp/ep or ns.comp.ep")
    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=["chat-model", "completion-model"])
    rm.add_argument("name")
    hsub.add_parser("list")

    dis = sub.add_parser("disagg", help="live disagg-router config")
    dsub = dis.add_subparsers(dest="disagg_cmd", required=True)
    st = dsub.add_parser("set-threshold")
    st.add_argument("model")
    st.add_argument("value", type=int)
    return p


async def amain(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..runtime.distributed import DistributedRuntime
    runtime = await DistributedRuntime.connect(args.runtime_server)
    try:
        if args.cmd == "http":
            from ..llm.discovery import (ModelEntry, list_models,
                                         register_model, remove_model)
            kind = getattr(args, "kind", "").replace("-model", "")
            if args.http_cmd == "add":
                await register_model(runtime, ModelEntry(
                    name=args.name, endpoint=args.endpoint, model_type=kind))
                print(f"added {kind} model {args.name} → {args.endpoint}")
            elif args.http_cmd == "remove":
                ok = await remove_model(runtime, kind, args.name)
                print(f"{'removed' if ok else 'not found'}: {args.name}")
                return 0 if ok else 1
            else:
                entries = await list_models(runtime)
                if not entries:
                    print("(no models)")
                for key, e in sorted(entries.items()):
                    print(f"{e.model_type:11s} {e.name:30s} {e.endpoint}")
        elif args.cmd == "disagg":
            from ..llm.disagg import disagg_config_key
            import json
            await runtime.store.kv_put(
                disagg_config_key(args.model),
                json.dumps({"max_local_prefill_length": args.value}).encode())
            print(f"disagg threshold for {args.model} → {args.value}")
        return 0
    finally:
        await runtime.shutdown()


def main() -> None:
    sys.exit(asyncio.run(amain()))


if __name__ == "__main__":
    main()
