"""Launch layer: the single-binary CLI (`run`) and the admin CLI (`llmctl`).

Reference: launch/dynamo-run (in=/out= matrix, SURVEY.md §2.4) and
launch/llmctl (etcd ModelEntry admin)."""
