"""The single-binary launcher: ``python -m dynamo_tpu.launch.run in=<src>
out=<engine> [flags]``.

Reference: launch/dynamo-run (src/opt.rs:23-130 input/output matrix,
src/flags.rs:22-158 flag set, src/input/common.rs:35-92 pipeline link,
src/input/endpoint.rs:34-115 worker registration).

Inputs:  http | text | stdin | batch:FILE.jsonl | dyn://ns/comp/ep | none
Outputs: jax | echo_core | echo_full | dyn://ns/comp/ep

The canonical local pipeline for core engines (jax/echo_core) is
preprocessor → backend(detokenizer) → engine, exactly the reference's
6-stage link (SURVEY.md §3.1). ``out=dyn://`` makes this process a frontend
routing to remote workers; ``in=dyn://`` makes it a worker serving its
pipeline on the distributed runtime. Disaggregation: ``--remote-prefill``
turns the worker into a disagg decode worker; ``--is-prefill-worker`` (with
``in=none``) runs the prefill side pulling the shared queue."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
from typing import Tuple

logger = logging.getLogger("dynamo_tpu.launch")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dynamo-tpu-run",
        description="TPU-native LLM serving launcher (in=SRC out=ENGINE)")
    p.add_argument("io", nargs="*", metavar="in=|out=",
                   help="in=http|text|stdin|batch:F|dyn://ns/c/e|none "
                        "out=jax|echo_core|echo_full|pystr:F|pytok:F|"
                        "dyn://ns/c/e")
    p.add_argument("--model-path", help="HF-style model dir (config.json, "
                                        "tokenizer.json, safetensors)")
    p.add_argument("--model-name", help="served model name "
                                        "(default: basename of model path)")
    p.add_argument("--http-port", type=int, default=8080)
    p.add_argument("--http-host", default="0.0.0.0")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--runtime-server",
                   help="discovery daemon host:port (default: in-process "
                        "runtime — single-process deployments)")
    p.add_argument("--advertise-host",
                   help="address other hosts can dial back (DCN)")
    # engine knobs (flags.rs analogs)
    p.add_argument("--max-model-len", type=int, default=4096)
    p.add_argument("--kv-block-size", type=int, default=0,
                   help="paged-KV block size; 0 (default) auto-selects "
                        "from the model geometry at bring-up "
                        "(EngineConfig.auto_kv_block_size: 64 for "
                        "small-C KVH*Dh<=128 geometries, 32 for int8 "
                        "KV pools, else 16)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="split prompt prefill into fixed-size chunk "
                        "dispatches (0 = whole-prompt)")
    p.add_argument("--decode-steps-per-dispatch", type=int, default=1,
                   help="fuse K decode steps per XLA dispatch (amortizes "
                        "device→host token-harvest latency; EOS/cancel "
                        "react at K-step granularity)")
    p.add_argument("--lane-prefill-max-tokens", type=int, default=0,
                   help="admissions with <= this many un-cached prompt "
                        "tokens ride the decode batch as planned inputs "
                        "when the engine is busy (continuous batching; "
                        "0 disables, needs K>1)")
    p.add_argument("--ragged", action="store_true",
                   help="unified ragged dispatch (engine/ragged.py): "
                        "ONE compiled program serves mixed prefill+"
                        "decode batches — admissions ride the batch as "
                        "prefill lanes, continuous batching becomes "
                        "the only serving code path "
                        "(docs/ragged_attention.md)")
    p.add_argument("--ragged-max-tokens", type=int, default=0,
                   help="token capacity of one ragged dispatch (0 = "
                        "auto: max_num_seqs + 2*ragged-max-seq-rows)")
    p.add_argument("--ragged-max-seq-rows", type=int, default=64,
                   help="per-sequence row budget per ragged dispatch "
                        "(longer prompts stream across dispatches)")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding: max prompt-lookup draft "
                        "tokens verified per step (engine/spec/; 0 "
                        "disables; per-request override via "
                        "nvext.speculation, live retune via llmctl "
                        "spec set-k)")
    p.add_argument("--decode-dispatch-pipeline", action="store_true",
                   help="overlap each dispatch's token harvest with the "
                        "next dispatch (requires K>1; finish reaction "
                        "widens to <=2K-1 steps)")
    p.add_argument("--num-kv-blocks", type=int, default=2048)
    p.add_argument("--max-num-seqs", type=int, default=8)
    p.add_argument("--host-kv-blocks", type=int, default=0,
                   help="host (TPU-VM DRAM) KV offload tier size")
    p.add_argument("--kv-disk-dir", default="",
                   help="persistent disk (G3) KV tier directory "
                        "(llm/kv/diskstore.py): host-tier evictions "
                        "spill here and a restarted engine pointed at "
                        "the same dir warm-starts from the previous "
                        "run's cache; needs --kv-disk-blocks and "
                        "--host-kv-blocks")
    p.add_argument("--kv-disk-blocks", type=int, default=0,
                   help="disk KV tier capacity in blocks (0 = off)")
    p.add_argument("--kv-remote-dir", default="",
                   help="remote (G4) object-store root (llm/kv/"
                        "remotestore.py — a mounted bucket/NFS export "
                        "shared across the fleet): disk-tier evictions "
                        "promote here write-behind and any worker "
                        "pointed at the same root reuses them; needs "
                        "the disk tier")
    p.add_argument("--kv-remote-blocks", type=int, default=0,
                   help="object tier capacity in blocks (0 = unbounded)")
    p.add_argument("--tenancy", action="store_true",
                   help="multi-tenant serving plane (llm/tenancy.py): "
                        "per-tenant KV block accounting + quota-"
                        "preferred eviction across the device/host/"
                        "disk/remote tiers, per-tenant nv_llm_tenant_* "
                        "stats, and the tenant/control/{ns} policy "
                        "watch (llmctl tenant {set-weight,set-quota})")
    p.add_argument("--kv-fabric", action="store_true",
                   help="join the fleet KV fabric (llm/kv/fabric.py): "
                        "serve this worker's disk/host KV to peers over "
                        "a kv_fabric endpoint and fetch peers' prefixes "
                        "instead of recomputing them, behind a "
                        "latency-aware admission gate")
    p.add_argument("--kv-remote-admission",
                   choices=["auto", "always", "never"], default="auto",
                   help="remote-hit admission: auto = promote only when "
                        "the modeled fetch beats the modeled recompute")
    p.add_argument("--no-prefix-reuse", action="store_true")
    p.add_argument("--kv-quantization",
                   choices=["none", "int8"], default="none",
                   help="KV-cache quantization (int8: per-token in-row "
                        "scales, 1.6-1.8x KV-byte cut, needs "
                        "--kv-block-size %% 32 == 0; the long-context "
                        "capacity lever)")
    p.add_argument("--quantization",
                   choices=["none", "int8", "int8-noembed",
                            "int4", "int4-noembed"],
                   default="none",
                   help="weight-only quantization (int8: per-channel "
                        "scales; int4: per-group-of-128 scales on dense "
                        "matmuls + lm_head, int8 embed; dequant fused "
                        "into matmuls; -noembed keeps the embedding "
                        "full-precision)")
    p.add_argument("--random-weights", action="store_true",
                   help="skip checkpoint load (benchmarks/smoke)")
    # parallelism (tensor-parallel-size analog + our axes)
    p.add_argument("--tensor-parallel-size", "--tp", type=int, default=1,
                   dest="tp")
    p.add_argument("--sequence-parallel-size", "--sp", type=int, default=1,
                   dest="sp")
    p.add_argument("--data-parallel-size", "--dp", type=int, default=1,
                   dest="dp")
    p.add_argument("--expert-parallel-size", "--ep", type=int, default=1,
                   dest="ep")
    p.add_argument("--pipeline-parallel-size", "--pp", type=int, default=1,
                   dest="pp",
                   help="pipeline-parallel stages (token-interleaved "
                        "stage ring, parallel/pipeline_parallel.py): "
                        "layer stacks + KV pool shard over pp; the "
                        "decode batch round-robins pp microbatches so "
                        "every stage computes each tick. The DCN-viable "
                        "cross-host axis. Composes with --tp only; "
                        "needs --decode-steps-per-dispatch > 1 and "
                        "--max-num-seqs divisible by pp")
    # multi-node bootstrap (reference MultiNodeConfig, engines.rs:33-50):
    # every host runs the same command with its own --node-rank; rank 0's
    # address is the coordinator
    p.add_argument("--num-nodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--leader-addr",
                   help="host:port of node 0 (jax.distributed coordinator)")
    p.add_argument("--dispatch-stream-port", type=int, default=5557,
                   help="leader port for the multihost dispatch stream "
                        "(engine/multihost.py; followers dial the "
                        "--leader-addr host at this port)")
    # routing / disagg
    p.add_argument("--router-mode", choices=["random", "round_robin"],
                   default="random")
    p.add_argument("--protocol", choices=["openai", "tokens"],
                   default="openai",
                   help="worker wire protocol for in=dyn://: openai = full "
                        "pipeline on the worker; tokens = core engine only "
                        "(preprocessing lives in a KV-routing processor)")
    p.add_argument("--remote-prefill", action="store_true",
                   help="decode worker: offload long prefills to the "
                        "prefill queue")
    p.add_argument("--is-prefill-worker", action="store_true",
                   help="serve the prefill side of disaggregation")
    p.add_argument("--role", choices=["serve", "prefill-publish"],
                   default="serve",
                   help="prefill-publish: prefill-as-a-service worker "
                        "(components/prefill_service.py) — pull the "
                        "prefill_publish queue + answer publish RPCs, "
                        "run prefill, publish prefix KV to the shared "
                        "object tier (--kv-remote-dir) for decode "
                        "fleets anywhere to admit via their measured "
                        "fetch-vs-recompute crossover")
    p.add_argument("--max-local-prefill-length", type=int, default=512)
    p.add_argument("--unconditional-disagg", action="store_true",
                   help="always prefill remotely (skip the threshold)")
    # batch mode
    p.add_argument("--trace-log-every", type=int, default=None,
                   help="log 1 of every N completed request traces "
                        "(slow/errored always log; skipped lines feed "
                        "nv_llm_trace_dropped_log_lines_total). Default: "
                        "env DYN_TRACE_LOG_EVERY or 1 (log all)")
    p.add_argument("--trace-log-slow-ms", type=float, default=None,
                   help="always log traces slower than this many ms, "
                        "regardless of sampling")
    p.add_argument("--output-path", help="batch: output JSONL path")
    p.add_argument("--max-tokens", type=int, default=256,
                   help="text/stdin/batch: generation budget")
    p.add_argument("--verbose", "-v", action="store_true")
    return p


def parse_io(io_args) -> Tuple[str, str]:
    src, out = "text", "echo_core"
    for a in io_args:
        if a.startswith("in="):
            src = a[3:]
        elif a.startswith("out="):
            out = a[4:]
        else:
            raise SystemExit(f"unrecognized positional arg {a!r} "
                             "(expected in=... / out=...)")
    return src, out


async def make_runtime(args):
    from ..runtime.distributed import DistributedRuntime
    if args.runtime_server:
        return await DistributedRuntime.connect(args.runtime_server,
                                                advertise=args.advertise_host)
    return DistributedRuntime.in_process()


def engine_config(args):
    from ..engine.config import EngineConfig
    return EngineConfig(
        max_model_len=args.max_model_len,
        kv_block_size=args.kv_block_size,
        num_kv_blocks=args.num_kv_blocks,
        max_num_seqs=args.max_num_seqs,
        enable_prefix_reuse=not args.no_prefix_reuse,
        host_kv_blocks=args.host_kv_blocks,
        kv_disk_dir=args.kv_disk_dir,
        kv_disk_blocks=args.kv_disk_blocks,
        kv_remote_dir=args.kv_remote_dir,
        kv_remote_blocks=args.kv_remote_blocks,
        kv_remote_admission=args.kv_remote_admission,
        prefill_chunk=args.prefill_chunk,
        decode_steps_per_dispatch=args.decode_steps_per_dispatch,
        decode_dispatch_pipeline=args.decode_dispatch_pipeline,
        lane_prefill_max_tokens=args.lane_prefill_max_tokens,
        ragged_dispatch=args.ragged,
        ragged_max_tokens=args.ragged_max_tokens,
        ragged_max_seq_rows=args.ragged_max_seq_rows,
        spec_k=args.spec_k,
        quantization=args.quantization,
        kv_quantization=args.kv_quantization,
        tp=args.tp, sp=args.sp, dp=args.dp, ep=args.ep, pp=args.pp)


def _model_name(args) -> str:
    if args.model_name:
        return args.model_name
    if args.model_path:
        return os.path.basename(os.path.normpath(args.model_path))
    return "echo"


async def build_engine(args, out: str, runtime):
    """→ (engine, mdc|None, core|None). Core engines get the preproc/backend
    link added by the caller; full engines speak OpenAI directly."""
    from ..llm.model_card import ModelDeploymentCard

    if out == "echo_full":
        from ..llm.engines.echo import EchoEngineFull
        return EchoEngineFull(), None, None
    if out == "echo_core":
        from ..llm.engines.echo import EchoEngineCore
        if not args.model_path:
            raise SystemExit("out=echo_core needs --model-path (tokenizer)")
        mdc = await asyncio.to_thread(
            ModelDeploymentCard.from_local_path,
            args.model_path, display_name=_model_name(args))
        return EchoEngineCore(), mdc, None
    if out.startswith("pystr:") or out.startswith("pytok:"):
        # user python-file engines (reference engines/python.rs:57-354)
        from ..llm.engines.python_file import (PythonFileEngineCore,
                                               PythonFileEngineFull)
        kind, _, path = out.partition(":")
        engine_args = {"model_path": args.model_path,
                       "model_name": _model_name(args)}
        if kind == "pystr":
            return PythonFileEngineFull(path, engine_args), None, None
        if not args.model_path:
            raise SystemExit("out=pytok needs --model-path (tokenizer)")
        mdc = await asyncio.to_thread(
            ModelDeploymentCard.from_local_path,
            args.model_path, display_name=_model_name(args))
        return PythonFileEngineCore(path, engine_args), mdc, None
    if out.startswith("dyn://") or out.count(".") == 2:
        from ..llm.engines.remote import RemoteEngine
        from ..runtime.distributed import Endpoint
        endpoint = Endpoint.parse_path(runtime, out)
        engine = await RemoteEngine.start(endpoint,
                                          router_mode=args.router_mode)
        return engine, None, None
    if out == "jax":
        from ..llm.engines.jax_engine import JaxEngine
        if not args.model_path:
            raise SystemExit("out=jax needs --model-path")
        mdc = await asyncio.to_thread(
            ModelDeploymentCard.from_local_path,
            args.model_path, display_name=_model_name(args))
        core = build_jax_core(args)
        engine = JaxEngine(core)
        if args.remote_prefill:
            from ..llm.disagg import DisaggEngine, DisaggregatedRouter
            router = DisaggregatedRouter(
                runtime, _model_name(args),
                max_local_prefill_length=args.max_local_prefill_length,
                conditional=not args.unconditional_disagg)
            await router.start()
            engine = DisaggEngine(core, runtime, router)
        return engine, mdc, core
    raise SystemExit(f"unknown out= engine {out!r}")


def build_jax_core(args):
    """Construct the (possibly sharded) EngineCore from CLI flags. Every
    rank of a multi-host engine calls this with identical flags, which is
    what makes the leader's and followers' device state bit-identical."""
    from ..engine.config import ModelConfig
    from ..engine.core import EngineCore
    if not args.model_path:
        raise SystemExit("out=jax needs --model-path")
    try:
        ecfg = engine_config(args)   # validates pp/K/batch combos early
    except (ValueError, NotImplementedError) as e:
        raise SystemExit(str(e))
    mesh = None
    if args.pp > 1:
        # pp(×tp) mesh: the stage ring crosses "pp" (the DCN-viable
        # axis — on a real multi-host deployment these are the ranks
        # that straddle hosts), in-stage collectives reduce over "tp"
        from ..parallel.pipeline_parallel import make_pp_mesh
        mesh = make_pp_mesh(args.pp, tp=args.tp)
    elif args.tp * args.sp * args.dp * args.ep > 1:
        from ..parallel.sharding import make_mesh
        mesh = make_mesh(dp=args.dp, tp=args.tp, sp=args.sp, ep=args.ep)
    model_cfg = ModelConfig.from_model_dir(args.model_path)
    params = None
    if not args.random_weights:
        from ..engine.weights import load_params_auto
        params = load_params_auto(args.model_path, model_cfg, mesh=mesh)
    return EngineCore(model_cfg, ecfg, params=params, mesh=mesh)


async def run_follower_rank(args, out: str) -> None:
    """Follower rank of one multi-host engine: build the identical core,
    dial the leader's dispatch stream, live-replay until leader shutdown
    (engine/multihost.py; reference: sglang per-rank worker split,
    lib/llm/src/engines/sglang/worker.rs:304-336)."""
    if out != "jax":
        raise SystemExit("multi-host serving requires out=jax")
    from ..engine.multihost import connect_follower, run_follower
    core = build_jax_core(args)
    host = args.leader_addr.rsplit(":", 1)[0]
    sock = await asyncio.to_thread(
        connect_follower, f"{host}:{args.dispatch_stream_port}")
    logger.info("follower rank %d/%d replaying the leader dispatch stream",
                args.node_rank, args.num_nodes)
    stats = await asyncio.to_thread(run_follower, core, sock)
    logger.info("follower rank %d done: %s", args.node_rank, stats)


def link_pipeline(engine, mdc):
    """Core engines ride the canonical 6-stage link; full engines are the
    pipeline (input/common.rs:35-92)."""
    if mdc is None:
        return engine
    from ..llm.backend import Backend
    from ..llm.preprocessor import OpenAIPreprocessor
    from ..runtime import link
    return link(OpenAIPreprocessor(mdc), Backend(mdc), engine)


async def collect_chat_text(stream) -> str:
    """Fold a chat chunk stream to its first choice's text; raises on
    Annotated error items so failures surface instead of reading as empty
    output (delegates to the OpenAI aggregator — one fold implementation)."""
    from ..llm.protocols.openai import aggregate_chat_stream
    folded = await aggregate_chat_stream(stream)
    choices = folded.get("choices") or []
    if not choices:
        return ""
    return (choices[0].get("message") or {}).get("content") or ""


async def run_http(args, pipeline, core) -> None:
    from ..llm.http import HttpService
    svc = HttpService(port=args.http_port, host=args.http_host)
    name = _model_name(args)
    svc.manager.add_chat_model(name, pipeline)
    svc.manager.add_completion_model(name, pipeline)
    await svc.start()
    logger.info("serving %s on http://%s:%d/v1", name, args.http_host,
                args.http_port)
    await svc.run_forever()


async def run_text(args, pipeline, interactive: bool) -> None:
    from ..runtime import Context
    name = _model_name(args)
    loop = asyncio.get_running_loop()
    if interactive and sys.stdin.isatty():
        print(f"model: {name} — empty line or Ctrl-D to exit")
    while True:
        if interactive and sys.stdin.isatty():
            print("> ", end="", flush=True)
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line:
            return                      # EOF
        if not line.strip():
            if interactive:
                return                  # empty line exits the REPL
            continue                    # piped input: skip blanks, keep going
        req = {"model": name, "max_tokens": args.max_tokens, "stream": True,
               "messages": [{"role": "user", "content": line.strip()}]}
        stream = await pipeline.generate(Context(req))
        print(await collect_chat_text(stream))


async def run_batch(args, pipeline, path: str) -> None:
    """batch:FILE.jsonl — one JSON per line: {"text": ...} (completion
    prompt) or {"messages": [...]} (chat). Results go to --output-path
    (default: <input>.out.jsonl)."""
    from ..runtime import Context
    name = _model_name(args)
    out_path = args.output_path or (path.rsplit(".jsonl", 1)[0] + ".out.jsonl")
    done = 0
    failed = 0

    def _read_lines() -> list:
        with open(path) as fin:
            return fin.readlines()

    # file reads/writes ride to_thread so generation on this loop (e.g. a
    # co-located in-process engine) keeps stepping during the I/O
    lines = await asyncio.to_thread(_read_lines)
    fout = await asyncio.to_thread(open, out_path, "w")
    try:
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                messages = d.get("messages") or [
                    {"role": "user",
                     "content": d.get("text", d.get("prompt", ""))}]
                req = {"model": name, "stream": True,
                       "max_tokens": d.get("max_tokens", args.max_tokens),
                       "messages": messages}
                if "temperature" in d:
                    req["temperature"] = d["temperature"]
                stream = await pipeline.generate(Context(req))
                text = await collect_chat_text(stream)
                out_line = json.dumps({**d, "response": text}) + "\n"
            except json.JSONDecodeError as e:
                failed += 1
                out_line = json.dumps({"input": line,
                                       "error": str(e)}) + "\n"
            except Exception as e:  # noqa: BLE001 — per-row isolation
                failed += 1
                out_line = json.dumps({**d, "error": str(e)}) + "\n"
            await asyncio.to_thread(fout.write, out_line)
            done += 1
    finally:
        await asyncio.to_thread(fout.close)
    level = logging.WARNING if failed else logging.INFO
    logger.log(level, "batch complete: %d requests (%d failed) → %s",
               done, failed, out_path)
    if failed:
        raise SystemExit(1)


async def run_worker_endpoint(args, engine, pipeline, core, runtime,
                              path: str, mdc=None) -> None:
    """in=dyn://ns/comp/ep — serve as a discoverable worker instance
    (input/endpoint.rs:34-115): stats handler publishes ForwardPassMetrics;
    KV events go to the component's kv_events subject for KV-aware routers.

    protocol=openai serves the full pipeline (preproc+detok on the worker,
    the dynamo-run shape); protocol=tokens serves the bare core engine (a
    KV-routing processor tokenizes and detokenizes, the examples/llm
    Processor→Router→Worker shape)."""
    from ..llm.protocols.annotated import encode_annotated_json
    from ..llm.protocols.common import PreprocessedRequest
    from ..runtime.distributed import Endpoint
    endpoint = Endpoint.parse_path(runtime, path)
    stats_handler = None
    if core is not None:
        def stats_handler():
            from ..runtime import netstore
            d = core.metrics().to_dict()
            # process-wide daemon-link counters ride the worker's scrape
            # (nv_llm_netstore_retries_total / _deadline_exceeded_total)
            d["netstore_retries_total"] = netstore.retries_total()
            d["netstore_deadline_exceeded_total"] = \
                netstore.deadline_exceeded_total()
            return d
        await _wire_kv_events(core, runtime, endpoint)
        await _wire_spec_config(core, runtime, endpoint.namespace)
        _wire_kv_admin(core, runtime, endpoint.namespace)
        _wire_kv_weights(runtime, endpoint.namespace)
        _wire_faults(runtime, endpoint.namespace)
        _wire_tracing(args, core, runtime, endpoint)
        if getattr(args, "tenancy", False):
            # multi-tenant quotas (llm/tenancy.py): per-tenant block
            # ledger across the KV tiers + live policy watch
            # (llmctl tenant {set-weight,set-quota})
            core.enable_tenancy()
            _wire_tenants(runtime, endpoint.namespace)
        if args.kv_fabric:
            # fleet KV fabric (llm/kv/fabric.py): serve our disk/host
            # blocks at dyn://{ns}/{comp}/kv_fabric, fetch peers' —
            # the G4 rung behind the same KvBlockManager cascade
            from ..llm.kv.fabric import KvFabric
            await KvFabric.attach(core, runtime, endpoint)
    if args.protocol == "tokens":
        if mdc is None:
            raise SystemExit(
                "--protocol tokens needs a token-level engine "
                "(out=jax or out=echo_core), not a full-pipeline one")
        await endpoint.serve(
            engine,
            decode_req=lambda raw: PreprocessedRequest.from_dict(
                json.loads(raw)),
            encode_resp=encode_annotated_json,
            stats_handler=stats_handler)
    else:
        await endpoint.serve(pipeline, encode_resp=encode_annotated_json,
                             stats_handler=stats_handler)
        # register the model entries under our lease so discovery-driven
        # frontends pick the model up — and drop it when this worker dies
        if args.model_path or args.model_name:
            from ..llm.discovery import ModelEntry, register_model
            lease = await runtime.primary_lease()
            for mt in ("chat", "completion"):
                await register_model(runtime, ModelEntry(
                    name=_model_name(args), endpoint=endpoint.path,
                    model_type=mt), lease_id=lease.id)
            # registry card (llm/registry.py): the model's deployment
            # record — tokenizer ref, geometry, program-set key — under
            # the same lease, so multi-model frontends can multiplex
            # the OpenAI `model` field onto this fleet
            from ..llm.registry import RegistryCard, register_card
            geometry = {
                "tp": args.tp, "pp": args.pp, "sp": args.sp,
                "quantization": args.quantization or None,
                "kv_quantization": args.kv_quantization or None,
                "spec_k": args.spec_k, "ragged": bool(args.ragged),
                "max_seq_len": args.max_model_len,
            }
            await register_card(runtime, RegistryCard(
                name=_model_name(args), endpoint=endpoint.path,
                model_path=args.model_path,
                kv_block_size=(core.cfg.kv_block_size if core is not None
                               else args.kv_block_size or 16),
                geometry=geometry), lease_id=lease.id)
    logger.info("worker serving %s (%s protocol)", endpoint.path,
                args.protocol)
    await asyncio.Event().wait()


def _wire_tenants(runtime, namespace: str) -> None:
    """llmctl tenant plumbing (llm/tenancy.py): converge to the stored
    tenant/control/{ns} policy table and keep applying live updates —
    the TIER_WEIGHTS retune pattern for tenant weights/quotas."""
    from ..llm.tenancy import watch_tenants_loop
    asyncio.get_running_loop().create_task(
        watch_tenants_loop(runtime, namespace), name="tenant-watch")


def _wire_tracing(args, core, runtime, endpoint) -> None:
    """Fleet tracing wiring (docs/observability.md): configure the
    process tracer's log sampling, publish every finished trace over the
    component's trace_events subject (the collector on the metrics
    service assembles the fleet trees), and watch the trace/control key
    so ``llmctl trace dump`` can pull this worker's flight recorder."""
    from ..components.trace_collector import wire_trace_publisher
    from ..engine.flight_recorder import watch_trace_dump_loop
    from ..runtime.tracing import tracer

    tracer.configure(log_every=getattr(args, "trace_log_every", None),
                     slow_ms=getattr(args, "trace_log_slow_ms", None))
    component = runtime.namespace(endpoint.namespace).component(
        endpoint.component)
    wire_trace_publisher(component)
    asyncio.get_running_loop().create_task(
        watch_trace_dump_loop(core, runtime, endpoint.namespace),
        name="trace-dump-watch")


async def _wire_kv_events(core, runtime, endpoint) -> None:
    """Attach a KvEventPublisher to the engine's block pool → bus subject
    ``evt.{ns}.{comp}.kv_events`` (reference kv_router/publisher.rs)."""
    from ..llm.kv_router.publisher import KvEventPublisher
    component = runtime.namespace(endpoint.namespace).component(
        endpoint.component)
    lease = await runtime.primary_lease()

    async def sink(ev) -> None:
        await component.publish_event("kv_events", ev)

    pub = KvEventPublisher(worker_id=lease.id, sink=sink)
    core.kv_event_publisher = pub
    # route pool events through the core's tier-aware wrappers: a device
    # eviction whose hash survives in the host/disk tier DEMOTES the
    # announce (tier-tagged re-store) instead of removing it, and disk
    # spills/evictions announce with tier="disk"
    core.kv_manager.pool.on_stored = core._on_block_stored
    core.kv_manager.pool.on_removed = core._on_block_removed

    if core.disk_store is not None and len(core.disk_store) > 0:
        # warm-started disk tier: announce the recovered prefixes so the
        # router's radix index routes matching prompts here for a
        # promote instead of a cold recompute elsewhere (the same
        # reannounce() hook the lease-reclaim recovery uses)
        # off-loop: the remote-tier inventory walk reads every durable
        # object's chain meta (per-object file I/O, proportional to the
        # warm tier) — the engine loop isn't serving yet, but frontends
        # sharing this process's loop are (DL001, found by the typed-
        # chain resolution this PR added)
        n = await asyncio.to_thread(core.reannounce_kv)
        logger.info("announced %d KV blocks at bring-up (%d disk-"
                    "resident from the previous run)", n,
                    len(core.disk_store))

    # transient lease expiry → reclaim replays discovery keys but the
    # router's radix index of OUR blocks was wiped by the DELETE events;
    # re-announce the pool so KV-aware routing recovers instead of
    # silently degrading to load-balancing (KNOWN_ISSUES, fixed this PR)
    prev = getattr(runtime.store, "on_lease_reclaimed", None)

    def reclaimed(lease_id: int) -> None:
        if prev is not None:
            prev(lease_id)
        if lease_id == lease.id:
            n = core.reannounce_kv()
            logger.info("re-announced %d stored KV blocks after lease "
                        "reclaim", n)

    runtime.store.on_lease_reclaimed = reclaimed


async def _wire_spec_config(core, runtime, namespace: str) -> None:
    """Live speculative-decoding retune (llmctl spec set-k/off): load the
    stored draft budget for this namespace, then watch the config key and
    move ``core.spec_k_live`` within [0, cfg.spec_k] — the compiled
    verify program never widens at runtime (engine/spec/admin.py)."""
    from ..engine.spec import SpecConfig, spec_config_key

    key = spec_config_key(namespace)

    def apply(raw: bytes) -> None:
        try:
            k = SpecConfig.from_json(raw).k
        except (ValueError, KeyError):
            logger.warning("ignoring malformed spec config at %s", key)
            return
        core.spec_k_live = max(0, min(k, core.cfg.spec_k))
        if k > core.cfg.spec_k:
            logger.warning(
                "spec set-k %d exceeds the compiled maximum %d — "
                "clamped (restart with a larger --spec-k to widen the "
                "verify program)", k, core.cfg.spec_k)
        logger.info("speculation live draft budget -> %d",
                    core.spec_k_live)

    from ..runtime.kvstore import WatchEventType
    entry = await runtime.store.kv_get(key)
    if entry is not None:
        apply(entry.value)
    watcher = await runtime.store.watch_prefix(key)

    async def watch_loop() -> None:
        async for ev in watcher:
            if ev.type == WatchEventType.PUT:
                apply(ev.entry.value)

    asyncio.get_running_loop().create_task(watch_loop(),
                                           name="spec-config-watch")


def _wire_kv_admin(core, runtime, namespace: str) -> None:
    """llmctl kv {status,flush} plumbing (llm/kv/admin.py): publish this
    worker's tier snapshot and act on flush/clear commands. Wired only
    when any offload tier exists — a pure-HBM engine has nothing to
    report or flush."""
    if core.kv_manager.host_pool is None and core.disk_store is None:
        return
    from ..llm.kv.admin import publish_status_loop, watch_control_loop
    loop = asyncio.get_running_loop()
    loop.create_task(publish_status_loop(core, runtime, namespace),
                     name="kv-admin-status")
    loop.create_task(watch_control_loop(core, runtime, namespace),
                     name="kv-admin-control")


def _wire_kv_weights(runtime, namespace: str) -> None:
    """llmctl kv set-weights plumbing: apply the namespace's stored tier
    weights and keep applying live updates (llm/kv/admin.py
    watch_weights_loop). Runs on every worker — and any process hosting
    a KV router gets the same watch via KvRoutedEngine — so the fleet's
    scoring stays coherent."""
    from ..llm.kv.admin import watch_weights_loop
    asyncio.get_running_loop().create_task(
        watch_weights_loop(runtime, namespace), name="kv-weights-watch")


def _wire_faults(runtime, namespace: str) -> None:
    """llmctl faults plumbing (runtime/faults.py): apply the
    namespace's stored failpoint table and keep applying live updates —
    the fleet-wide chaos-drill lever (docs/chaos.md)."""
    from ..runtime.faults import watch_faults_loop
    asyncio.get_running_loop().create_task(
        watch_faults_loop(runtime, namespace), name="faults-watch")


async def run_prefill_worker(args, core, runtime) -> None:
    from ..llm.disagg import PrefillWorker
    worker = await PrefillWorker(core, runtime).start()
    logger.info("prefill worker pulling queue (engine ready)")
    try:
        await asyncio.Event().wait()
    finally:
        await worker.stop()


async def run_prefill_publish(args, core, runtime, src: str) -> None:
    """--role prefill-publish: the prefill-as-a-service worker
    (components/prefill_service.py). Serves publish/status RPCs at a
    discoverable endpoint (in=dyn://… or the default
    dyn://{ns}/prefill/prefill_publish) and pulls the shared
    prefill_publish work queue; published prefix KV lands in the
    --kv-remote-dir object tier for any decode fleet to admit."""
    from ..components.prefill_service import (PREFILL_PUBLISH_ENDPOINT,
                                              PrefillService)
    from ..runtime.distributed import Endpoint
    try:
        svc = await PrefillService(core, runtime).start()
    except ValueError as e:
        raise SystemExit(str(e))
    if src.startswith("dyn://") or src.count(".") == 2:
        endpoint = Endpoint.parse_path(runtime, src)
    else:
        endpoint = Endpoint(runtime, args.namespace, "prefill",
                            PREFILL_PUBLISH_ENDPOINT)

    def stats_handler():
        d = core.metrics().to_dict()
        d.update(svc.stats())
        return d

    await endpoint.serve(svc, decode_req=lambda raw: json.loads(raw),
                         stats_handler=stats_handler)
    logger.info("prefill-publish worker serving %s (object root %s)",
                endpoint.path, core.cfg.kv_remote_dir)
    try:
        await asyncio.Event().wait()
    finally:
        await svc.stop()


async def amain(argv=None) -> None:
    args = build_parser().parse_args(argv)
    from ..runtime.log import setup_logging
    setup_logging('debug' if args.verbose else None)
    src, out = parse_io(args.io)

    if args.model_path and not os.path.isdir(args.model_path):
        # hub resolution (reference launch/dynamo-run/src/hub.rs: a model
        # NAME is fetched into the local cache; a directory passes through)
        from ..llm.hub import HubError, fetch_model
        try:
            # hub download + manifest validation is bulk file I/O — keep
            # it off the loop even at startup (a co-located server on
            # this loop would stall behind a 70B snapshot check)
            args.model_path = await asyncio.to_thread(
                fetch_model, args.model_path)
        except HubError as e:
            raise SystemExit(str(e))

    # Multi-host join must precede any JAX use in this process. Every host
    # runs the same command with its own --node-rank; rank 0 is the leader
    # (scheduler + frontend + token egress) and streams its dispatch
    # sequence to the followers, which live-replay it so every rank enters
    # the SPMD collectives in lockstep (engine/multihost.py; reference:
    # lib/llm/src/engines/vllm/ray.rs leader/follower).
    from ..parallel.multihost import MultiNodeConfig, initialize_multihost
    if args.num_nodes > 1:
        # validate BEFORE weights load / listener bind, on every rank — the
        # same constraints DispatchStreamLeader.attach enforces, surfaced
        # as CLI config errors
        if out != "jax":
            raise SystemExit("multi-host serving requires out=jax")
        if args.decode_steps_per_dispatch <= 1:
            raise SystemExit(
                "multi-host serving requires --decode-steps-per-dispatch "
                "> 1 (the single-step decode path is not in the dispatch "
                "stream)")
    initialize_multihost(MultiNodeConfig(
        num_nodes=args.num_nodes, node_rank=args.node_rank,
        leader_addr=args.leader_addr))

    if args.num_nodes > 1 and args.node_rank > 0:
        await run_follower_rank(args, out)
        return

    runtime = await make_runtime(args)
    stream = None
    try:
        engine, mdc, core = await build_engine(args, out, runtime)
        if args.num_nodes > 1:
            if core is None:
                raise SystemExit("multi-host serving requires out=jax")
            from ..engine.multihost import DispatchStreamLeader
            stream = DispatchStreamLeader(
                port=args.dispatch_stream_port,
                num_followers=args.num_nodes - 1)
            stream.attach(core)
            logger.info("waiting for %d follower rank(s) on dispatch "
                        "stream port %d", args.num_nodes - 1, stream.port)
            stream.wait_for_followers()
        if args.is_prefill_worker:
            if core is None:
                raise SystemExit("--is-prefill-worker requires out=jax")
            await run_prefill_worker(args, core, runtime)
            return
        if args.role == "prefill-publish":
            if core is None:
                raise SystemExit("--role prefill-publish requires out=jax")
            await run_prefill_publish(args, core, runtime, src)
            return
        pipeline = link_pipeline(engine, mdc)
        if src == "http":
            await run_http(args, pipeline, core)
        elif src == "text":
            await run_text(args, pipeline, interactive=True)
        elif src == "stdin":
            await run_text(args, pipeline, interactive=False)
        elif src.startswith("batch:"):
            await run_batch(args, pipeline, src[len("batch:"):])
        elif src.startswith("dyn://") or src.count(".") == 2:
            await run_worker_endpoint(args, engine, pipeline, core, runtime,
                                      src, mdc=mdc)
        elif src == "none":
            await asyncio.Event().wait()
        else:
            raise SystemExit(f"unknown in= source {src!r}")
    finally:
        if 'core' in locals() and core is not None:
            try:
                await core.stop()
            except asyncio.CancelledError:
                # SIGINT: asyncio.run cancelled amain and the cancel
                # landed at stop()'s first await — finish the graceful
                # stop anyway (it flushes the host KV tier to the disk
                # store; losing it would turn every Ctrl-C restart into
                # a partially-cold start), then let the cancel proceed
                await core.stop()
                raise
        if stream is not None:
            stream.close()   # followers get __shutdown__, exit cleanly
        await runtime.shutdown()


def main() -> None:
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
