"""Model-hub fetch: resolve a model NAME to a local directory.

Reference: launch/dynamo-run/src/hub.rs — `from_hf` lists a repo's files,
downloads everything except housekeeping files (.gitattributes, LICENSE,
README.md) and images into the hub cache, and returns the snapshot
directory. The TPU deployment runs in zero-egress environments, so the
transport here is a MIRROR — a directory (or file:// URL) laid out like
the hub (``<mirror>/<org>/<name>/<files>``), typically an NFS/GCS-fuse
mount — with the same filtering, the same local cache, and per-file
sha256 validation recorded in a manifest so a torn copy is detected and
re-fetched instead of served.

Resolution order (`fetch_model`):
1. an existing local directory path is returned as-is;
2. a cached snapshot with a valid manifest is reused;
3. otherwise the model is copied from the mirror into the cache
   atomically (temp dir + rename) and the manifest written last.

Env: ``DYN_HUB_MIRROR`` (mirror root), ``DYN_HUB_CACHE`` (cache root,
default ``~/.cache/dynamo_tpu/hub``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import tempfile
from typing import Dict, Optional

logger = logging.getLogger("dynamo_tpu.llm.hub")

__all__ = ["fetch_model", "HubError"]

# reference hub.rs:19 IGNORED + is_image
IGNORED = {".gitattributes", "LICENSE", "README.md"}
IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp", ".svg"}

MANIFEST = ".dynamo_hub_manifest.json"


class HubError(RuntimeError):
    pass


def _is_ignored(name: str) -> bool:
    return (name in IGNORED
            or os.path.splitext(name)[1].lower() in IMAGE_EXTS)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _mirror_root(mirror: Optional[str]) -> str:
    mirror = mirror or os.environ.get("DYN_HUB_MIRROR", "")
    if not mirror:
        raise HubError(
            "model is not a local directory and no hub mirror is "
            "configured (set DYN_HUB_MIRROR or pass mirror=)")
    if mirror.startswith("file://"):
        mirror = mirror[len("file://"):]
    return mirror


def _cache_root(cache_dir: Optional[str]) -> str:
    return (cache_dir or os.environ.get("DYN_HUB_CACHE")
            or os.path.expanduser("~/.cache/dynamo_tpu/hub"))


def _snapshot_valid(snap: str, deep: bool = False) -> bool:
    """A snapshot is valid iff its manifest exists and every listed file
    is present with the recorded size (hot path — cheap enough for every
    process start even at 70B scale). ``deep`` additionally verifies each
    sha256 (hub.rs relies on hf-hub's etag cache; a mirror copy needs
    explicit integrity on demand)."""
    mpath = os.path.join(snap, MANIFEST)
    try:
        with open(mpath) as f:
            manifest: Dict[str, dict] = json.load(f)["files"]
    except (OSError, ValueError, KeyError):
        return False
    for name, rec in manifest.items():
        p = os.path.join(snap, name)
        try:
            ok = os.path.getsize(p) == rec["size"]
        except OSError:
            ok = False
        if ok and deep:
            ok = _sha256(p) == rec["sha256"]
        if not ok:
            logger.warning("hub cache %s: %s failed validation", snap, name)
            return False
    return True


def _list_files(root: str) -> list:
    """Relative paths of all regular files under root, housekeeping and
    images filtered by BASENAME (subdirectories like HF's `original/`
    are part of the snapshot and must not be silently dropped)."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if _is_ignored(name):
                continue
            out.append(os.path.relpath(os.path.join(dirpath, name), root))
    return sorted(out)


def fetch_model(name_or_path: str, mirror: Optional[str] = None,
                cache_dir: Optional[str] = None,
                revalidate: bool = False) -> str:
    """Resolve a model name (or local path) to a local snapshot directory.

    Reference contract: launch/dynamo-run/src/hub.rs `from_hf` (name →
    cached dir, housekeeping files skipped, empty repos rejected).
    """
    if os.path.isdir(name_or_path):
        return name_or_path

    slug = name_or_path.replace("/", "--")
    snap = os.path.join(_cache_root(cache_dir), slug)
    if os.path.isdir(snap) and _snapshot_valid(snap, deep=revalidate):
        logger.info("hub cache hit: %s -> %s", name_or_path, snap)
        return snap

    src = os.path.join(_mirror_root(mirror), name_or_path)
    if not os.path.isdir(src):
        raise HubError(
            f"model {name_or_path!r} not found in hub mirror "
            f"({src} does not exist). Is this a valid model id?")
    names = _list_files(src)
    if not names:
        raise HubError(
            f"model {name_or_path!r} exists but contains no usable files")

    os.makedirs(_cache_root(cache_dir), exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f".{slug}.", dir=_cache_root(cache_dir))
    try:
        manifest: Dict[str, dict] = {}
        for name in names:
            dst = os.path.join(tmp, name)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copyfile(os.path.join(src, name), dst)
            manifest[name] = {"sha256": _sha256(dst),
                              "size": os.path.getsize(dst)}
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump({"model": name_or_path, "files": manifest}, f,
                      indent=1)
        # atomic publish, never destructive to a concurrent reader: if
        # another process won the race with a VALID snapshot, use theirs;
        # only an invalid loser is moved aside and removed
        try:
            os.rename(tmp, snap)
        except OSError:
            # validate at the caller's requested depth — a shallow check
            # here would bless the very snapshot a deep revalidate just
            # rejected
            if _snapshot_valid(snap, deep=revalidate):
                shutil.rmtree(tmp, ignore_errors=True)
                logger.info("hub fetch race: reusing %s", snap)
                return snap
            aside = tempfile.mkdtemp(prefix=f".{slug}.stale.",
                                     dir=_cache_root(cache_dir))
            os.rename(snap, os.path.join(aside, "old"))
            os.rename(tmp, snap)
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.info("hub fetch: %s -> %s (%d files)", name_or_path, snap,
                len(names))
    return snap
