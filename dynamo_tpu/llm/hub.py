"""Model-hub fetch: resolve a model NAME to a local directory.

Reference: launch/dynamo-run/src/hub.rs — `from_hf` lists a repo's files,
downloads everything except housekeeping files (.gitattributes, LICENSE,
README.md) and images into the hub cache, and returns the snapshot
directory. Two transports, selected by the mirror URL's scheme:

- **directory mirror** (path or ``file://``): a tree laid out like the
  hub (``<mirror>/<org>/<name>/<files>``), typically an NFS/GCS-fuse
  mount — the zero-egress deployment shape.
- **HTTP(S) hub** (``http://`` / ``https://``): the HF-hub wire surface
  the reference's hf-hub crate speaks — repo listing from
  ``GET {base}/api/models/{repo}`` (``siblings[].rfilename``), file
  bytes from ``GET {base}/{repo}/resolve/{rev}/{file}`` — with bearer
  auth from ``HF_TOKEN``/``DYN_HUB_TOKEN``, per-file retry, and Range
  resume of partial downloads.

Both land in the same local cache with per-file sha256 recorded in a
manifest, so a torn copy is detected and re-fetched instead of served.

Resolution order (`fetch_model`):
1. an existing local directory path is returned as-is;
2. a cached snapshot with a valid manifest is reused;
3. otherwise the model is fetched from the mirror into the cache
   atomically (temp dir + rename) and the manifest written last.

Env: ``DYN_HUB_MIRROR`` (mirror root or hub base URL), ``DYN_HUB_CACHE``
(cache root, default ``~/.cache/dynamo_tpu/hub``), ``DYN_HUB_REVISION``
(HTTP revision, default ``main``), ``HF_TOKEN``/``DYN_HUB_TOKEN``.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import shutil
import tempfile
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.llm.hub")

__all__ = ["fetch_model", "HubError"]

# reference hub.rs:19 IGNORED + is_image
IGNORED = {".gitattributes", "LICENSE", "README.md"}
IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp", ".svg"}

MANIFEST = ".dynamo_hub_manifest.json"


class HubError(RuntimeError):
    pass


def _is_ignored(name: str) -> bool:
    return (name in IGNORED
            or os.path.splitext(name)[1].lower() in IMAGE_EXTS)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _mirror_root(mirror: Optional[str]) -> str:
    mirror = mirror or os.environ.get("DYN_HUB_MIRROR", "")
    if not mirror:
        raise HubError(
            "model is not a local directory and no hub mirror is "
            "configured (set DYN_HUB_MIRROR or pass mirror=)")
    if mirror.startswith("file://"):
        mirror = mirror[len("file://"):]
    return mirror


def _is_http(mirror: str) -> bool:
    return mirror.startswith(("http://", "https://"))


# ------------------------------------------------------------- HTTP hub

_HTTP_RETRIES = 3
_HTTP_CHUNK = 1 << 20


def _hub_token() -> Optional[str]:
    return os.environ.get("DYN_HUB_TOKEN") or os.environ.get("HF_TOKEN")


class _AuthStrippingRedirectHandler(urllib.request.HTTPRedirectHandler):
    """Drop the Authorization header when a redirect leaves the original
    host — the hub 302s LFS shards to CDNs, and forwarding the bearer
    token to a third-party (or attacker-chosen) host would leak it.
    (huggingface_hub strips auth on cross-host redirects for the same
    reason.)"""

    def redirect_request(self, req, fp, code, msg, headers, newurl):
        new = super().redirect_request(req, fp, code, msg, headers, newurl)
        if new is not None and new.host != req.host:
            new.remove_header("Authorization")
        return new


_OPENER = urllib.request.build_opener(_AuthStrippingRedirectHandler)


def _http_open(url: str, headers: Optional[dict] = None, timeout=30):
    req = urllib.request.Request(url, headers=dict(headers or {}))
    tok = _hub_token()
    if tok:
        req.add_header("Authorization", f"Bearer {tok}")
    return _OPENER.open(req, timeout=timeout)  # noqa: S310


def _http_list_files(base: str, repo: str, revision: str) -> List[str]:
    """Repo file listing via the hub API (hub.rs `api.model(...).info()`):
    ``GET {base}/api/models/{repo}/revision/{rev}`` ->
    ``{"siblings": [{"rfilename": ...}]}``."""
    url = f"{base.rstrip('/')}/api/models/{repo}/revision/{revision}"
    try:
        with _http_open(url) as r:
            info = json.load(r)
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise HubError(
                f"model {repo!r} not found on hub {base} "
                f"(HTTP 404). Is this a valid model id?") from e
        raise HubError(f"hub listing failed for {repo!r}: HTTP "
                       f"{e.code}") from e
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise HubError(f"hub listing failed for {repo!r}: {e}") from e
    # the body is untrusted: wrong-shaped JSON must be a HubError, not
    # an AttributeError escaping fetch_model
    if not isinstance(info, dict) or not isinstance(
            info.get("siblings", []), list):
        raise HubError(f"hub listing for {repo!r} is not a model-info "
                       f"object")
    names = [s.get("rfilename", "") for s in info.get("siblings", [])
             if isinstance(s, dict)]
    out = []
    for n in names:
        if not n or _is_ignored(os.path.basename(n)):
            continue
        # the listing is UNTRUSTED input: a hostile server must not be
        # able to write outside the snapshot via ../ or absolute names
        if os.path.isabs(n) or n.startswith("~"):
            raise HubError(f"hub listing for {repo!r} contains an "
                           f"absolute path {n!r}")
        norm = os.path.normpath(n)
        if norm.startswith("..") or os.path.isabs(norm):
            raise HubError(f"hub listing for {repo!r} contains a "
                           f"path-traversal name {n!r}")
        out.append(norm)
    return sorted(out)


def _http_fetch_file(base: str, repo: str, revision: str, name: str,
                     dst: str) -> None:
    """Download one file (hub.rs `repo.get(name)` analog):
    ``GET {base}/{repo}/resolve/{rev}/{name}`` with per-file retries; a
    partial ``.part`` from a failed attempt resumes via a Range request
    (checked against 206) instead of restarting multi-GB shards."""
    url = f"{base.rstrip('/')}/{repo}/resolve/{revision}/{name}"
    part = dst + ".part"
    last: Optional[Exception] = None
    for attempt in range(_HTTP_RETRIES):
        have = os.path.getsize(part) if os.path.exists(part) else 0
        headers = {"Range": f"bytes={have}-"} if have else {}
        try:
            with _http_open(url, headers) as r:
                if have and r.status != 206:
                    # server ignored the Range: restart from zero
                    have = 0
                expect = r.headers.get("Content-Length")
                mode = "ab" if have else "wb"
                wrote = 0
                with open(part, mode) as f:
                    while True:
                        chunk = r.read(_HTTP_CHUNK)
                        if not chunk:
                            break
                        f.write(chunk)
                        wrote += len(chunk)
            if expect is not None and wrote != int(expect):
                # a dropped connection can surface as a silent short
                # body — a truncated shard must NEVER be blessed into
                # the manifest (its sha256 would "validate" the damage)
                raise OSError(
                    f"short body: {wrote} of {expect} bytes")
            os.replace(part, dst)
            return
        except urllib.error.HTTPError as e:
            if e.code in (404, 401, 403):
                raise HubError(
                    f"hub download of {repo}/{name} failed: HTTP "
                    f"{e.code}") from e
            last = e
        except (urllib.error.URLError, OSError, http.client.HTTPException
                ) as e:
            last = e
        if attempt < _HTTP_RETRIES - 1:   # no pointless backoff after
            logger.warning("hub download retry %d/%d for %s/%s: %s",
                           attempt + 1, _HTTP_RETRIES, repo, name, last)
            time.sleep(min(2 ** attempt, 5))
    raise HubError(
        f"hub download of {repo}/{name} failed after "
        f"{_HTTP_RETRIES} attempts: {last}")


def _cache_root(cache_dir: Optional[str]) -> str:
    return (cache_dir or os.environ.get("DYN_HUB_CACHE")
            or os.path.expanduser("~/.cache/dynamo_tpu/hub"))


def _snapshot_valid(snap: str, deep: bool = False) -> bool:
    """A snapshot is valid iff its manifest exists and every listed file
    is present with the recorded size (hot path — cheap enough for every
    process start even at 70B scale). ``deep`` additionally verifies each
    sha256 (hub.rs relies on hf-hub's etag cache; a mirror copy needs
    explicit integrity on demand)."""
    mpath = os.path.join(snap, MANIFEST)
    try:
        with open(mpath) as f:
            manifest: Dict[str, dict] = json.load(f)["files"]
    except (OSError, ValueError, KeyError):
        return False
    for name, rec in manifest.items():
        p = os.path.join(snap, name)
        try:
            ok = os.path.getsize(p) == rec["size"]
        except OSError:
            ok = False
        if ok and deep:
            ok = _sha256(p) == rec["sha256"]
        if not ok:
            logger.warning("hub cache %s: %s failed validation", snap, name)
            return False
    return True


def _list_files(root: str) -> list:
    """Relative paths of all regular files under root, housekeeping and
    images filtered by BASENAME (subdirectories like HF's `original/`
    are part of the snapshot and must not be silently dropped)."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if _is_ignored(name):
                continue
            out.append(os.path.relpath(os.path.join(dirpath, name), root))
    return sorted(out)


def fetch_model(name_or_path: str, mirror: Optional[str] = None,
                cache_dir: Optional[str] = None,
                revalidate: bool = False) -> str:
    """Resolve a model name (or local path) to a local snapshot directory.

    Reference contract: launch/dynamo-run/src/hub.rs `from_hf` (name →
    cached dir, housekeeping files skipped, empty repos rejected).
    """
    if os.path.isdir(name_or_path):
        return name_or_path

    slug = name_or_path.replace("/", "--")
    snap = os.path.join(_cache_root(cache_dir), slug)
    if os.path.isdir(snap) and _snapshot_valid(snap, deep=revalidate):
        logger.info("hub cache hit: %s -> %s", name_or_path, snap)
        return snap

    root = _mirror_root(mirror)
    if _is_http(root):
        revision = os.environ.get("DYN_HUB_REVISION", "main")
        names = _http_list_files(root, name_or_path, revision)
        src = None
    else:
        src = os.path.join(root, name_or_path)
        if not os.path.isdir(src):
            raise HubError(
                f"model {name_or_path!r} not found in hub mirror "
                f"({src} does not exist). Is this a valid model id?")
        names = _list_files(src)
    if not names:
        raise HubError(
            f"model {name_or_path!r} exists but contains no usable files")

    os.makedirs(_cache_root(cache_dir), exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f".{slug}.", dir=_cache_root(cache_dir))
    try:
        manifest: Dict[str, dict] = {}
        for name in names:
            dst = os.path.join(tmp, name)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            if src is None:
                _http_fetch_file(root, name_or_path, revision, name, dst)
            else:
                shutil.copyfile(os.path.join(src, name), dst)
            manifest[name] = {"sha256": _sha256(dst),
                              "size": os.path.getsize(dst)}
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump({"model": name_or_path, "files": manifest}, f,
                      indent=1)
        # atomic publish, never destructive to a concurrent reader: if
        # another process won the race with a VALID snapshot, use theirs;
        # only an invalid loser is moved aside and removed
        try:
            os.rename(tmp, snap)
        except OSError:
            # validate at the caller's requested depth — a shallow check
            # here would bless the very snapshot a deep revalidate just
            # rejected
            if _snapshot_valid(snap, deep=revalidate):
                shutil.rmtree(tmp, ignore_errors=True)
                logger.info("hub fetch race: reusing %s", snap)
                return snap
            aside = tempfile.mkdtemp(prefix=f".{slug}.stale.",
                                     dir=_cache_root(cache_dir))
            os.rename(snap, os.path.join(aside, "old"))
            os.rename(tmp, snap)
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.info("hub fetch: %s -> %s (%d files)", name_or_path, snap,
                len(names))
    return snap
