"""Tool-calling: request-side choice validation + response-side matching.

Reference: lib/llm/src/preprocessor/tools{.rs,/request.rs,/response.rs} —
``ToolCallingMatcher`` parses an LLM's final message as JSON in the shapes
models actually emit ({"name", "parameters"} or {"name", "arguments"},
singly or as a list) and produces OpenAI ``tool_calls`` entries. Tool
*rendering* happens in the chat template (the HF templates take a ``tools``
kwarg — PromptFormatter.render passes it through).
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Dict, List, Optional, Union

__all__ = ["ToolChoice", "ToolCallingMatcher"]


class ToolChoice:
    """Normalized ``tool_choice``: none | auto | required | a named tool
    (reference tools/request.rs)."""

    NONE = "none"
    AUTO = "auto"
    REQUIRED = "required"

    def __init__(self, raw: Union[str, Dict[str, Any], None],
                 has_tools: bool):
        self.forced_name: Optional[str] = None
        if isinstance(raw, dict):
            # only the OpenAI named-tool shape is valid:
            # {"type": "function", "function": {"name": ...}}
            name = (raw.get("function") or {}).get("name")
            if raw.get("type") != "function" or not isinstance(name, str):
                raise ValueError(f"invalid tool_choice object: {raw!r}")
            self.mode = self.REQUIRED
            self.forced_name = name
        elif raw in (self.NONE, self.AUTO, self.REQUIRED):
            self.mode = raw
        elif raw is None:
            # OpenAI default: auto when tools are present, none otherwise
            self.mode = self.AUTO if has_tools else self.NONE
        else:
            raise ValueError(f"invalid tool_choice: {raw!r}")

    @property
    def active(self) -> bool:
        return self.mode != self.NONE


def _as_call(name: str, args: Dict[str, Any]) -> dict:
    return {
        "id": f"call-{uuid.uuid4()}",
        "type": "function",
        "function": {"name": name, "arguments": json.dumps(args)},
    }


class ToolCallingMatcher:
    """Parse a complete assistant message into tool calls.

    Accepted shapes (reference tools.rs:53-115):
    - ``{"name": n, "parameters": {...}}`` and a list of those
    - ``{"name": n, "arguments": {...}}`` and a list of those

    Returns [] when the message isn't a tool call; raises when a specific
    tool was forced (`tool_choice = {"type": "function", ...}` or
    "required") but nothing parseable came back.
    """

    def __init__(self, choice: ToolChoice):
        self.choice = choice

    @staticmethod
    def _parse_one(obj: Any) -> Optional[dict]:
        if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
            return None
        for key in ("parameters", "arguments"):
            if isinstance(obj.get(key), dict):
                return _as_call(obj["name"], obj[key])
        return None

    def get_calls(self, message: str) -> List[dict]:
        if not self.choice.active:
            return []
        try:
            data = json.loads(message.strip())
        except (json.JSONDecodeError, ValueError):
            data = None
        calls: List[dict] = []
        if data is not None:
            items = data if isinstance(data, list) else [data]
            parsed = [self._parse_one(x) for x in items]
            if parsed and all(p is not None for p in parsed):
                calls = parsed  # type: ignore[assignment]
        if not calls and self.choice.mode == ToolChoice.REQUIRED:
            raise ValueError(
                "tool choice was required but no tool was called")
        if (self.choice.forced_name
                and any(c["function"]["name"] != self.choice.forced_name
                        for c in calls)):
            raise ValueError(
                f"model called a tool other than the forced "
                f"{self.choice.forced_name!r}")
        return calls
