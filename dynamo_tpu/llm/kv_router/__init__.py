from .indexer import KvIndexer, KvIndexerSharded, OverlapScores
from .protocols import (ForwardPassMetrics, KVHitRateEvent, KvRemovedEvent,
                        KvStoredEvent, RouterEvent)
from .router import KvRouter
from .scheduler import KvScheduler
from .scoring import Endpoint, ProcessedEndpoints

__all__ = [
    "KvIndexer", "KvIndexerSharded", "OverlapScores", "KvRouter",
    "KvScheduler", "Endpoint", "ProcessedEndpoints", "ForwardPassMetrics",
    "KVHitRateEvent", "KvStoredEvent", "KvRemovedEvent", "RouterEvent",
]
