"""ctypes bridge to the C KV-event ABI (csrc/kv_event_abi.cpp).

Reference: lib/bindings/c/src/lib.rs:51-297 — the cdylib external engines
load to publish KV cache events (`dynamo_llm_init`,
`dynamo_kv_event_publish_stored/removed`), consumed via ctypes from the
vLLM patch's KVCacheEventManager (patch lines 302-416). Here the native lib
queues events and :class:`CtypesKvEventPublisher.drain` converts them to
:class:`RouterEvent`s for the message-bus sink — identical wire shape to the
in-process :class:`~dynamo_tpu.llm.kv_router.publisher.KvEventPublisher`
(the parity test feeds both into one indexer).
"""

from __future__ import annotations

import asyncio
import ctypes
import json
from typing import Awaitable, Callable, List, Optional, Sequence

from ...utils import native
from ..kv.blocks import hash_tokens
from .protocols import KvRemovedEvent, KvStoredEvent, RouterEvent

DYN_OK = 0


def load_abi() -> Optional[ctypes.CDLL]:
    lib = native.load("dynkvabi", ["kv_event_abi.cpp"])
    if lib is None:
        return None
    lib.dynamo_llm_init.restype = ctypes.c_int64
    lib.dynamo_llm_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_int64, ctypes.c_uint32]
    lib.dynamo_llm_shutdown.restype = ctypes.c_int64
    lib.dynamo_kv_event_publish_stored.restype = ctypes.c_int64
    lib.dynamo_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
    lib.dynamo_kv_event_publish_removed.restype = ctypes.c_int64
    lib.dynamo_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
    lib.dyn_kv_event_poll.restype = ctypes.c_void_p
    lib.dyn_kv_event_str_free.argtypes = [ctypes.c_void_p]
    lib.dyn_kv_event_pending.restype = ctypes.c_size_t
    lib.dyn_kv_event_dropped.restype = ctypes.c_uint64
    lib.dyn_kv_abi_info.restype = ctypes.c_void_p
    return lib


def _take_string(lib: ctypes.CDLL, ptr: int) -> Optional[str]:
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr).decode("utf-8")
    finally:
        lib.dyn_kv_event_str_free(ptr)


class CtypesKvEventPublisher:
    """Engine-facing handle over the C ABI, plus the runtime-side drain.

    The publish methods take exactly what the C signatures take, so an
    out-of-process engine written against the ABI and this in-process
    wrapper exercise the same code path.
    """

    def __init__(self, namespace: str, component: str, worker_id: int,
                 kv_block_size: int):
        self.lib = load_abi()
        if self.lib is None:
            raise RuntimeError("native kv_event_abi unavailable "
                               "(no C++ toolchain?)")
        rc = self.lib.dynamo_llm_init(namespace.encode(), component.encode(),
                                      worker_id, kv_block_size)
        if rc != DYN_OK:
            raise RuntimeError(f"dynamo_llm_init failed: rc={rc}")
        self.worker_id = worker_id

    def shutdown(self) -> None:
        self.lib.dynamo_llm_shutdown()

    # ---- engine-facing (mirrors the C signatures) ----
    def publish_stored(self, event_id: int, blocks_tokens: Sequence[Sequence[int]],
                       block_hashes: Sequence[int],
                       parent_hash: Optional[int] = None,
                       lora_id: int = 0) -> int:
        flat: List[int] = [t for blk in blocks_tokens for t in blk]
        n = len(block_hashes)
        token_arr = (ctypes.c_uint32 * max(len(flat), 1))(*flat)
        sizes = (ctypes.c_size_t * max(n, 1))(*[len(b) for b in blocks_tokens])
        hashes = (ctypes.c_uint64 * max(n, 1))(*block_hashes)
        parent = (ctypes.c_uint64(parent_hash) if parent_hash is not None
                  else None)
        return self.lib.dynamo_kv_event_publish_stored(
            event_id, token_arr, sizes, hashes, n,
            ctypes.byref(parent) if parent is not None else None, lora_id)

    def publish_removed(self, event_id: int,
                        block_hashes: Sequence[int]) -> int:
        n = len(block_hashes)
        hashes = (ctypes.c_uint64 * max(n, 1))(*block_hashes)
        return self.lib.dynamo_kv_event_publish_removed(event_id, hashes, n)

    # ---- runtime-facing drain ----
    @property
    def pending(self) -> int:
        return self.lib.dyn_kv_event_pending()

    @property
    def dropped(self) -> int:
        return self.lib.dyn_kv_event_dropped()

    def info(self) -> Optional[dict]:
        raw = _take_string(self.lib, self.lib.dyn_kv_abi_info())
        return None if raw is None else json.loads(raw)

    def poll(self) -> Optional[RouterEvent]:
        """Pop one queued event, computing local token hashes (xxh3 seed
        1337) exactly as the in-process engine does."""
        raw = _take_string(self.lib, self.lib.dyn_kv_event_poll())
        if raw is None:
            return None
        d = json.loads(raw)
        ev = RouterEvent(worker_id=d["worker_id"], event_id=d["event_id"])
        if "stored" in d:
            s = d["stored"]
            ev.stored = KvStoredEvent(
                parent_hash=s["parent_hash"],
                block_hashes=list(s["block_hashes"]),
                tokens_hashes=[hash_tokens(b) for b in s["blocks_tokens"]],
                lora_id=s.get("lora_id", 0))
        if "removed" in d:
            ev.removed = KvRemovedEvent(
                block_hashes=list(d["removed"]["block_hashes"]))
        return ev

    async def drain(self, sink: Callable[[RouterEvent], Awaitable[None]],
                    poll_interval: float = 0.01) -> None:
        """Forward queued events to ``sink`` until cancelled (the runtime
        spawns this next to the bus publisher)."""
        while True:
            ev = self.poll()
            if ev is None:
                await asyncio.sleep(poll_interval)
                continue
            await sink(ev)

    async def drain_pending(self,
                            sink: Callable[[RouterEvent], Awaitable[None]]
                            ) -> int:
        """Drain whatever is queued right now (test/shutdown helper)."""
        count = 0
        while True:
            ev = self.poll()
            if ev is None:
                return count
            await sink(ev)
            count += 1
