"""KV indexer: the router's global radix/prefix index of which worker holds
which KV blocks.

Reference: lib/llm/src/kv_router/indexer.rs:139-790 (`RadixTree`,
`KvIndexer::new` single-writer event task, `compute_block_hash_for_seq`,
`KvIndexerSharded`). The tree itself is native C++ (csrc/kv_radix_index.cpp)
behind ctypes, with a pure-Python fallback; both sit behind the same
single-writer asyncio task so event application is serialized exactly like
the reference's mpsc actor.
"""

from __future__ import annotations

import asyncio
import ctypes
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ...utils import native
from ..kv.blocks import compute_block_hashes
from .protocols import RouterEvent

__all__ = ["OverlapScores", "KvIndexer", "RadixIndexNative",
           "RadixIndexPython", "make_radix_index"]


class OverlapScores:
    """worker_id → number of consecutive leading request blocks that worker
    already holds (reference `OverlapScores`). With frequency tracking on
    (an ``expiration_s`` on the index), ``frequencies`` lists the matched
    blocks' recent-use counts inside the expiration window, outermost
    first — the scheduler's hotness signal (reference add_frequency,
    indexer.rs:429-436)."""

    def __init__(self, scores: Optional[Dict[int, int]] = None,
                 frequencies: Optional[List[int]] = None,
                 weighted: Optional[Dict[int, float]] = None,
                 remote_blocks: Optional[Dict[int, int]] = None):
        self.scores: Dict[int, int] = scores or {}
        self.frequencies: List[int] = frequencies or []
        # tier-discounted effective overlap per worker (scoring.py
        # TIER_WEIGHTS): equals ``scores`` when every matched block is
        # device-resident. The scheduler consumes this, so a worker whose
        # matched prefix lives on disk wins ties only against recompute,
        # not against an HBM-resident copy elsewhere.
        self.weighted: Dict[int, float] = (
            dict(weighted) if weighted is not None else dict(self.scores))
        # worker → how many of its matched blocks carry tier "remote"
        # (a fabric fetch away, not local). The scheduler's NetKV
        # scoring keeps their credit only when that worker's modeled
        # transfer beats its modeled recompute (scoring.py
        # network_adjusted_overlap).
        self.remote_blocks: Dict[int, int] = dict(remote_blocks or {})

    @property
    def fleet_depth(self) -> int:
        """Deepest overlap any worker holds — the fabric makes those
        blocks fetchable by every attached candidate."""
        return max(self.scores.values(), default=0)

    def best(self) -> Optional[int]:
        if not self.scores:
            return None
        return max(self.scores, key=lambda w: self.scores[w])

    def __repr__(self) -> str:
        if self.frequencies:
            return f"OverlapScores({self.scores}, freq={self.frequencies})"
        return f"OverlapScores({self.scores})"


# ---------------------------------------------------------------------------
# Native tree (C++ via ctypes)
# ---------------------------------------------------------------------------


class RadixIndexNative:
    MAX_WORKERS = 4096
    MAX_DEPTH = 65536      # frequency out-buffer bound (blocks per request)

    def __init__(self, expiration_s: Optional[float] = None):
        lib = native.load("dynkv", ["kv_radix_index.cpp"])
        if lib is None:
            raise RuntimeError("native radix index unavailable")
        self._lib = lib
        # normalize: <=0 means off, matching the C++ gate (expiration > 0)
        if expiration_s is not None and expiration_s <= 0:
            expiration_s = None
        self.expiration_s = expiration_s
        lib.dyn_kv_index_new.restype = ctypes.c_void_p
        lib.dyn_kv_index_free.argtypes = [ctypes.c_void_p]
        lib.dyn_kv_index_apply_stored.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
        lib.dyn_kv_index_apply_removed.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
        lib.dyn_kv_index_remove_worker.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_int64]
        lib.dyn_kv_index_find_matches.restype = ctypes.c_size_t
        lib.dyn_kv_index_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t, ctypes.c_int]
        lib.dyn_kv_index_node_count.restype = ctypes.c_size_t
        lib.dyn_kv_index_node_count.argtypes = [ctypes.c_void_p]
        lib.dyn_kv_index_event_count.restype = ctypes.c_uint64
        lib.dyn_kv_index_event_count.argtypes = [ctypes.c_void_p]
        lib.dyn_kv_index_set_expiration.argtypes = [ctypes.c_void_p,
                                                    ctypes.c_double]
        lib.dyn_kv_index_find_matches2.restype = ctypes.c_size_t
        lib.dyn_kv_index_find_matches2.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t, ctypes.c_int, ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_size_t)]
        self._ptr = lib.dyn_kv_index_new()
        if expiration_s is not None:
            lib.dyn_kv_index_set_expiration(self._ptr, float(expiration_s))
        # reusable output buffers: find_matches is the routing hot path and
        # the index is single-reader by design, so one pair suffices
        self._out_w = (ctypes.c_int64 * self.MAX_WORKERS)()
        self._out_c = (ctypes.c_uint32 * self.MAX_WORKERS)()
        self._out_f = (ctypes.c_uint32 * self.MAX_DEPTH)()
        self._out_nf = ctypes.c_size_t(0)

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.dyn_kv_index_free(ptr)
            self._ptr = None

    @staticmethod
    def _arr(hashes: Sequence[int]):
        return (ctypes.c_uint64 * len(hashes))(*[h & 0xFFFFFFFFFFFFFFFF
                                                 for h in hashes])

    def apply_stored(self, worker_id: int, parent_hash: Optional[int],
                     block_hashes: Sequence[int]) -> None:
        self._lib.dyn_kv_index_apply_stored(
            self._ptr, worker_id, (parent_hash or 0) & 0xFFFFFFFFFFFFFFFF,
            self._arr(block_hashes), len(block_hashes))

    def apply_removed(self, worker_id: int,
                      block_hashes: Sequence[int]) -> None:
        self._lib.dyn_kv_index_apply_removed(
            self._ptr, worker_id, self._arr(block_hashes), len(block_hashes))

    def remove_worker(self, worker_id: int) -> None:
        self._lib.dyn_kv_index_remove_worker(self._ptr, worker_id)

    def find_matches(self, block_hashes: Sequence[int],
                     now: Optional[float] = None) -> OverlapScores:
        out_w, out_c = self._out_w, self._out_c
        if self.expiration_s is None:
            n = self._lib.dyn_kv_index_find_matches(
                self._ptr, self._arr(block_hashes), len(block_hashes),
                out_w, out_c, self.MAX_WORKERS, 1)
            return OverlapScores(
                {int(out_w[i]): int(out_c[i]) for i in range(n)})
        n = self._lib.dyn_kv_index_find_matches2(
            self._ptr, self._arr(block_hashes),
            min(len(block_hashes), self.MAX_DEPTH),
            out_w, out_c, self.MAX_WORKERS, 1,
            float(time.monotonic() if now is None else now),
            self._out_f, ctypes.byref(self._out_nf))
        freqs = [int(self._out_f[i]) for i in range(self._out_nf.value)]
        return OverlapScores(
            {int(out_w[i]): int(out_c[i]) for i in range(n)}, freqs)

    def node_count(self) -> int:
        return int(self._lib.dyn_kv_index_node_count(self._ptr))

    def event_count(self) -> int:
        """Events applied (stored/removed/remove_worker) since creation —
        the staleness/liveness stat the router status surface reads."""
        return int(self._lib.dyn_kv_index_event_count(self._ptr))


# ---------------------------------------------------------------------------
# Python fallback (same semantics)
# ---------------------------------------------------------------------------


class _PyNode:
    __slots__ = ("hash", "parent", "children", "workers", "recent_uses")

    def __init__(self, h: int = 0, parent=None):
        self.hash = h
        self.parent = parent
        self.children: Dict[int, "_PyNode"] = {}
        self.workers: set = set()
        self.recent_uses: deque = deque()   # timestamps inside the window


class RadixIndexPython:
    def __init__(self, expiration_s: Optional[float] = None):
        self._root = _PyNode()
        self._by_hash: Dict[int, _PyNode] = {}
        self._worker_nodes: Dict[int, set] = {}
        # normalize: <=0 means off, matching the native tree's gate
        if expiration_s is not None and expiration_s <= 0:
            expiration_s = None
        self.expiration_s = expiration_s
        self._event_count = 0    # mirrors RadixIndex::event_count

    def _find(self, h: Optional[int]) -> Optional[_PyNode]:
        if not h:
            return self._root
        return self._by_hash.get(h)

    def apply_stored(self, worker_id, parent_hash, block_hashes) -> None:
        self._event_count += 1
        node = self._find(parent_hash) or self._root
        for h in block_hashes:
            child = node.children.get(h)
            if child is None:
                child = _PyNode(h, node)
                node.children[h] = child
                self._by_hash[h] = child
            child.workers.add(worker_id)
            self._worker_nodes.setdefault(worker_id, set()).add(child)
            node = child

    def _detach_if_empty(self, node: _PyNode) -> None:
        while (node is not None and node is not self._root
               and not node.workers and not node.children):
            parent = node.parent
            if self._by_hash.get(node.hash) is node:  # only the map's holder
                del self._by_hash[node.hash]
            parent.children.pop(node.hash, None)
            node = parent

    def apply_removed(self, worker_id, block_hashes) -> None:
        self._event_count += 1
        for h in block_hashes:
            node = self._by_hash.get(h)
            if node is None:
                continue
            node.workers.discard(worker_id)
            nodes = self._worker_nodes.get(worker_id)
            if nodes:
                nodes.discard(node)
            self._detach_if_empty(node)

    def remove_worker(self, worker_id) -> None:
        # mirror the native tree exactly: snapshot hash values, then detach
        # via the flat map's current holder (kv_radix_index.cpp remove_worker)
        self._event_count += 1
        nodes = self._worker_nodes.pop(worker_id, set())
        hashes = []
        for node in nodes:
            node.workers.discard(worker_id)
            hashes.append(node.hash)
        for h in hashes:
            node = self._by_hash.get(h)
            if node is not None:
                self._detach_if_empty(node)

    def find_matches(self, block_hashes,
                     now: Optional[float] = None) -> OverlapScores:
        scores: Dict[int, int] = {}
        freqs: List[int] = []
        exp = self.expiration_s
        if exp is not None and now is None:
            now = time.monotonic()
        node = self._root
        for depth, h in enumerate(block_hashes):
            node = node.children.get(h)
            if node is None:
                break
            any_advance = False
            for w in node.workers:
                if scores.get(w, 0) == depth:
                    scores[w] = depth + 1
                    any_advance = True
            if exp is not None:
                # expire stale uses, report survivors, record this access
                # (reference find_matches, indexer.rs:252-263)
                uses = node.recent_uses
                while uses and now - uses[0] > exp:
                    uses.popleft()
                if uses:
                    freqs.append(len(uses))
                uses.append(now)
            if not any_advance:
                break
        return OverlapScores(scores, freqs)

    def node_count(self) -> int:
        # count actual tree nodes, not the flat map: duplicate hashes from
        # out-of-order re-roots occupy two tree positions but one map slot
        def cnt(n: _PyNode) -> int:
            return 1 + sum(cnt(c) for c in n.children.values())
        return cnt(self._root) - 1

    def event_count(self) -> int:
        """Events applied — mirrors RadixIndexNative.event_count."""
        return self._event_count


def make_radix_index(prefer_native: bool = True,
                     expiration_s: Optional[float] = None):
    if prefer_native:
        try:
            return RadixIndexNative(expiration_s)
        except RuntimeError:
            pass
    return RadixIndexPython(expiration_s)


# ---------------------------------------------------------------------------
# KvIndexer: single-writer event application + query API
# ---------------------------------------------------------------------------


class KvIndexer:
    """Applies RouterEvents to the tree from one task; queries compute block
    hashes for the request tokens then walk the tree (reference
    KvIndexer::new / find_matches_for_request)."""

    def __init__(self, block_size: int, prefer_native: bool = True,
                 expiration_s: Optional[float] = None):
        """``expiration_s`` enables frequency tracking: matched blocks
        report their recent-use counts inside that window via
        OverlapScores.frequencies (reference KvIndexer::new_with_frequency,
        indexer.rs:525-560)."""
        self.block_size = block_size
        self.tree = make_radix_index(prefer_native, expiration_s)
        # (worker_id, seq_hash) → tier, tracked OUTSIDE the tree (both
        # tree backends stay tier-agnostic; device is the implicit
        # default and never stored here)
        self._tiers: Dict[tuple, str] = {}
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None

    # -- event side
    def apply_event(self, event: RouterEvent) -> None:
        if event.stored is not None:
            self.tree.apply_stored(event.worker_id, event.stored.parent_hash,
                                   event.stored.block_hashes)
            tier = getattr(event.stored, "tier", "device") or "device"
            for h in event.stored.block_hashes:
                key = (event.worker_id, h)
                if tier == "device":
                    # promotion back to HBM restores full weight
                    self._tiers.pop(key, None)
                else:
                    self._tiers[key] = tier
        if event.removed is not None:
            self.tree.apply_removed(event.worker_id,
                                    event.removed.block_hashes)
            for h in event.removed.block_hashes:
                self._tiers.pop((event.worker_id, h), None)

    async def enqueue_event(self, event: RouterEvent) -> None:
        self._ensure_task()
        await self._queue.put(event)

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="kv-indexer")

    async def _run(self) -> None:
        while True:
            ev = await self._queue.get()
            self.apply_event(ev)

    async def drain(self) -> None:
        while not self._queue.empty():
            await asyncio.sleep(0)

    def remove_worker(self, worker_id: int) -> None:
        self.tree.remove_worker(worker_id)
        self._tiers = {k: v for k, v in self._tiers.items()
                       if k[0] != worker_id}

    # -- query side
    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        scores = self.tree.find_matches(block_hashes)
        if self._tiers:
            from .scoring import TIER_WEIGHTS
            for w, depth in scores.scores.items():
                eff = 0.0
                remote = 0
                for i in range(depth):
                    tier = self._tiers.get((w, block_hashes[i]), "device")
                    eff += TIER_WEIGHTS.get(tier, 1.0)
                    if tier == "remote":
                        remote += 1
                scores.weighted[w] = eff
                if remote:
                    scores.remote_blocks[w] = remote
        return scores

    def find_matches_for_request(self, token_ids: Sequence[int]
                                 ) -> OverlapScores:
        return self.find_matches(
            compute_block_hashes(token_ids, self.block_size))


class KvIndexerSharded:
    """N independent trees, events partitioned by worker id — bounds
    single-writer throughput at high event rates (reference
    `KvIndexerSharded`). Queries fan out and merge."""

    def __init__(self, block_size: int, shards: int = 4,
                 prefer_native: bool = True,
                 expiration_s: Optional[float] = None):
        self.block_size = block_size
        self.shards = [KvIndexer(block_size, prefer_native, expiration_s)
                       for _ in range(shards)]

    def _shard(self, worker_id: int) -> KvIndexer:
        return self.shards[worker_id % len(self.shards)]

    def apply_event(self, event: RouterEvent) -> None:
        self._shard(event.worker_id).apply_event(event)

    def remove_worker(self, worker_id: int) -> None:
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches_for_request(self, token_ids) -> OverlapScores:
        hashes = compute_block_hashes(token_ids, self.block_size)
        merged: Dict[int, int] = {}
        weighted: Dict[int, float] = {}
        remote: Dict[int, int] = {}
        freqs: List[int] = []
        for sh in self.shards:
            r = sh.find_matches(hashes)
            merged.update(r.scores)
            weighted.update(r.weighted)
            remote.update(r.remote_blocks)
            # each shard tracks its own subtree's uses; take the
            # elementwise max as the merged hotness view
            for i, f in enumerate(r.frequencies):
                if i < len(freqs):
                    freqs[i] = max(freqs[i], f)
                else:
                    freqs.append(f)
        return OverlapScores(merged, freqs, weighted, remote)
