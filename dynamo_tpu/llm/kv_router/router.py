"""KvRouter: indexer + scheduler glued into one `schedule(tokens)` service
(reference lib/llm/src/kv_router/kv_router.rs:44-140 — subscribe `kv_events`,
feed the indexer, scrape metrics, pick a worker)."""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from .indexer import KvIndexer
from .protocols import ForwardPassMetrics, RouterEvent
from .scheduler import KvScheduler
from .scoring import Endpoint, ProcessedEndpoints

logger = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    def __init__(self, block_size: int, prefer_native: bool = True,
                 on_hit_rate=None,
                 frequency_expiration_s: Optional[float] = None):
        """``frequency_expiration_s`` turns on the indexer's per-block
        recent-use tracking (reference new_with_frequency); the matched
        blocks' hotness lands on ``self.last_frequencies`` after every
        schedule() — surfaced for external schedulers/telemetry exactly
        like the reference's OverlapScores.frequencies (which its own
        scheduler likewise does not consume internally)."""
        self.block_size = block_size
        self.indexer = KvIndexer(block_size, prefer_native=prefer_native,
                                 expiration_s=frequency_expiration_s)
        self.scheduler = KvScheduler(block_size, on_hit_rate=on_hit_rate)
        self.last_frequencies: list = []

    # -- feeds (wired to transports in the distributed runtime layer)
    def on_kv_event(self, event: RouterEvent) -> None:
        self.indexer.apply_event(event)

    def on_metrics(self, worker_metrics: dict) -> None:
        """worker_metrics: worker_id → ForwardPassMetrics (or dict)."""
        eps = []
        for wid, m in worker_metrics.items():
            if isinstance(m, dict):
                m = ForwardPassMetrics.from_dict(m)
            eps.append(Endpoint(worker_id=int(wid), metrics=m))
        self.scheduler.update_endpoints(ProcessedEndpoints(eps))

    def on_worker_gone(self, worker_id: int) -> None:
        self.indexer.remove_worker(worker_id)

    # -- decision
    def schedule(self, token_ids: Sequence[int],
                 exclude: Optional[set] = None,
                 tenant: Optional[str] = None) -> Optional[tuple]:
        """Returns (worker_id, overlap_blocks) or None if no workers.
        ``exclude`` bars draining workers from new admissions — their
        indexed blocks stay in the radix tree (they come back if the
        drain is cancelled), the scheduler just won't pick them.
        ``tenant`` attributes the decision for fair-share accounting."""
        overlap = self.indexer.find_matches_for_request(token_ids)
        self.last_frequencies = overlap.frequencies
        # the scheduler gets the FULL OverlapScores: tier-discounted
        # depth (scoring.py TIER_WEIGHTS) plus the NetKV network
        # adjustment — remote-tier credit gated on each candidate's
        # modeled transfer beating its modeled recompute, and
        # fabric-fetchable credit for blocks other workers hold
        worker = self.scheduler.schedule(len(token_ids), overlap,
                                         exclude=exclude, tenant=tenant)
        if worker is None:
            return None
        return worker, overlap.scores.get(worker, 0)
