"""KV-routing wire protocols.

Reference: lib/llm/src/kv_router/protocols.rs:18-97 — ForwardPassMetrics
scraped from workers, KvCacheEvent stored/removed payloads flowing over the
`kv_events` subject, and the router-side RouterEvent envelope tagging events
with the emitting worker.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

KV_EVENTS_SUBJECT = "kv_events"
KV_HIT_RATE_SUBJECT = "kv-hit-rate"
LOAD_METRICS_ENDPOINT = "load_metrics"


@dataclasses.dataclass
class ForwardPassMetrics:
    """Worker load metrics published to the router (reference
    kv_router/protocols.rs ForwardPassMetrics)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    # speculative decoding (engine/spec/): cumulative draft/accept
    # counters + derived rates — defaults keep old payloads decoding
    # (from_dict drops unknown keys, absent keys take these zeros)
    spec_drafted_total: int = 0
    spec_accepted_total: int = 0
    spec_acceptance_rate: float = 0.0
    spec_accepted_per_step: float = 0.0
    # KV tier ladder (llm/kv/offload.py host tier + llm/kv/diskstore.py
    # G3 disk tier) — the nv_llm_kv_host_* / nv_llm_kv_disk_* gauge
    # feeds (components/metrics.py). Defaults keep old payloads decoding.
    host_stored_total: int = 0
    host_evicted_total: int = 0
    host_hit_rate: float = 0.0
    disk_used_blocks: int = 0
    disk_capacity_blocks: int = 0
    disk_stored_total: int = 0
    disk_evicted_total: int = 0
    disk_hit_rate: float = 0.0
    disk_bytes_used: int = 0
    disk_spill_dropped_total: int = 0
    offload_dropped_jobs_total: int = 0
    # remote (G4) fleet KV fabric (llm/kv/remotestore.py + fabric.py) —
    # the nv_llm_kv_remote_* gauge feeds, plus the MEASURED link/cost
    # model the router's NetKV scoring prices candidates with
    # (kv_router/scoring.py network_adjusted_overlap). remote_link_gbps
    # and remote_link_rtt_s are the fabric's decay-averaged peer-link
    # estimates (probe at attach, refined per transfer);
    # kv_bytes_per_block and prefill_tok_per_s complete the
    # transfer-vs-recompute model. Zeros on old payloads / no fabric.
    remote_used_blocks: int = 0
    remote_capacity_blocks: int = 0
    remote_peer_blocks: int = 0
    remote_stored_total: int = 0
    remote_hit_rate: float = 0.0
    remote_fetch_failures_total: int = 0
    remote_admission_rejects_total: int = 0
    remote_link_gbps: float = 0.0
    remote_link_rtt_s: float = 0.0
    kv_bytes_per_block: int = 0
    prefill_tok_per_s: float = 0.0
    # tokens per KV block (EngineConfig.kv_block_size) — closes the
    # transfer-vs-recompute model fleet-side: with it, the planner can
    # derive each worker's fetch-vs-recompute CROSSOVER DEPTH in tokens
    # (kv_router/scoring.py crossover_tokens) and floor the disagg
    # retune there. Zero on old payloads (crossover then unknowable for
    # that worker — it simply drops out of the fleet median).
    kv_block_size: int = 0
    # runtime/netstore.py client retry counter (bounded jittered retry;
    # a rising rate means the discovery daemon link is flapping)
    netstore_retries_total: int = 0
    # contiguity-aware KV layout (llm/kv/pool.py run-tracking allocator
    # + engine/attention.py run-coalesced DMA; docs/kv_layout.md) — the
    # nv_llm_kv_frag_ratio / _contig_runs / _defrag_moves_total /
    # _attn_dma_copies_per_wave gauge feeds (components/metrics.py
    # "KV layout" Grafana row). Zeros on old payloads.
    kv_frag_ratio: float = 0.0          # 1 - largest_free_run/free
    kv_contig_runs: int = 0             # maximal free runs (1 = coalesced)
    kv_contiguity_ratio: float = 0.0    # adjacency delivered/possible
    kv_defrag_moves_total: int = 0      # blocks migrated by compaction
    attn_dma_copies_per_wave: float = 0.0  # decode DMA issues per wave
    # pipeline parallelism (parallel/pipeline_parallel.py): stage count,
    # per-stage microbatch slots, and the dispatch-level interleave
    # model — steady-state utilization K·pp/(K·pp+pp-1) and its bubble
    # complement — the nv_llm_pp_* gauge feeds (components/metrics.py
    # "Pipeline" Grafana row). Zeros on non-pp engines / old payloads.
    pp_stages: int = 0
    pp_microbatch: int = 0
    pp_utilization: float = 0.0
    pp_bubble_fraction: float = 0.0
    # unified ragged dispatch (engine/ragged.py +
    # docs/ragged_attention.md) — the nv_llm_ragged_* gauge feeds:
    # tokens-per-dispatch fill ratio against the compiled capacity,
    # the fraction of dispatches serving prefill AND decode rows
    # together, and the cumulative split-path dispatches the packing
    # replaced. Zeros on old payloads / non-ragged engines.
    ragged_fill_ratio: float = 0.0
    ragged_mixed_ratio: float = 0.0
    ragged_dispatches_saved_total: int = 0
    # fleet tracing + engine flight recorder (runtime/tracing.py +
    # engine/flight_recorder.py): trace log lines the sampler skipped
    # (nv_llm_trace_dropped_log_lines_total — rising means sampling is
    # active, by design at fleet QPS), and the event-loop lag probe
    # (nv_llm_engine_loop_lag_ms — rising means something is BLOCKING
    # the engine loop: sync I/O, long host glue). Zeros on old payloads.
    trace_dropped_log_lines_total: int = 0
    loop_lag_ms: float = 0.0
    loop_lag_max_ms: float = 0.0
    # ragged takeover round 11 (appended — DL004 append-only evolution):
    # the cross-sequence wave-prefetch hit ratio (first waves whose DMA
    # a predecessor's last wave already started — the host mirror of
    # the kernel's parity chain, attention.ragged_prefetch_counts) and
    # the cumulative draft rows that rode ragged dispatches as spec
    # spans (ragged × speculative decoding). Zeros on old payloads /
    # non-ragged engines.
    ragged_prefetch_hit_ratio: float = 0.0
    ragged_spec_rows_total: int = 0
    # prefill-as-a-service over the native KV dataplane round 12
    # (appended — DL004 append-only evolution): fetches that rode the
    # native data plane vs the base64-over-JSON fallback (llm/kv/
    # fabric.py — a rising fallback rate means peers without the C++
    # toolchain), and the prefix blocks this worker published to the
    # durable object tier as a prefill-publish worker
    # (components/prefill_service.py). Zeros on old payloads.
    remote_dataplane_fetches_total: int = 0
    remote_dataplane_fallbacks_total: int = 0
    prefill_published_blocks_total: int = 0
    # chaos-hardening round 13 (appended — DL004 append-only evolution;
    # docs/chaos.md): the graceful-degradation counters the Grafana
    # "Degradation" row plots. Requests vacated because the client
    # stopped caring (disconnect → KILL → engine sweep) vs because the
    # wire-propagated deadline budget ran out engine-side; netstore
    # calls that burned their whole per-call deadline (a partitioned —
    # not merely flapping — discovery daemon); the fabric circuit
    # breaker's currently-tripped peer count + cumulative trips; and
    # write-behind spill jobs SHED because the disk refused (ENOSPC) —
    # serving continued without them. Zeros on old payloads.
    requests_cancelled_total: int = 0
    requests_deadline_exceeded_total: int = 0
    netstore_deadline_exceeded_total: int = 0
    remote_breaker_open_peers: int = 0
    remote_breaker_trips_total: int = 0
    disk_spill_shed_total: int = 0
    # multi-tenant serving plane round 14 (appended — DL004 append-only
    # evolution; llm/tenancy.py, docs/multi_tenant.md): per-tenant
    # serving stats — {tenant: {admitted, throttled, kv_blocks,
    # hit_rate}} — the nv_llm_tenant_* LABELED gauge feed
    # (components/metrics.py exports one series per tenant). Empty on
    # old payloads / untenanted engines.
    tenant_stats: dict = dataclasses.field(default_factory=dict)
    # streaming layer-wise KV handoff round 15 (appended — DL004
    # append-only evolution; llm/kv/stream.py, docs/kv_fabric.md): the
    # nv_llm_disagg_stream_* gauge feed plus the router's overlap-credit
    # input. Layers this decode worker progressively scattered; stream
    # admissions that degraded (torn frame → monolithic fill, dead
    # stream → cold recompute); the fraction of stream-onboard wall time
    # the engine spent doing hidden work (prep/scatter of arrived
    # layers) rather than exposed waiting on the wire; and the MEASURED
    # streaming depth — the model's layer count once a streamed
    # admission has proven the plane live, 0 before (scoring.
    # network_adjusted_overlap prices the overlapped transfer with it).
    # Zeros on old payloads / non-streaming engines.
    disagg_stream_layers_total: int = 0
    disagg_stream_fallbacks_total: int = 0
    disagg_stream_overlap_ratio: float = 0.0
    disagg_stream_layers: int = 0

    def to_dict(self) -> dict:
        # every field is a scalar; dataclasses.asdict would deep-copy
        # recursively — measurable on the per-second stats publish path
        # at fleet scale (and per-scrape × workers on the planner side)
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, d: dict) -> "ForwardPassMetrics":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass
class KvStoredEvent:
    """Blocks entered a worker's reusable pool. `block_hashes` are chained
    sequence hashes (globally comparable); `tokens_hashes` the local ones."""

    parent_hash: Optional[int]
    block_hashes: List[int]
    tokens_hashes: List[int] = dataclasses.field(default_factory=list)
    lora_id: int = 0
    # which rung of the ladder holds the blocks: "device" (HBM, the
    # historical default — absent in old payloads), "host" (TPU-VM
    # DRAM), "disk" (the persistent G3 store) or "remote" (the G4 fleet
    # fabric — a fetch over a real link away). The router's radix index
    # keeps tier per (worker, hash) and the scheduler discounts colder
    # tiers' overlap depth (kv_router/scoring.py TIER_WEIGHTS) — a
    # disk-resident prefix is worth routing to, but less than an
    # HBM-resident one, and a remote-resident one counts only while the
    # announcing worker's modeled transfer beats its modeled recompute
    # (NetKV network-aware scoring).
    tier: str = "device"


@dataclasses.dataclass
class KvRemovedEvent:
    block_hashes: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class RouterEvent:
    """Worker-tagged KV cache event (reference RouterEvent)."""

    worker_id: int
    event_id: int = 0
    stored: Optional[KvStoredEvent] = None
    removed: Optional[KvRemovedEvent] = None

    def to_dict(self) -> dict:
        d: dict = {"worker_id": self.worker_id, "event_id": self.event_id}
        if self.stored is not None:
            d["stored"] = dataclasses.asdict(self.stored)
        if self.removed is not None:
            d["removed"] = dataclasses.asdict(self.removed)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "RouterEvent":
        ev = cls(worker_id=d["worker_id"], event_id=d.get("event_id", 0))
        if d.get("stored"):
            ev.stored = KvStoredEvent(**d["stored"])
        if d.get("removed"):
            ev.removed = KvRemovedEvent(**d["removed"])
        return ev


@dataclasses.dataclass
class KVHitRateEvent:
    """Emitted by the scheduler per routing decision (reference
    scheduler.rs:28-33); consumed by the metrics component."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int
