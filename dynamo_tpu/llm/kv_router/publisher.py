"""Worker-side publishers: KV cache events + load metrics.

Reference: lib/llm/src/kv_router/publisher.rs:33-137 (`KvEventPublisher`
mpsc → NATS `kv_events`; `KvMetricsPublisher` watch channel behind the
`load_metrics` endpoint) and the C ABI wrapper the reference exposes for
external engines (lib/bindings/c/src/lib.rs:51-297) — our engine is
in-process so the publisher hooks the block pool directly; the C ABI analog
for out-of-process engines lives in csrc/kv_event_abi.cpp.

Transport-agnostic: a `sink` async callable receives each RouterEvent; the
distributed runtime layer plugs in the message-bus publish, tests plug in a
list. Events are buffered through an asyncio queue so the engine loop never
blocks on the network.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from .protocols import (ForwardPassMetrics, KvRemovedEvent, KvStoredEvent,
                        RouterEvent)

logger = logging.getLogger("dynamo_tpu.kv_publisher")

EventSink = Callable[[RouterEvent], Awaitable[None]]


class KvEventPublisher:
    def __init__(self, worker_id: int, sink: Optional[EventSink] = None,
                 max_buffer: int = 8192):
        self.worker_id = worker_id
        self.sink = sink
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_buffer)
        self._task: Optional[asyncio.Task] = None
        self._event_id = 0
        self.dropped = 0

    # engine-side hooks (called synchronously from the engine loop) ---------
    def publish_stored(self, block_id: int, seq_hash: int, tokens_hash: int,
                       parent_hash: Optional[int],
                       tier: str = "device") -> None:
        """``tier`` tags which rung of the KV ladder holds the block
        (device | host | disk) — the router discounts colder tiers'
        overlap depth (kv_router/scoring.py TIER_WEIGHTS)."""
        self._enqueue(RouterEvent(
            worker_id=self.worker_id, event_id=self._next_id(),
            stored=KvStoredEvent(parent_hash=parent_hash,
                                 block_hashes=[seq_hash],
                                 tokens_hashes=[tokens_hash]
                                 if tokens_hash is not None else [],
                                 tier=tier)))

    def publish_removed(self, seq_hashes: list) -> None:
        self._enqueue(RouterEvent(
            worker_id=self.worker_id, event_id=self._next_id(),
            removed=KvRemovedEvent(block_hashes=list(seq_hashes))))

    def _next_id(self) -> int:
        self._event_id += 1
        return self._event_id

    def _enqueue(self, ev: RouterEvent) -> None:
        try:
            self._queue.put_nowait(ev)
        except asyncio.QueueFull:
            self.dropped += 1
            return
        self._ensure_task()

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # no loop (sync test context); events drain later
            self._task = loop.create_task(self._run(), name="kv-event-pub")

    async def _run(self) -> None:
        while True:
            ev = await self._queue.get()
            try:
                if self.sink is not None:
                    await self.sink(ev)
            except Exception:  # noqa: BLE001 — transport boundary
                logger.exception("kv event publish failed (event dropped)")
            finally:
                self._queue.task_done()

    async def drain(self) -> None:
        """Wait until every enqueued event has fully passed the sink (not
        merely left the queue — the last event may still be awaiting inside
        ``sink`` when the queue reads empty)."""
        self._ensure_task()
        await self._queue.join()


class KvMetricsPublisher:
    """Holds the latest ForwardPassMetrics snapshot; the endpoint stats
    handler (and scrapers) read it (reference: watch channel semantics —
    readers always see the newest value, never a backlog)."""

    def __init__(self) -> None:
        self._latest = ForwardPassMetrics()

    def publish(self, metrics: ForwardPassMetrics) -> None:
        self._latest = metrics

    @property
    def latest(self) -> ForwardPassMetrics:
        return self._latest

    def stats_handler(self) -> dict:
        return self._latest.to_dict()
