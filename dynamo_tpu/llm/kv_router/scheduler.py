"""KV scheduler: pick the worker for a request given prefix overlap + load.

Reference: lib/llm/src/kv_router/scheduler.rs:88-316 (`select_worker`). The
cost model re-implemented here keeps the reference's observable behavior:

- cost = alpha * load_deviation + (1 - alpha) * normalized_new_tokens
         + gamma * request_load_ratio
- balance mode: alpha = 0.7 when load_std > 0.1 * load_avg (loads diverging →
  weight load more), else alpha = 0.3 (loads even → weight cache hits more)
- workers with no free request slots are skipped
- optimistic local accounting: the chosen worker's active blocks/slots are
  bumped immediately so back-to-back decisions don't dogpile one worker
  before the next metrics scrape lands
- a KVHitRateEvent is emitted per decision
"""

from __future__ import annotations

import logging
import random
from typing import Callable, Optional

from .protocols import KVHitRateEvent
from .scoring import ProcessedEndpoints

logger = logging.getLogger("dynamo_tpu.kv_scheduler")

GAMMA = 0.2

# indexer ⇄ scheduler would cycle at import time; resolve once on first
# use instead of per call (the routing hot path runs _effective_overlap
# once per candidate per decision)
_LAZY: tuple = ()


def _lazy_imports():
    global _LAZY
    if not _LAZY:
        from .indexer import OverlapScores
        from .scoring import network_adjusted_overlap
        _LAZY = (OverlapScores, network_adjusted_overlap)
    return _LAZY


class KvScheduler:
    def __init__(self, block_size: int,
                 on_hit_rate: Optional[Callable[[KVHitRateEvent], None]] = None,
                 rng: Optional[random.Random] = None):
        self.block_size = block_size
        self.on_hit_rate = on_hit_rate
        self.endpoints = ProcessedEndpoints([])
        self._rng = rng or random.Random(0)
        # optimistic deltas applied on top of the last scrape
        self._opt_blocks: dict = {}
        self._opt_slots: dict = {}
        # multi-tenant accounting (llm/tenancy.py; docs/multi_tenant.md):
        # per-tenant routing decisions + optimistic in-flight slots since
        # the last scrape — the nv_llm_tenant_* gauge feed and the
        # FairShareAdmission gate's contention signal
        self.tenant_admitted: dict = {}
        self._opt_tenant_slots: dict = {}

    def update_endpoints(self, endpoints: ProcessedEndpoints) -> None:
        self.endpoints = endpoints
        self._opt_blocks.clear()
        self._opt_slots.clear()
        self._opt_tenant_slots.clear()

    def fleet_total_slots(self) -> int:
        """Sum of scraped request slots — the FairShareAdmission gate's
        live capacity input (llm/tenancy.py): a tenant's fair-share
        bound tracks scale-out without re-plumbing."""
        return sum(ep.metrics.request_total_slots
                   for ep in self.endpoints.endpoints.values())

    def tenant_counters(self) -> dict:
        """tenant → admitted routing decisions since start (the
        scheduler's half of the nv_llm_tenant_* feed; throttles are
        counted by the admission gate that actually queues)."""
        return dict(self.tenant_admitted)

    def _effective_overlap(self, ep, overlap, fleet_depth: int) -> float:
        """One candidate's overlap credit. With a full OverlapScores in
        hand the credit is NETWORK-AWARE (NetKV): tier-discounted depth,
        with remote-tier blocks kept only when the candidate's modeled
        transfer beats its modeled recompute, plus fabric-fetchable
        credit for blocks other workers hold (scoring.py
        network_adjusted_overlap). A plain dict scores as before.

        (Imports are module-lazy via _lazy_imports(), NOT per-call: this
        runs once per candidate per routing decision — the router's
        hottest loop at fleet scale.)"""
        OverlapScores, network_adjusted_overlap = _lazy_imports()
        if not isinstance(overlap, OverlapScores):
            return overlap.get(ep.worker_id, 0)
        wid = ep.worker_id
        return network_adjusted_overlap(
            weighted=overlap.weighted.get(wid, 0.0),
            own_depth=overlap.scores.get(wid, 0),
            remote_depth=overlap.remote_blocks.get(wid, 0),
            fleet_depth=fleet_depth,
            block_size=self.block_size,
            m=ep.metrics)

    @staticmethod
    def _raw_overlap(overlap, worker_id: int):
        OverlapScores, _ = _lazy_imports()
        if isinstance(overlap, OverlapScores):
            return overlap.scores.get(worker_id, 0)
        return overlap.get(worker_id, 0)

    def schedule(self, isl_tokens: int, overlap_scores,
                 exclude: Optional[set] = None,
                 tenant: Optional[str] = None) -> Optional[int]:
        """Returns the chosen worker id, or None when no worker is usable.
        ``overlap_scores``: an indexer OverlapScores (network-aware
        scoring) or a plain {worker_id: effective_overlap} dict (legacy
        callers). ``exclude``: worker ids barred from NEW admissions
        (the planner's draining set) — skipped like full workers, so a
        drain shifts load instead of dropping requests. ``tenant``
        attributes the decision for per-tenant fair-share accounting
        (llm/tenancy.py FairShareAdmission queues BEFORE this runs —
        placement itself stays tenant-blind so cache affinity is never
        sacrificed to fairness)."""
        OverlapScores, _ = _lazy_imports()
        eps = self.endpoints
        if not len(eps):
            return None
        isl_blocks = max((isl_tokens + self.block_size - 1) // self.block_size,
                         1)
        load_avg = eps.load_avg
        load_std = eps.load_std
        balance_mode = load_std > 0.1 * load_avg
        alpha = 0.7 if balance_mode else 0.3
        fleet_depth = (overlap_scores.fleet_depth
                       if isinstance(overlap_scores, OverlapScores) else 0)

        best_cost = None
        best_worker = None
        candidates = list(eps.endpoints.values())
        self._rng.shuffle(candidates)  # tie-break fairness
        for ep in candidates:
            if exclude and ep.worker_id in exclude:
                continue
            m = ep.metrics
            slots_used = (m.request_active_slots
                          + self._opt_slots.get(ep.worker_id, 0))
            if m.request_total_slots and slots_used >= m.request_total_slots:
                continue  # full worker
            overlap_blocks = min(
                self._effective_overlap(ep, overlap_scores, fleet_depth),
                isl_blocks)
            new_blocks = isl_blocks - overlap_blocks
            normalized_new = new_blocks / isl_blocks
            load = ep.load + self._opt_blocks.get(ep.worker_id, 0)
            # deviation normalized by the fleet average (not stddev — a tiny
            # stddev would explode the term and drown out cache overlap)
            load_dev = (load - load_avg) / max(load_avg, 1.0)
            req_ratio = (slots_used / m.request_total_slots
                         if m.request_total_slots else 0.0)
            cost = (alpha * load_dev + (1 - alpha) * normalized_new
                    + GAMMA * req_ratio)
            if best_cost is None or cost < best_cost:
                best_cost = cost
                best_worker = ep
        if best_worker is None:
            return None
        # optimistic accounting + routing hints use the RAW local depth:
        # the chosen worker's prefill skips exactly the blocks it itself
        # holds (a fabric fetch still allocates device blocks for them)
        overlap_blocks = min(self._raw_overlap(overlap_scores,
                                               best_worker.worker_id),
                             isl_blocks)
        # optimistic accounting until the next metrics scrape
        self._opt_blocks[best_worker.worker_id] = (
            self._opt_blocks.get(best_worker.worker_id, 0)
            + (isl_blocks - overlap_blocks))
        self._opt_slots[best_worker.worker_id] = (
            self._opt_slots.get(best_worker.worker_id, 0) + 1)
        if tenant is not None:
            self.tenant_admitted[tenant] = (
                self.tenant_admitted.get(tenant, 0) + 1)
            self._opt_tenant_slots[tenant] = (
                self._opt_tenant_slots.get(tenant, 0) + 1)
        if self.on_hit_rate is not None:
            # tier-weighted overlap may be fractional; the hit-rate
            # event's contract is whole blocks
            self.on_hit_rate(KVHitRateEvent(
                worker_id=best_worker.worker_id, isl_blocks=isl_blocks,
                overlap_blocks=int(round(overlap_blocks))))
        logger.debug("scheduled worker=%d cost=%.3f overlap=%d/%d alpha=%.1f",
                     best_worker.worker_id, best_cost, overlap_blocks,
                     isl_blocks, alpha)
        return best_worker.worker_id
