"""Endpoint load scoring (reference lib/llm/src/kv_router/scoring.rs:24-55:
`ProcessedEndpoints` — load average/stddev over kv_active_blocks)."""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List

from .protocols import ForwardPassMetrics


@dataclasses.dataclass
class Endpoint:
    worker_id: int
    metrics: ForwardPassMetrics

    @property
    def load(self) -> int:
        return self.metrics.kv_active_blocks


class ProcessedEndpoints:
    def __init__(self, endpoints: List[Endpoint]):
        self.endpoints: Dict[int, Endpoint] = {e.worker_id: e
                                               for e in endpoints}
        loads = [e.load for e in endpoints]
        n = len(loads)
        self.load_avg = sum(loads) / n if n else 0.0
        if n:
            var = sum((x - self.load_avg) ** 2 for x in loads) / n
            self.load_std = math.sqrt(var)
        else:
            self.load_std = 0.0

    @property
    def worker_ids(self) -> List[int]:
        return list(self.endpoints)

    def __len__(self) -> int:
        return len(self.endpoints)
