"""Endpoint load scoring (reference lib/llm/src/kv_router/scoring.rs:24-55:
`ProcessedEndpoints` — load average/stddev over kv_active_blocks) plus the
KV-tier overlap weights and the NetKV-style transfer model: a matched
prefix block is worth less the colder the tier that holds it, because
serving it costs a promote (host h2d scatter, a disk read + scatter, or a
fabric fetch over a real network link) instead of a free HBM reuse — and a
remote block is worth NOTHING when the modeled transfer loses to simply
recomputing it (NetKV, arXiv:2606.03910: score decode instances by
measured transfer cost, not overlap depth alone)."""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

from .protocols import ForwardPassMetrics

# Per-tier overlap discount (the indexer tags each (worker, hash) with
# the announcing event's tier; KvIndexer.find_matches applies these).
# device = free HBM reuse; host = one DRAM→HBM scatter (~the +40% TTFT
# win's cost side); disk = a file read + scatter — still far cheaper
# than recomputing the prefix, hence > 0; remote = a fabric fetch (peer
# RPC or object-store read) + scatter — the coldest rung that still
# beats recompute WHEN the link pays (the scheduler additionally gates
# remote credit on the transfer model below).
#
# Runtime-configurable: `llmctl kv set-weights` writes the kvtier/weights
# key and every watching worker/router applies it live via
# set_tier_weights() — the dict is mutated IN PLACE so module importers
# see the change without re-importing.
TIER_WEIGHTS: Dict[str, float] = {"device": 1.0, "host": 0.8, "disk": 0.5,
                                  "remote": 0.25}
_DEFAULT_TIER_WEIGHTS: Dict[str, float] = dict(TIER_WEIGHTS)


def set_tier_weights(weights: Dict[str, float]) -> Dict[str, float]:
    """Apply a (partial) weight override live (llmctl kv set-weights →
    kvtier/weights/{ns} → admin.watch_weights_loop). Unknown tiers are
    ignored; values clamp to [0, 1] (an overlap block can never be worth
    more than a device-resident one). Returns the effective table."""
    for k, v in weights.items():
        if k in TIER_WEIGHTS and v is not None:
            TIER_WEIGHTS[k] = min(max(float(v), 0.0), 1.0)
    return dict(TIER_WEIGHTS)


def reset_tier_weights() -> None:
    """Restore the defaults (test isolation)."""
    TIER_WEIGHTS.update(_DEFAULT_TIER_WEIGHTS)


def tier_weighted_depth(depth: int, tiers: Sequence[str]) -> float:
    """Effective overlap of one worker's ``depth`` leading matched blocks
    given each block's tier tag (entries beyond ``tiers`` default to
    device)."""
    total = 0.0
    for i in range(depth):
        tier = tiers[i] if i < len(tiers) else "device"
        total += TIER_WEIGHTS.get(tier, 1.0)
    return total


# ---------------------------------------------------------------------------
# NetKV transfer model: would moving the blocks beat recomputing them?
# The inputs ride ForwardPassMetrics — each worker publishes its measured
# fabric link (remote_link_gbps / remote_link_rtt_s, decay-averaged by
# llm/kv/fabric.PeerLinkTable), its KV wire density (kv_bytes_per_block)
# and its measured prefill rate (prefill_tok_per_s) — so the ROUTER
# prices a candidate's fetch with the candidate's own numbers.
# ---------------------------------------------------------------------------


def modeled_transfer_s(n_blocks: int, bytes_per_block: int, gbps: float,
                       rtt_s: float) -> float:
    """Modeled wall time to move ``n_blocks`` of KV over a link."""
    if gbps <= 0:
        return float("inf")
    return rtt_s + n_blocks * bytes_per_block / (gbps * 1e9)


def modeled_overlap_transfer_s(n_blocks: int, bytes_per_block: int,
                               gbps: float, rtt_s: float, n_layers: int,
                               hidden_s: float = 0.0) -> float:
    """Modeled EXPOSED wall time of the same move when the receiver
    consumes it as a per-layer stream (llm/kv/stream.py): scatter of
    layer l overlaps the wire time of layer l+1, so only
    max(serial/L, serial − hidden) sits on the critical path. A worker
    that published ``disagg_stream_layers == 0`` (monolithic consumer /
    old payload) is priced via n_layers ≤ 1, which degrades to
    modeled_transfer_s exactly."""
    if gbps <= 0:
        return float("inf")
    from ..kv.stream import exposed_transfer_s
    serial = n_blocks * bytes_per_block / (gbps * 1e9)
    return rtt_s + exposed_transfer_s(serial, n_layers, hidden_s)


def modeled_recompute_s(n_blocks: int, block_size: int,
                        prefill_tok_per_s: float) -> float:
    """Modeled wall time to re-prefill ``n_blocks`` worth of tokens.
    inf when the rate is unknown (no prefill measured yet) — transfer
    then wins by default, matching the fabric's optimistic admission."""
    if prefill_tok_per_s <= 0:
        return float("inf")
    return n_blocks * block_size / prefill_tok_per_s


def transfer_pays(n_blocks: int, block_size: int,
                  m: "ForwardPassMetrics") -> bool:
    """Does fetching ``n_blocks`` to the worker described by ``m`` beat
    recomputing them there? False when the worker has no fabric link."""
    if n_blocks <= 0 or m.remote_link_gbps <= 0 or m.kv_bytes_per_block <= 0:
        return False
    t = modeled_transfer_s(n_blocks, m.kv_bytes_per_block,
                           m.remote_link_gbps, m.remote_link_rtt_s)
    r = modeled_recompute_s(n_blocks, block_size, m.prefill_tok_per_s)
    return t < r


def network_adjusted_overlap(weighted: float, own_depth: int,
                             remote_depth: int, fleet_depth: int,
                             block_size: int,
                             m: "ForwardPassMetrics") -> float:
    """NetKV scoring for ONE candidate: tier-discounted overlap minus
    modeled transfer cost, in block units.

    - ``remote_depth`` matched blocks sit in the candidate's REMOTE tier
      (a fabric fetch away). Their TIER_WEIGHTS["remote"] credit stands
      only when the candidate's modeled transfer beats its modeled
      recompute — the router prefers the holder only when the fetch
      pays; otherwise those blocks are priced exactly like a miss.
    - ``fleet_depth - own_depth`` blocks exist elsewhere in the fleet;
      a fabric-attached candidate can fetch them, so they earn remote
      credit scaled by the modeled saving fraction (1 - transfer /
      recompute): a near-free link earns almost full remote weight, a
      barely-winning link earns almost nothing.
    """
    w_remote = TIER_WEIGHTS.get("remote", 0.0)
    eff = weighted
    if remote_depth > 0 and not transfer_pays(remote_depth, block_size, m):
        eff -= remote_depth * w_remote
    extra = fleet_depth - own_depth
    if extra > 0 and m.remote_link_gbps > 0 and m.kv_bytes_per_block > 0:
        # transfer_pays inlined so the t/r the saving needs aren't
        # modeled twice — this runs once per candidate per routing
        # decision, the router's hottest loop at fleet scale. A
        # candidate whose streaming plane has proven live (it published
        # a MEASURED disagg_stream_layers > 0) is priced at the exposed
        # overlapped transfer, not the serial one — streaming consumers
        # earn more fetch credit because their fetch costs less.
        layers = max(int(getattr(m, "disagg_stream_layers", 0) or 0), 1)
        t = modeled_overlap_transfer_s(extra, m.kv_bytes_per_block,
                                       m.remote_link_gbps,
                                       m.remote_link_rtt_s, layers)
        r = modeled_recompute_s(extra, block_size, m.prefill_tok_per_s)
        if t < r:
            saving = 1.0 if math.isinf(r) else max(1.0 - t / r, 0.0)
            eff += extra * w_remote * saving
    return max(eff, 0.0)


# ---------------------------------------------------------------------------
# Fleet-level fetch-vs-recompute crossover (ROADMAP KV-fabric item (c),
# second half): the planner's disagg retune consumes the fleet's
# aggregate crossover depth — there is no point pushing the disagg
# threshold BELOW the depth at which moving KV across the fabric starts
# beating recompute, because a remote prefill's payoff rides the same
# link economics the per-worker AdmissionGate prices.
# ---------------------------------------------------------------------------


def crossover_tokens(m: dict) -> Optional[float]:
    """One worker's fetch-vs-recompute crossover depth in TOKENS, from
    its published ForwardPassMetrics dict: the depth where
    rtt + tokens·(bytes_per_block/block_size)/bw  ==  tokens/rate.

    Returns None when the worker's inputs are absent (no fabric, old
    payload, rate still unknown) and +inf when its link NEVER beats
    recompute (per-token transfer >= per-token recompute)."""
    rate = float(m.get("prefill_tok_per_s", 0) or 0)
    gbps = float(m.get("remote_link_gbps", 0) or 0)
    bpb = float(m.get("kv_bytes_per_block", 0) or 0)
    bs = float(m.get("kv_block_size", 0) or 0)
    rtt = float(m.get("remote_link_rtt_s", 0) or 0)
    if rate <= 0 or gbps <= 0 or bpb <= 0 or bs <= 0:
        return None
    # a worker whose streaming handoff plane has proven live publishes
    # its measured pipeline depth (disagg_stream_layers); its exposed
    # per-token transfer is 1/L of the serial cost (llm/kv/stream.py),
    # so its crossover sits shallower. 0 (old payload / monolithic
    # consumer) prices serially — identical to the pre-streaming model.
    layers = max(int(m.get("disagg_stream_layers", 0) or 0), 1)
    per_tok_gain = 1.0 / rate - bpb / (bs * gbps * 1e9) / layers
    if per_tok_gain <= 0:
        return math.inf
    return rtt / per_tok_gain


def fleet_crossover_tokens(stats: Dict[int, dict]) -> Optional[float]:
    """Median per-worker crossover depth across the scraped fleet — the
    robust aggregate the planner's disagg retune floors at. None when no
    worker published usable inputs."""
    vals = sorted(v for v in (crossover_tokens(m) for m in stats.values())
                  if v is not None)
    if not vals:
        return None
    return vals[len(vals) // 2]


@dataclasses.dataclass
class Endpoint:
    worker_id: int
    metrics: ForwardPassMetrics

    @property
    def load(self) -> int:
        return self.metrics.kv_active_blocks


class ProcessedEndpoints:
    def __init__(self, endpoints: List[Endpoint]):
        self.endpoints: Dict[int, Endpoint] = {e.worker_id: e
                                               for e in endpoints}
        loads = [e.load for e in endpoints]
        n = len(loads)
        self.load_avg = sum(loads) / n if n else 0.0
        if n:
            var = sum((x - self.load_avg) ** 2 for x in loads) / n
            self.load_std = math.sqrt(var)
        else:
            self.load_std = 0.0

    @property
    def worker_ids(self) -> List[int]:
        return list(self.endpoints)

    def __len__(self) -> int:
        return len(self.endpoints)
