"""Endpoint load scoring (reference lib/llm/src/kv_router/scoring.rs:24-55:
`ProcessedEndpoints` — load average/stddev over kv_active_blocks) plus the
KV-tier overlap weights: a matched prefix block is worth less the colder
the tier that holds it, because serving it costs a promote (host h2d
scatter, or a disk read + h2d scatter) instead of a free HBM reuse."""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Sequence

from .protocols import ForwardPassMetrics

# Per-tier overlap discount (the indexer tags each (worker, hash) with
# the announcing event's tier; KvIndexer.tier_weighted applies these).
# device = free HBM reuse; host = one DRAM→HBM scatter (~the +40% TTFT
# win's cost side); disk = a file read + scatter — still far cheaper
# than recomputing the prefix, hence > 0.
TIER_WEIGHTS: Dict[str, float] = {"device": 1.0, "host": 0.8, "disk": 0.5}


def tier_weighted_depth(depth: int, tiers: Sequence[str]) -> float:
    """Effective overlap of one worker's ``depth`` leading matched blocks
    given each block's tier tag (entries beyond ``tiers`` default to
    device)."""
    total = 0.0
    for i in range(depth):
        tier = tiers[i] if i < len(tiers) else "device"
        total += TIER_WEIGHTS.get(tier, 1.0)
    return total


@dataclasses.dataclass
class Endpoint:
    worker_id: int
    metrics: ForwardPassMetrics

    @property
    def load(self) -> int:
        return self.metrics.kv_active_blocks


class ProcessedEndpoints:
    def __init__(self, endpoints: List[Endpoint]):
        self.endpoints: Dict[int, Endpoint] = {e.worker_id: e
                                               for e in endpoints}
        loads = [e.load for e in endpoints]
        n = len(loads)
        self.load_avg = sum(loads) / n if n else 0.0
        if n:
            var = sum((x - self.load_avg) ** 2 for x in loads) / n
            self.load_std = math.sqrt(var)
        else:
            self.load_std = 0.0

    @property
    def worker_ids(self) -> List[int]:
        return list(self.endpoints)

    def __len__(self) -> int:
        return len(self.endpoints)
