"""Native SentencePiece inference engine (no `sentencepiece` dependency).

The reference ships a real SentencePiece tokenizer kind
(lib/llm/src/tokenizers/sp.rs:1-109, via the sentencepiece crate); this
image has no `sentencepiece` package and no egress to fetch one, so the
tokenizer would otherwise stay import-gated with no runnable test
(VERDICT r3 missing #5). This module is a clean-room implementation of
the INFERENCE side of a unigram SentencePiece model:

- a minimal protobuf wire-format reader/writer for the subset of
  `sentencepiece_model.proto` inference needs (ModelProto.pieces with
  piece/score/type; TrainerSpec unk/bos/eos/pad ids + byte_fallback;
  NormalizerSpec.add_dummy_prefix) — field numbers match the public
  .proto, so real sentencepiece-produced models load here and models
  written here load in real sentencepiece;
- Viterbi (max-score) unigram segmentation with byte-fallback for
  out-of-vocab characters;
- decode with byte-piece reassembly (incomplete UTF-8 surfaces as the
  replacement character, which is exactly what the incremental
  DecodeStream's hold-until-complete logic keys on).

Training is out of scope (the serving framework only loads models).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = ["NativeSentencePiece", "write_model_proto"]

# sentencepiece_model.proto piece types
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6

_SPACE = "▁"          # ▁ — SP's escaped whitespace


# --------------------------------------------------------------- proto wire

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _signed(v: int) -> int:
    """int32/int64 fields ride varints as two's complement 64-bit."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message's bytes."""
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            n, i = _read_varint(buf, i)
            v, i = buf[i:i + n], i + n
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


def _emit_varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _emit_field(field: int, wt: int, payload: bytes) -> bytes:
    return _emit_varint((field << 3) | wt) + payload


def write_model_proto(pieces: List[Tuple[str, float, int]], *,
                      unk_id: int = 0, bos_id: int = 1, eos_id: int = 2,
                      pad_id: int = -1, byte_fallback: bool = True,
                      add_dummy_prefix: bool = True) -> bytes:
    """Serialize a loadable .model (ModelProto). Field numbers follow the
    public sentencepiece_model.proto so real sentencepiece reads the
    output; used by the committed fixture generator and the roundtrip
    tests."""
    out = bytearray()
    for piece, score, ptype in pieces:
        body = (_emit_field(1, 2, _emit_varint(len(piece.encode()))
                            + piece.encode())
                + _emit_field(2, 5, struct.pack("<f", score))
                + _emit_field(3, 0, _emit_varint(ptype)))
        out += _emit_field(1, 2, _emit_varint(len(body)) + body)
    trainer = (_emit_field(35, 0, _emit_varint(int(byte_fallback)))
               + _emit_field(40, 0, _emit_varint(unk_id))
               + _emit_field(41, 0, _emit_varint(bos_id))
               + _emit_field(42, 0, _emit_varint(eos_id))
               + _emit_field(43, 0, _emit_varint(pad_id)))
    out += _emit_field(2, 2, _emit_varint(len(trainer)) + trainer)
    norm = _emit_field(3, 0, _emit_varint(int(add_dummy_prefix)))
    out += _emit_field(3, 2, _emit_varint(len(norm)) + norm)
    return bytes(out)


# ----------------------------------------------------------------- engine

class NativeSentencePiece:
    """Drop-in for the `sentencepiece.SentencePieceProcessor` surface the
    framework uses (EncodeAsIds/DecodeIds/IdToPiece/PieceToId/
    GetPieceSize/bos_id/eos_id/pad_id)."""

    def __init__(self, pieces: List[Tuple[str, float, int]], *,
                 unk_id: int, bos_id: int, eos_id: int, pad_id: int,
                 byte_fallback: bool, add_dummy_prefix: bool):
        self._pieces = pieces
        self._unk, self._bos, self._eos, self._pad = (unk_id, bos_id,
                                                      eos_id, pad_id)
        self._byte_fallback = byte_fallback
        self._dummy_prefix = add_dummy_prefix
        self._by_piece: Dict[str, int] = {
            p: i for i, (p, _, t) in enumerate(pieces) if t != UNUSED}
        self._byte_ids: Dict[int, int] = {}
        for i, (p, _, t) in enumerate(pieces):
            if t == BYTE and len(p) == 6 and p.startswith("<0x"):
                self._byte_ids[int(p[3:5], 16)] = i
        self._max_piece = max((len(p) for p, _, t in pieces
                               if t in (NORMAL, USER_DEFINED)), default=1)
        scores = [s for _, s, t in pieces if t in (NORMAL, USER_DEFINED)]
        # real SP scores unknowns below every vocab piece
        self._unk_score = (min(scores) if scores else 0.0) - 10.0

    # ------------------------------------------------------------- loading
    @classmethod
    def load(cls, path: str) -> "NativeSentencePiece":
        with open(path, "rb") as f:
            buf = f.read()
        pieces: List[Tuple[str, float, int]] = []
        unk_id, bos_id, eos_id, pad_id = 0, 1, 2, -1
        byte_fallback = False
        add_dummy_prefix = True
        for field, wt, v in _fields(buf):
            if field == 1 and wt == 2:                 # SentencePiece
                piece, score, ptype = "", 0.0, NORMAL
                for f2, wt2, v2 in _fields(v):
                    if f2 == 1:
                        piece = v2.decode("utf-8")
                    elif f2 == 2:
                        score = struct.unpack("<f", v2)[0]
                    elif f2 == 3:
                        ptype = v2
                pieces.append((piece, score, ptype))
            elif field == 2 and wt == 2:               # TrainerSpec
                for f2, _wt2, v2 in _fields(v):
                    if f2 == 35:
                        byte_fallback = bool(v2)
                    elif f2 == 40:
                        unk_id = _signed(v2)
                    elif f2 == 41:
                        bos_id = _signed(v2)
                    elif f2 == 42:
                        eos_id = _signed(v2)
                    elif f2 == 43:
                        pad_id = _signed(v2)
            elif field == 3 and wt == 2:               # NormalizerSpec
                for f2, _wt2, v2 in _fields(v):
                    if f2 == 3:
                        add_dummy_prefix = bool(v2)
        if not pieces:
            raise ValueError(f"no pieces in sentencepiece model {path!r}")
        return cls(pieces, unk_id=unk_id, bos_id=bos_id, eos_id=eos_id,
                   pad_id=pad_id, byte_fallback=byte_fallback,
                   add_dummy_prefix=add_dummy_prefix)

    # ------------------------------------------------------------ encoding
    def _normalize(self, text: str) -> str:
        if self._dummy_prefix:
            text = " " + text
        return text.replace(" ", _SPACE)

    def EncodeAsIds(self, text: str) -> List[int]:  # noqa: N802 — spm API
        s = self._normalize(text)
        n = len(s)
        # Viterbi over character positions: best[i] = (score, back, ids)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, List[int]]]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == NEG:
                continue
            matched = False
            for ln in range(1, min(self._max_piece, n - i) + 1):
                pid = self._by_piece.get(s[i:i + ln])
                if pid is None:
                    continue
                _, score, ptype = self._pieces[pid]
                if ptype not in (NORMAL, USER_DEFINED):
                    continue
                matched = True
                cand = best[i] + score
                if cand > best[i + ln]:
                    best[i + ln] = cand
                    back[i + ln] = (i, [pid])
            if not matched or best[i + 1] == NEG:
                # out-of-vocab char: byte fallback, else <unk>
                ch = s[i]
                if self._byte_fallback and self._byte_ids:
                    ids = [self._byte_ids[b] for b in ch.encode("utf-8")]
                    score = sum(self._pieces[j][1] for j in ids)
                else:
                    ids = [self._unk]
                    score = self._unk_score
                cand = best[i] + score
                if cand > best[i + 1]:
                    best[i + 1] = cand
                    back[i + 1] = (i, ids)
        ids: List[int] = []
        i = n
        while i > 0:
            prev, seg = back[i]
            ids[:0] = seg
            i = prev
        return ids

    # ------------------------------------------------------------ decoding
    def DecodeIds(self, ids: Sequence[int]) -> str:  # noqa: N802
        out = bytearray()
        for tid in ids:
            if not 0 <= tid < len(self._pieces):
                continue
            piece, _, ptype = self._pieces[tid]
            if ptype in (CONTROL, UNUSED):
                continue
            if ptype == BYTE:
                out.append(int(piece[3:5], 16))
            elif ptype == UNKNOWN:
                out += " ⁇ ".encode()     # SP's default unk surface
            else:
                out += piece.encode("utf-8")
        text = out.decode("utf-8", errors="replace").replace(_SPACE, " ")
        if self._dummy_prefix and text.startswith(" "):
            text = text[1:]
        return text

    def IdToPiece(self, token_id: int) -> str:  # noqa: N802
        if not 0 <= token_id < len(self._pieces):
            raise IndexError(token_id)
        return self._pieces[token_id][0]

    def PieceToId(self, piece: str) -> int:  # noqa: N802
        return self._by_piece.get(piece, self._unk)

    def GetPieceSize(self) -> int:  # noqa: N802
        return len(self._pieces)

    def bos_id(self) -> int:
        return self._bos

    def eos_id(self) -> int:
        return self._eos

    def pad_id(self) -> int:
        return self._pad
