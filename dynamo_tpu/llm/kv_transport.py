"""Device-to-device KV bulk plane for PD disaggregation.

The reference moves KV blocks engine-to-engine with NIXL RDMA so the
handoff never touches host Python (vLLM patch nixl.py `read_blocks` /
`write_blocks`; SURVEY.md §5.8 names this THE transfer to replace). The
TPU-native equivalent exploits JAX's single-controller model: same-host
disagg runs BOTH engines in one process on disjoint device subsets (e.g.
a v5e-8 split 4+4 — BASELINE config 3), so the bulk handoff is a
`jax.device_put` of the gathered block stack from the prefill engine's
devices to the decode engine's devices/sharding — a pure ICI transfer
scheduled on the device streams, never staged through host numpy. The
TCP wire path (llm/disagg.py) remains the cross-host/DCN fallback.

TP-reshard on handoff falls out of the same `device_put`: the stacked
blocks [L, n, bs, KVH*Dh] are placed under the decode mesh's KV pspec
(last axis over "tp"), so XLA performs the reshard collective — the
analog of the reference's `permute_scatter_memcpy` (block_copy.cu:558).

Rendezvous: the decode side registers a sink future keyed by request id
before enqueueing the prefill work and advertises this process's token in
`RemotePrefillRequest.device_bridge`; a prefill worker in the same
process deposits the device payload here and sends only a tiny control
frame over the response plane (keeping the existing timeout/fallback
control flow). Everything else falls back to the wire path.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("dynamo_tpu.llm.kv_transport")

__all__ = ["PROC_TOKEN", "DeviceKvPayload", "DeviceKvBridge", "bridge",
           "scatter_blocks_device"]

# identity of this process's bridge: a prefill worker seeing this token in
# a request knows the decode engine shares its jax runtime
PROC_TOKEN = uuid.uuid4().hex


@dataclasses.dataclass
class DeviceKvPayload:
    """KV handoff that never left the devices: stacked block-major gather
    output straight from the prefill engine's pool."""

    request_id: str
    first_token: object             # int OR device scalar (never fetched on
    first_logprob: object           # the prefill side — saves a round-trip)
    seq_hashes: List[int]
    stacked: Dict[str, jax.Array]   # {"k": [L, n_padded, bs, KVH*Dh], "v"}
    n_blocks: int                   # valid blocks (rest is pow2 padding)
    block_size: int


class DeviceKvBridge:
    """In-process rendezvous: decode registers a sink, prefill deposits.

    Follower ranks of a multihost decode engine have no asyncio sink —
    their prefill-engine replica ``park``s its shard of the payload and
    the dispatch-stream consumer ``take_parked``s it when the leader's
    "precomputed_device_admit" event arrives (multihost.run_follower)."""

    # parked payloads pin gathered KV in HBM; entries whose decode
    # admission never arrives (cancelled decodes, failed requests) are
    # evicted by AGE, not count — a count cap would evict LIVE in-flight
    # shards under bursty load and crash the follower when the admission
    # later arrived. PARK_TTL_S is far beyond any decode-side disagg
    # timeout (llm/disagg.py send_timeout), so an entry this old can
    # never be legitimately claimed.
    PARK_TTL_S = 300.0

    def __init__(self) -> None:
        import threading
        self._sinks: Dict[str, asyncio.Future] = {}
        # rid → (payload, park time); guarded by _park_lock — park and
        # take_parked are called from DIFFERENT follower threads (the
        # prefill-engine consumer parks, the decode-engine consumer
        # claims)
        self._parked: "OrderedDict[str, tuple]" = OrderedDict()
        self._park_lock = threading.Lock()
        self.deposits = 0
        self.misses = 0
        self.park_evictions = 0

    def register(self, request_id: str) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._sinks[request_id] = fut
        return fut

    def deposit(self, request_id: str, payload: DeviceKvPayload) -> bool:
        """True if a sink was waiting (decode will take the device path)."""
        fut = self._sinks.pop(request_id, None)
        if fut is None or fut.done():
            self.misses += 1
            return False
        fut.set_result(payload)
        self.deposits += 1
        return True

    def cancel(self, request_id: str) -> None:
        fut = self._sinks.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    def park(self, request_id: str, payload: DeviceKvPayload) -> None:
        """Follower-rank deposit: hold this rank's shard of the payload
        until the leader's admission event claims it."""
        import time as _time
        now = _time.monotonic()
        with self._park_lock:
            self._parked[request_id] = (payload, now)
            self.deposits += 1
            while self._parked:
                rid, (_, t) = next(iter(self._parked.items()))
                if now - t <= self.PARK_TTL_S:
                    break
                self._parked.popitem(last=False)
                self.park_evictions += 1
                logger.warning(
                    "evicting parked device payload rid=%s (unclaimed "
                    "for >%ss) — its decode admission never arrived",
                    rid, self.PARK_TTL_S)

    def take_parked(self, request_id: str) -> Optional[DeviceKvPayload]:
        with self._park_lock:
            got = self._parked.pop(request_id, None)
        return got[0] if got is not None else None


# constructed at import (the module import lock makes this thread-safe):
# on a follower rank the FIRST callers are two different threads — the
# prefill-engine consumer parking and the decode-engine consumer claiming
# — and a lazy check-then-set could hand each its own instance
_BRIDGE: DeviceKvBridge = DeviceKvBridge()


def bridge() -> DeviceKvBridge:
    return _BRIDGE


def _stacked_kv_sharding(mesh, key: str):
    """The pool pspec (parallel/sharding.kv_pspecs, [L, NTOK, C]) lifted to
    the stacked-blocks rank [L, n, bs, C]: the block axis is new and
    unsharded, bs inherits the (unsharded) token axis, C keeps its axes —
    derived, not duplicated, so a pool-layout change can't silently
    diverge the device plane's placement. Keys without a pspec (the MLA
    latent "kv" pool) replicate, matching shard_kv's fallback."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import kv_pspecs
    s = kv_pspecs().get(key, P())
    if s == P():
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(s[0], None, s[1], s[2]))


def scatter_blocks_device(kv, target_ids, payload: DeviceKvPayload,
                          skip_blocks: int, n_needed: int, mesh=None):
    """Scatter a device payload's blocks [skip_blocks:n_needed] into this
    engine's pool at `target_ids`, moving the values device-to-device
    (ICI) — and resharding under `mesh`'s KV layout when given — without
    host staging. Returns the new (donated-in-place) cache."""
    from jax.sharding import NamedSharding

    from ..engine.block_copy import _pad_pow2, scatter_blocks

    vals = {k: v[:, skip_blocks:n_needed]
            for k, v in payload.stacked.items()}
    pool_sharding = next(iter(kv.values())).sharding
    if mesh is not None:
        target = {k: _stacked_kv_sharding(mesh, k) for k in vals}
    elif isinstance(pool_sharding, NamedSharding):
        target = {k: _stacked_kv_sharding(pool_sharding.mesh, k)
                  for k in vals}
    else:
        # single-device pool: its placement applies rank-agnostically
        target = {k: pool_sharding for k in vals}
    # the cross-engine (and cross-mesh) hop: device→device over ICI
    vals = {k: jax.device_put(v, target[k]) for k, v in vals.items()}
    n = n_needed - skip_blocks
    pad = _pad_pow2(n) - n
    ids = list(target_ids) + [0] * pad     # pad scatters hit trash block 0
    if pad:
        vals = {k: jnp.concatenate(
            [v, jnp.zeros((v.shape[0], pad) + v.shape[2:], v.dtype)], axis=1)
            for k, v in vals.items()}
    return scatter_blocks(kv, jnp.asarray(np.asarray(ids, np.int32)),
                          vals, payload.block_size)
