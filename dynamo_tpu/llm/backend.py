"""Backend operator: incremental detokenization + stop handling ("jail").

Reference: `Backend` (lib/llm/src/backend.rs:66-247) and its
`Decoder`/`StopTrigger`/`StepResult` machinery (backend.rs:253-507). Sits
between the preprocessor and the engine: the engine emits raw token ids; this
operator turns them into UTF-8-safe text deltas, watches for stop sequences
(holding back — "jailing" — text that might be the prefix of a stop string so
it is never surfaced), recognizes hidden stop tokens (model EOS ids, which
produce no text), and converts all of that into finish reasons. When a stop
triggers, it calls `ctx.stop_generating()` so the engine halts at the next
step boundary (TPU engines can only cancel between dispatched steps).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import AsyncIterator, List, Optional, Sequence

from ..runtime.engine import AsyncEngine, ManyOut, ResponseStream, SingleIn
from ..runtime.pipeline import Operator
from .model_card import ModelDeploymentCard
from .protocols.annotated import Annotated
from .protocols.common import BackendOutput, FinishReason, PreprocessedRequest


class StopTrigger(enum.Enum):
    """Why the decoder declared the stream finished (reference backend.rs)."""

    HIDDEN_STOP_TOKEN = "hidden_stop_token"
    STOP_SEQUENCE = "stop_sequence"
    MAX_TOKENS = "max_tokens"

    def finish_reason(self) -> FinishReason:
        if self is StopTrigger.HIDDEN_STOP_TOKEN:
            return FinishReason.EOS
        if self is StopTrigger.STOP_SEQUENCE:
            return FinishReason.STOP
        return FinishReason.LENGTH


@dataclasses.dataclass
class StepResult:
    """Outcome of feeding one token to the decoder."""

    text: Optional[str] = None
    stop_trigger: Optional[StopTrigger] = None

    @property
    def is_complete(self) -> bool:
        return self.stop_trigger is not None


def _longest_jail_suffix(buffer: str, stops: Sequence[str]) -> int:
    """Length of the longest suffix of `buffer` that is a proper prefix of any
    stop sequence — that many chars must stay jailed. (The reference uses a
    galil-seiferas search over a bounded jail, backend.rs:253+; for serving-
    sized stop lists a direct scan is equivalent and simpler.)"""
    best = 0
    for stop in stops:
        limit = min(len(buffer), len(stop) - 1)
        for k in range(limit, 0, -1):
            if buffer[-k:] == stop[:k]:
                best = max(best, k)
                break
    return best


class Decoder:
    """Per-request decode state: incremental detok + stop-jail.

    Reference `Decoder` (backend.rs:253-507).
    """

    def __init__(self, tokenizer, stop_sequences: Optional[List[str]] = None,
                 hidden_stop_ids: Optional[List[int]] = None,
                 max_tokens: Optional[int] = None,
                 min_tokens: Optional[int] = None,
                 skip_special_tokens: bool = True):
        self._stream = tokenizer.decode_stream(skip_special_tokens=skip_special_tokens)
        self._stops = [s for s in (stop_sequences or []) if s]
        self._hidden = set(hidden_stop_ids or [])
        self._max_tokens = max_tokens
        self._min_tokens = min_tokens or 0
        self._jail = ""
        self._emitted_tokens = 0

    def step(self, token_id: int) -> StepResult:
        self._emitted_tokens += 1
        past_min = self._emitted_tokens > self._min_tokens
        if token_id in self._hidden and past_min:
            # Hidden stop tokens never surface text (backend.rs hidden stops);
            # jailed text stays hidden too (it may be a partial stop prefix).
            self._discard_jail()
            return StepResult(text=None,
                              stop_trigger=StopTrigger.HIDDEN_STOP_TOKEN)
        delta = self._stream.step(token_id)
        text_out: Optional[str] = None
        trigger: Optional[StopTrigger] = None
        if delta:
            self._jail += delta
            if self._stops:
                hit_pos = -1
                for stop in self._stops:
                    pos = self._jail.find(stop)
                    if pos != -1 and (hit_pos == -1 or pos < hit_pos):
                        hit_pos = pos
                if hit_pos != -1 and past_min:
                    # Emit text before the stop match; swallow the rest.
                    text_out = self._jail[:hit_pos] or None
                    self._jail = ""
                    return StepResult(text=text_out,
                                      stop_trigger=StopTrigger.STOP_SEQUENCE)
                keep = _longest_jail_suffix(self._jail, self._stops)
                if keep:
                    text_out = self._jail[:-keep] or None
                    self._jail = self._jail[-keep:]
                else:
                    text_out = self._jail or None
                    self._jail = ""
            else:
                text_out = self._jail or None
                self._jail = ""
        if (self._max_tokens is not None
                and self._emitted_tokens >= self._max_tokens):
            trigger = StopTrigger.MAX_TOKENS
            self._discard_jail()
        return StepResult(text=text_out, stop_trigger=trigger)

    def _discard_jail(self) -> None:
        # A partial stop-sequence prefix at end-of-stream stays hidden
        # (reference: jailed text is only released when disambiguated).
        self._jail = ""

    @property
    def emitted_tokens(self) -> int:
        return self._emitted_tokens


class Backend(Operator):
    """The detokenizing operator (reference `Backend`, backend.rs:66-247)."""

    def __init__(self, mdc: ModelDeploymentCard, validate_engine_decode: bool = False):
        self.mdc = mdc
        self.tokenizer = mdc.tokenizer()
        self.validate_engine_decode = validate_engine_decode

    async def generate(self, request: SingleIn, next_engine: AsyncEngine) -> ManyOut:
        pre: PreprocessedRequest = request.data
        sc = pre.stop_conditions
        decoder = Decoder(
            self.tokenizer,
            stop_sequences=sc.stop,
            hidden_stop_ids=sc.stop_token_ids_hidden,
            max_tokens=sc.max_tokens,
            min_tokens=sc.min_tokens,
            skip_special_tokens=pre.output_options.skip_special_tokens,
        )
        downstream = await next_engine.generate(request)
        ctx = request.ctx

        async def backward() -> AsyncIterator[Annotated[BackendOutput]]:
            finished = False
            async for item in downstream:
                ann = item if isinstance(item, Annotated) else Annotated.from_data(item)
                if ann.data is None:
                    yield ann
                    continue
                out: BackendOutput = ann.data
                texts: List[str] = []
                trigger: Optional[StopTrigger] = None
                consumed: List[int] = []
                for tid in out.token_ids:
                    consumed.append(tid)
                    res = decoder.step(tid)
                    if res.text:
                        texts.append(res.text)
                    if res.is_complete:
                        trigger = res.stop_trigger
                        break
                new = BackendOutput(
                    # truncate to what the decoder consumed so usage
                    # accounting matches the visible completion
                    token_ids=consumed,
                    text="".join(texts) if texts else None,
                    cum_log_probs=out.cum_log_probs,
                    log_probs=out.log_probs,
                    top_logprobs=out.top_logprobs,
                    tokens=out.tokens,
                    finish_reason=out.finish_reason,
                )
                if self.validate_engine_decode and out.text is not None:
                    if new.text != out.text:
                        ann.comment = (ann.comment or []) + [
                            f"detok mismatch: engine={out.text!r} local={new.text!r}"]
                if trigger is not None:
                    new.finish_reason = trigger.finish_reason()
                    finished = True
                    # Step-granular cancellation: tell the engine to halt.
                    ctx.stop_generating()
                yield Annotated(data=new, id=ann.id, event=ann.event,
                                comment=ann.comment)
                if finished:
                    break

        return ResponseStream(backward(), ctx)
