"""OpenAI → engine-internal preprocessing (and the backward delta path).

Reference: `OpenAIPreprocessor` (lib/llm/src/preprocessor.rs:63-303) plus the
prompt-template machinery (preprocessor/prompt/template/{oai,tokcfg,formatters}.rs):
render the HF chat template (jinja), tokenize, merge request sampling/stop
options with the model's EOS ids, optionally emit `token_ids` /
`formatted_prompt` annotations, and on the way back turn `BackendOutput`
deltas into OpenAI streaming chunks.

It is a pipeline :class:`Operator` on both the chat and completion types, so
`link(OpenAIPreprocessor(mdc), Backend(mdc), engine)` is a full OpenAI engine.
"""

from __future__ import annotations

import datetime
from typing import AsyncIterator, List, Optional

import jinja2

from ..runtime.engine import AsyncEngine, ManyOut, ResponseStream, SingleIn
from ..runtime.pipeline import Operator
from .model_card import ModelDeploymentCard
from .protocols.annotated import Annotated
from .protocols.common import (BackendOutput, FinishReason, OutputOptions,
                               PreprocessedRequest, SamplingOptions,
                               StopConditions)
from .protocols.openai import (ChatCompletionRequest, ChatDeltaGenerator,
                               CompletionDeltaGenerator, CompletionRequest,
                               usage_dict)
from .tools import ToolCallingMatcher, ToolChoice

ANNOTATION_TOKEN_IDS = "token_ids"
ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"

_FALLBACK_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message.role }}|>\n{{ message.content }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


def _strftime_now(fmt: str) -> str:
    return datetime.datetime.now().strftime(fmt)


def _tojson(value, ensure_ascii: bool = False, indent=None, separators=None,
            sort_keys: bool = False) -> str:
    """transformers' chat-template tojson (plain json.dumps)."""
    import json
    return json.dumps(value, ensure_ascii=ensure_ascii, indent=indent,
                      separators=separators, sort_keys=sort_keys)


class PromptFormatter:
    """HF chat-template renderer (reference template/oai.rs + formatters.rs)."""

    def __init__(self, template: Optional[str], bos_token: str = "",
                 eos_token: str = ""):
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            trim_blocks=True, lstrip_blocks=True,
            extensions=["jinja2.ext.loopcontrols"])
        env.globals["raise_exception"] = self._raise
        env.globals["strftime_now"] = _strftime_now
        # HF's renderer uses plain json.dumps, NOT jinja's HTML-escaping
        # tojson — tool schemas with &, <, > must render identically to
        # apply_chat_template (tests/test_chat_template_conformance.py)
        env.filters["tojson"] = _tojson
        self._env = env
        self._template = env.from_string(template or _FALLBACK_TEMPLATE)
        self.bos_token = bos_token
        self.eos_token = eos_token

    @staticmethod
    def _raise(msg: str):
        raise jinja2.TemplateError(msg)

    def render(self, messages: List[dict], add_generation_prompt: bool = True,
               tools: Optional[List[dict]] = None, **extra) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self.bos_token, eos_token=self.eos_token,
            tools=tools, **extra)


class OpenAIPreprocessor(Operator):
    """Chat/completions → PreprocessedRequest operator.

    forward: validate + render + tokenize + merge options
    backward: BackendOutput deltas → OpenAI chunks via the delta generators
    """

    def __init__(self, mdc: ModelDeploymentCard):
        self.mdc = mdc
        self.tokenizer = mdc.tokenizer()
        bos = ""
        if mdc.model_info.bos_token_id is not None:
            bos = self.tokenizer.id_to_token(mdc.model_info.bos_token_id) or ""
        eos = ""
        if mdc.model_info.eos_token_ids:
            eos = self.tokenizer.id_to_token(mdc.model_info.eos_token_ids[0]) or ""
        self.formatter = PromptFormatter(
            mdc.prompt_format.chat_template, bos_token=bos, eos_token=eos)

    # ------------------------------------------------------------------ fwd
    def preprocess_chat(self, req: ChatCompletionRequest) -> PreprocessedRequest:
        return self._preprocess_chat(req)[0]

    def _preprocess_chat(self, req: ChatCompletionRequest
                         ) -> tuple[PreprocessedRequest, str]:
        """Returns (request, formatted_prompt) — kept stateless so one
        operator instance serves concurrent requests."""
        use_raw = bool(req.nvext and req.nvext.use_raw_prompt)
        if use_raw and len(req.messages) == 1:
            prompt = req.messages[0].text()
        else:
            messages = []
            for m in req.messages:
                d = {"role": m.role, "content": m.text()}
                if m.name:
                    d["name"] = m.name
                if m.tool_calls:
                    d["tool_calls"] = m.tool_calls
                messages.append(d)
            prompt = self.formatter.render(messages, tools=req.tools)
        token_ids = self.tokenizer.encode(prompt).ids
        pre = self._common(req, token_ids, req.effective_max_tokens(),
                           req.stop_list())
        pre.annotations = list((req.nvext.annotations if req.nvext else None) or [])
        return pre, prompt

    def preprocess_completion(self, req: CompletionRequest) -> PreprocessedRequest:
        if isinstance(req.prompt, str):
            token_ids = self.tokenizer.encode(req.prompt).ids
        elif req.prompt and isinstance(req.prompt[0], int):
            token_ids = list(req.prompt)  # pre-tokenized
        else:
            raise ValueError("batch prompts must be fanned out before preprocessing")
        pre = self._common(req, token_ids, req.max_tokens, req.stop_list())
        pre.annotations = list((req.nvext.annotations if req.nvext else None) or [])
        return pre

    def _common(self, req, token_ids: List[int], max_tokens: Optional[int],
                stops: List[str]) -> PreprocessedRequest:
        info = self.mdc.model_info
        budget = info.context_length - len(token_ids)
        if budget <= 0:
            raise ValueError(
                f"prompt length {len(token_ids)} exceeds model context "
                f"{info.context_length}")
        nvext = getattr(req, "nvext", None)
        ignore_eos = bool(nvext and nvext.ignore_eos)
        stop_conditions = StopConditions(
            max_tokens=min(max_tokens, budget) if max_tokens is not None else budget,
            stop=stops or None,
            stop_token_ids_hidden=list(info.eos_token_ids),
            ignore_eos=ignore_eos,
        )
        stop_conditions.apply_ignore_eos()
        sampling = SamplingOptions(
            n=getattr(req, "n", 1) or 1,
            temperature=req.temperature,
            top_p=req.top_p,
            top_k=(nvext.top_k if nvext else None),
            seed=req.seed,
            frequency_penalty=req.frequency_penalty,
            presence_penalty=req.presence_penalty,
            repetition_penalty=(nvext.repetition_penalty if nvext else None),
            greedy=bool(nvext and nvext.greed_sampling),
        )
        # chat: `logprobs` is a bool + `top_logprobs` a count;
        # completions: `logprobs` IS the count.
        want = getattr(req, "logprobs", None)
        if isinstance(want, bool):
            n_logprobs = (getattr(req, "top_logprobs", None) or 1) if want else None
        else:
            n_logprobs = want
        output = OutputOptions(logprobs=n_logprobs)
        return PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=stop_conditions,
            sampling_options=sampling,
            output_options=output,
            eos_token_ids=list(info.eos_token_ids),
            mdc_sum=None,
            # per-request draft budget (engine/spec/); None falls back
            # to the serving engine's live default
            speculation=(nvext.speculation if nvext else None),
            # multi-tenant plane (llm/tenancy.py): tenant/QoS/session
            # ride into the router's fair-share admission and the KV
            # tiers' quota accounting
            tenant_id=(nvext.tenant if nvext else None),
            qos=(nvext.priority if nvext else None),
            session_id=(nvext.session_id if nvext else None),
        )

    # ------------------------------------------------------------- operator
    async def generate(self, request: SingleIn, next_engine: AsyncEngine) -> ManyOut:
        from ..runtime.tracing import span
        req = request.data
        if isinstance(req, dict):
            req = (ChatCompletionRequest.model_validate(req)
                   if "messages" in req else CompletionRequest.model_validate(req))
        is_chat = isinstance(req, ChatCompletionRequest)
        with span("preprocess", chat=is_chat):
            if is_chat:
                pre, formatted_prompt = self._preprocess_chat(req)
            else:
                pre = self.preprocess_completion(req)
                formatted_prompt = None
        prompt_len = len(pre.token_ids)
        annotations: List[Annotated] = []
        if ANNOTATION_TOKEN_IDS in pre.annotations:
            annotations.append(Annotated.from_annotation(
                ANNOTATION_TOKEN_IDS, pre.token_ids))
        if is_chat and ANNOTATION_FORMATTED_PROMPT in pre.annotations:
            annotations.append(Annotated.from_annotation(
                ANNOTATION_FORMATTED_PROMPT, formatted_prompt))

        # Tool calling (reference preprocessor/tools.rs): when tools are in
        # play the full message must be inspected, so text is buffered and
        # either re-emitted verbatim or replaced by tool_calls at finish.
        # Validation happens BEFORE engine dispatch — a malformed request
        # must not leak an orphaned in-flight generation.
        matcher = None
        if is_chat:
            choice = ToolChoice(req.tool_choice,
                                has_tools=bool(req.tools))
            if choice.active and not req.tools:
                raise ValueError(
                    "tool_choice requires a non-empty tools list")
            if req.tools and choice.active:
                matcher = ToolCallingMatcher(choice)

        downstream = await next_engine.generate(request.transfer(pre))

        gen = (ChatDeltaGenerator(req.model, request_id=f"chatcmpl-{request.id}")
               if is_chat else
               CompletionDeltaGenerator(req.model, request_id=f"cmpl-{request.id}"))

        # engines report chosen-token logprobs unconditionally; the wire
        # only carries them when the client asked (OpenAI conformance)
        want_logprobs = pre.output_options.logprobs is not None

        async def backward() -> AsyncIterator[Annotated[dict]]:
            for ann in annotations:
                yield ann
            completion_tokens = 0
            finished = False
            buffered: List[str] = []
            buffered_logprobs: List[dict] = []

            def chat_end_chunks(reason: FinishReason) -> List[dict]:
                """Finish-time chunks for the chat path, applying the tool
                matcher to the buffered message when active. Raises
                ValueError when a required tool call is missing — but only
                for clean finishes: a cancelled or truncated generation is
                reported as its real finish reason, not a tool error."""
                chunks: List[dict] = []
                if matcher is not None:
                    full = "".join(buffered)
                    clean = reason in (FinishReason.EOS, FinishReason.STOP)
                    try:
                        calls = matcher.get_calls(full)
                    except ValueError:
                        if clean:
                            raise
                        calls = []
                    if calls:
                        chunks.append(gen.tool_calls_chunk(calls))
                        reason = FinishReason.TOOL_CALLS
                    elif full:
                        merged = None
                        if buffered_logprobs:
                            merged = {"content": [
                                e for lp in buffered_logprobs
                                for e in lp.get("content", [])]}
                        chunks.append(gen.text_chunk(full, logprobs=merged))
                chunks.append(gen.finish_chunk(reason))
                # Usage always rides the stream; the HTTP layer drops it for
                # SSE clients that didn't opt in, and the unary aggregator
                # folds it into the response.
                chunks.append(gen.usage_chunk(prompt_len, completion_tokens))
                return chunks

            def chat_end(reason: FinishReason):
                try:
                    return chat_end_chunks(reason)
                except ValueError as e:
                    return [Annotated.from_error(str(e))]

            async for item in downstream:
                if isinstance(item, Annotated):
                    if item.data is None:
                        yield item  # pass through errors/annotations
                        continue
                    out: BackendOutput = item.data
                else:
                    out = item
                completion_tokens += len(out.token_ids)
                text = out.text
                if text is None and out.tokens:
                    text = "".join(out.tokens)
                logprobs_payload = (_format_logprobs(out, is_chat)
                                    if want_logprobs else None)
                if matcher is not None and (text
                                            or logprobs_payload is not None):
                    # nothing escapes mid-buffer: empty-text deltas carrying
                    # logprobs are buffered too
                    if text:
                        buffered.append(text)
                    if logprobs_payload is not None:
                        buffered_logprobs.append(logprobs_payload)
                elif text:
                    yield Annotated.from_data(
                        gen.text_chunk(text, logprobs=logprobs_payload))
                elif logprobs_payload is not None:
                    yield Annotated.from_data(
                        gen.text_chunk("", logprobs=logprobs_payload))
                if out.finish_reason is not None:
                    finished = True
                    if is_chat:
                        for c in chat_end(out.finish_reason):
                            yield (c if isinstance(c, Annotated)
                                   else Annotated.from_data(c))
                    else:
                        yield Annotated.from_data(gen.finish_chunk(
                            out.finish_reason,
                            usage=usage_dict(prompt_len, completion_tokens)))
            if not finished and not request.ctx.is_killed:
                reason = (FinishReason.CANCELLED if request.ctx.is_stopped
                          else FinishReason.STOP)
                if is_chat:
                    for c in chat_end(reason):
                        yield (c if isinstance(c, Annotated)
                               else Annotated.from_data(c))
                else:
                    yield Annotated.from_data(gen.finish_chunk(
                        reason, usage=usage_dict(prompt_len, completion_tokens)))

        return ResponseStream(backward(), request.ctx)


def _format_logprobs(out: BackendOutput, is_chat: bool) -> Optional[dict]:
    if out.log_probs is None:
        return None
    if is_chat:
        content = []
        for i, lp in enumerate(out.log_probs):
            tok = (out.tokens[i] if out.tokens and i < len(out.tokens) else "")
            entry = {"token": tok, "logprob": lp, "top_logprobs": []}
            if out.top_logprobs and i < len(out.top_logprobs):
                entry["top_logprobs"] = [
                    {"token": str(t), "logprob": p}
                    for t, p in out.top_logprobs[i].items()]
            content.append(entry)
        return {"content": content}
    return {"token_logprobs": list(out.log_probs),
            "tokens": list(out.tokens or [])}
