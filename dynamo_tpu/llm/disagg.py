"""PD disaggregation: conditional remote prefill, the prefill queue, the
decode-side engine wrapper, and the prefill worker.

Reference pieces this re-implements TPU-natively:
- ``DisaggregatedRouter`` (lib/llm/src/disagg_router.rs:24-259): remote iff
  ``(prefill_len - prefix_hit_len) > max_local_prefill_length``, threshold
  live-reconfigurable via a KV-store watch.
- The NATS JetStream prefill queue (examples/llm/utils/{nats_queue,
  prefill_queue}.py) → our bus WorkQueue (at-least-once, ack/nack).
- ``VllmWorker``'s remote-prefill path + ``PrefillWorker``
  (examples/llm/components/{worker,prefill_worker}.py): decode allocates,
  enqueues a RemotePrefillRequest, prefill runs with max_tokens=1 and writes
  the KV back, then decode proceeds.
- The NIXL RDMA block handoff (vLLM patch nixl.py) → a TCP stream on the
  existing response plane carrying the gathered block values (DCN staged
  through TPU-VM DRAM; TP-reshard happens in the decode engine's scatter —
  SURVEY.md §5.8).

Failure semantics: remote prefill is an *optimization*. Any failure —
no prefill workers, queue timeout, transfer error — falls back to local
prefill on the decode engine, so disagg can never lose a request.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
from typing import Optional

from ..engine.core import EngineCore, FINISH_SENTINEL, EngineRequest
from ..runtime.codec import ConnectionInfo
from ..runtime.distributed import DistributedRuntime
from ..runtime.engine import ManyOut, SingleIn
from ..runtime.kvstore import WatchEventType
from ..runtime.tcp import open_stream_sender
from .engines.jax_engine import JaxEngine
from .kv.blocks import TokenBlockSequence
from .kv.stream import (LAYER_KIND, MANIFEST_KIND, LayeredHarvest,
                        LayerStreamManifest, LayerStreamPayload,
                        decode_layer_frame, send_layer_stream,
                        send_monolithic_payload)
from .protocols.disagg import (KvPayload, RemotePrefillRequest,
                               decode_kv_payload)

logger = logging.getLogger("dynamo_tpu.llm.disagg")

__all__ = ["DisaggregatedRouter", "PrefillQueue", "DisaggEngine",
           "PrefillWorker", "PREFILL_QUEUE"]

PREFILL_QUEUE = "prefill_queue"


def disagg_config_key(model_name: str, kind: str = "chat") -> str:
    """Reference etcd path: public/components/disagg_router/models/chat/{m}
    (disagg_router.rs:38-140)."""
    return f"public/components/disagg_router/models/{kind}/{model_name}"


class DisaggregatedRouter:
    """Local-vs-remote prefill decision with a live-watched threshold."""

    def __init__(self, runtime: DistributedRuntime, model_name: str,
                 max_local_prefill_length: int = 512,
                 conditional: bool = True):
        self.runtime = runtime
        self.model_name = model_name
        self.max_local_prefill_length = max_local_prefill_length
        self.conditional = conditional
        # planner drain flag (docs/planner.md): while the prefill fleet is
        # being decommissioned, every prefill runs local — no new remote
        # admissions regardless of length
        self.prefill_draining = False
        self._watch_task: Optional[asyncio.Task] = None
        self._watcher = None

    def prefill_remote(self, prefill_len: int, prefix_hit_len: int) -> bool:
        """disagg_router.rs:239-249: remote iff the *un-cached* prefill work
        exceeds the local threshold."""
        if self.prefill_draining:
            return False
        if not self.conditional:
            return True
        return (prefill_len - prefix_hit_len) > self.max_local_prefill_length

    async def start(self) -> "DisaggregatedRouter":
        """Load the current stored threshold and watch for live updates."""
        key = disagg_config_key(self.model_name)
        entry = await self.runtime.store.kv_get(key)
        if entry is not None:
            self._apply(entry.value)
        self._watcher = await self.runtime.store.watch_prefix(key)
        self._watch_task = asyncio.get_running_loop().create_task(
            self._watch_loop(), name="disagg-router-watch")
        return self

    def _apply(self, raw: bytes) -> None:
        try:
            cfg = json.loads(raw)
            self.max_local_prefill_length = int(
                cfg["max_local_prefill_length"])
            self.prefill_draining = bool(cfg.get("draining", False))
            logger.info("disagg threshold for %s → %d%s", self.model_name,
                        self.max_local_prefill_length,
                        " (prefill fleet draining)" if self.prefill_draining
                        else "")
        except (ValueError, KeyError, TypeError):
            logger.warning("bad disagg config update ignored: %r", raw)

    async def _watch_loop(self) -> None:
        async for ev in self._watcher:
            if ev.type == WatchEventType.PUT:
                self._apply(ev.entry.value)

    async def publish_threshold(self, value: int,
                                draining: bool = False) -> None:
        """Admin write (the llmctl-style live reconfig path)."""
        await self.runtime.store.kv_put(
            disagg_config_key(self.model_name),
            json.dumps({"max_local_prefill_length": value,
                        "draining": draining}).encode())

    async def stop(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        if self._watcher is not None:
            self._watcher.close()


class PrefillQueue:
    """Thin wrapper over the bus work queue (prefill_queue.py:24-56)."""

    def __init__(self, runtime: DistributedRuntime, name: str = PREFILL_QUEUE):
        self.runtime = runtime
        self.name = name
        self._q = None

    async def _queue(self):
        if self._q is None:
            # racing first callers bind the SAME named queue (work_queue
            # is idempotent by name); last-writer-wins is equivalent
            self._q = await self.runtime.bus.work_queue(self.name)  # dynalint: ok DL008 idempotent-by-name bind
        return self._q

    async def enqueue(self, req: RemotePrefillRequest) -> int:
        q = await self._queue()
        return await q.enqueue(req.to_json())

    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 60.0):
        q = await self._queue()
        return await q.dequeue(timeout=timeout, ack_deadline=ack_deadline)

    async def ack(self, item_id: int) -> None:
        q = await self._queue()
        await q.ack(item_id)

    async def nack(self, item_id: int) -> None:
        q = await self._queue()
        await q.nack(item_id)

    async def depth(self) -> int:
        q = await self._queue()
        return await q.depth()


class DisaggEngine(JaxEngine):
    """Decode-side engine: per request, decide local vs remote prefill;
    remote path registers a KV-sink stream, enqueues the prefill work, and
    admits the request with the shipped KV (examples worker.py:37-189)."""

    def __init__(self, core: EngineCore, runtime: DistributedRuntime,
                 disagg_router: DisaggregatedRouter,
                 queue: Optional[PrefillQueue] = None,
                 prefill_timeout: float = 30.0,
                 device_plane: bool = True,
                 layer_stream: Optional[bool] = None):
        super().__init__(core)
        self.runtime = runtime
        self.disagg_router = disagg_router
        self.queue = queue or PrefillQueue(runtime)
        self.prefill_timeout = prefill_timeout
        # advertise the in-process ICI bulk plane (kv_transport) to prefill
        # workers; False forces the TCP wire path even in-process
        self.device_plane = device_plane
        # streaming layer-wise wire handoff (llm/kv/stream.py): announce
        # layer-stream consumption to prefill workers so the TTFT-serial
        # transfer pipelines per layer. Default ON; DYN_DISAGG_LAYER_
        # STREAM=0 forces the monolithic payload (ops escape hatch + the
        # bench's A/B lever).
        self.layer_stream = (layer_stream if layer_stream is not None
                             else os.environ.get(
                                 "DYN_DISAGG_LAYER_STREAM", "1") != "0")
        # observability
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_failures = 0
        self.device_transfers = 0   # handoffs that rode the ICI bulk plane
        self.stream_transfers = 0   # handoffs that arrived layer-streamed
        # layer-stream drain pumps, one per in-flight streamed handoff —
        # each owns its receiver's cleanup (see _spawn_stream_drain)
        self._drain_tasks: set = set()

    def _estimate_prefix_hit(self, req: EngineRequest) -> int:
        """Hold-free device-tier prefix estimate (in tokens). The hash chain
        is kept on the request so admission doesn't re-hash the prompt."""
        bs = self.core.cfg.kv_block_size
        req.seq = TokenBlockSequence(bs, req.prompt)
        n = self.core.kv_manager.pool.peek_prefix(req.seq.sequence_hashes)
        return n * bs

    async def generate(self, request: SingleIn) -> ManyOut:
        from ..runtime.tracing import span
        req = self.build_request(request)
        hit = self._estimate_prefix_hit(req)
        if self.disagg_router.prefill_remote(len(req.prompt), hit):
            with span("disagg.remote_prefill", prompt=len(req.prompt),
                      prefix_hit=hit) as s:
                payload = await self._remote_prefill(req, hit)
            if payload is not None:
                req.precomputed = payload
                self.remote_prefills += 1
            else:
                if s is not None:
                    s.attrs["fallback"] = True
                self.remote_failures += 1
                self.local_prefills += 1
        else:
            self.local_prefills += 1
        await self.core.submit(req)
        return self.stream_response(req, request)

    async def _remote_prefill(self, req: EngineRequest,
                              hit: int) -> Optional[KvPayload]:
        from ..runtime.tracing import current_wire_context
        from .kv_transport import PROC_TOKEN, bridge
        rt = self.runtime
        await rt.tcp.start()
        rx = rt.tcp.register()
        sink = bridge().register(req.rid)   # device-path rendezvous
        rpr = RemotePrefillRequest(
            request_id=req.rid, token_ids=list(req.prompt),
            sampling=dataclasses.asdict(req.sampling),
            connection_info=rt.tcp.connection_info(rx).to_dict(),
            engine_id=rt.worker_uuid, prefix_hit_tokens=hit,
            device_bridge=PROC_TOKEN if self.device_plane else "",
            trace=current_wire_context(),
            deadline_ms=(req.ctx.remaining_ms()
                         if req.ctx is not None
                         and hasattr(req.ctx, "remaining_ms") else None),
            layer_stream=self.layer_stream)
        handed_off = False
        try:
            await self.queue.enqueue(rpr)
            prologue = await rx.wait_connected(timeout=self.prefill_timeout)
            if prologue.error is not None:
                raise RuntimeError(prologue.error)
            deadline = asyncio.get_running_loop().time() + self.prefill_timeout
            from ..runtime.codec import FrameKind
            meta_header: Optional[bytes] = None
            chunks: list = []
            while True:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    raise TimeoutError("kv payload timeout")
                f = await rx.next_frame(timeout=remaining)
                if f is None:
                    continue
                if f.kind == FrameKind.DATA:
                    if f.header:
                        h = json.loads(f.header)
                        if h.get("stream") == MANIFEST_KIND:
                            # streaming layer-wise handoff: admit against
                            # the manifest NOW — a drain task keeps
                            # consuming layer frames while the engine
                            # progressively scatters (llm/kv/stream.py)
                            payload = LayerStreamPayload(
                                LayerStreamManifest.from_header(h))
                            self._spawn_stream_drain(req.rid, rx, payload)
                            handed_off = True
                            self.stream_transfers += 1
                            return payload
                        meta_header = f.header
                    chunks.append(f.data)
                elif f.kind == FrameKind.ERROR:
                    raise RuntimeError(f.header_json().get("error", "remote"))
                elif f.kind == FrameKind.SENTINEL:
                    if meta_header is None:
                        raise RuntimeError("stream ended without payload")
                    meta = json.loads(meta_header)
                    if meta.get("device_deposit"):
                        # bulk bytes took the in-process ICI plane; the
                        # deposit happened before this control frame
                        if not sink.done():
                            raise RuntimeError(
                                "device-deposit frame without deposit")
                        self.device_transfers += 1
                        return sink.result()
                    return decode_kv_payload(meta_header, b"".join(chunks))
        except Exception as e:  # noqa: BLE001 — any failure → local fallback
            logger.warning("remote prefill failed for %s (%s); "
                           "falling back to local", req.rid, e)
            return None
        finally:
            if not handed_off:
                bridge().cancel(req.rid)
                rx.close()
                rt.tcp.unregister(rx.stream_id)

    def _spawn_stream_drain(self, rid: str, rx, payload) -> None:
        """Stand up the frame→payload pump for one layer stream; stream
        cleanup (bridge rendezvous, receiver, tcp registration) moves
        here from _remote_prefill's finally — the stream outlives that
        call by design."""
        from .kv_transport import bridge
        rt = self.runtime

        async def drain() -> None:
            from ..runtime.codec import FrameKind
            mono_header: Optional[bytes] = None
            chunks: list = []
            try:
                while True:
                    f = await rx.next_frame(timeout=self.prefill_timeout)
                    if f is None:
                        continue
                    if f.kind == FrameKind.DATA:
                        h = (json.loads(f.header) if f.header else None)
                        if h is not None and h.get("stream") == LAYER_KIND:
                            payload.put_layer(
                                int(h["layer"]),
                                decode_layer_frame(payload.manifest,
                                                   f.data))
                        else:
                            # the producer tore a frame and degraded to
                            # the monolithic payload on this same stream
                            # (stream.py rung 1) — accumulate its chunks
                            if f.header:
                                mono_header = f.header
                            chunks.append(f.data)
                    elif f.kind == FrameKind.ERROR:
                        payload.fail(
                            f.header_json().get("error", "remote"))
                        return
                    elif f.kind == FrameKind.SENTINEL:
                        if mono_header is not None:
                            mono = decode_kv_payload(mono_header,
                                                     b"".join(chunks))
                            payload.put_all(mono.values)
                        payload.finish()
                        return
            except Exception as e:  # noqa: BLE001 — dead peer/short frame
                # → the engine's cold-recompute rung, never an error
                payload.fail(str(e))
            finally:
                bridge().cancel(rid)
                rx.close()
                rt.tcp.unregister(rx.stream_id)

        t = asyncio.get_running_loop().create_task(
            drain(), name=f"kv-stream-drain-{rid}")
        self._drain_tasks.add(t)
        t.add_done_callback(self._drain_tasks.discard)

    def stats(self) -> dict:
        return {"remote_prefills": self.remote_prefills,
                "local_prefills": self.local_prefills,
                "remote_failures": self.remote_failures,
                "device_transfers": self.device_transfers,
                "stream_transfers": self.stream_transfers,
                "max_local_prefill_length":
                    self.disagg_router.max_local_prefill_length}


class PrefillWorker:
    """Pulls the prefill queue, runs prefill-with-handoff on its own engine,
    streams the KV payload to the decode worker's sink, and acks.

    Reference: examples/llm/components/prefill_worker.py:36-141 (dequeue →
    NIXL-read metadata → prefill is_remote_decode max_tokens=1 → NIXL write
    → notify). The TPU version needs no metadata store: the decode worker's
    sink address travels inside the request."""

    MAX_DELIVERIES = 3

    def __init__(self, core: EngineCore, runtime: DistributedRuntime,
                 queue: Optional[PrefillQueue] = None,
                 send_timeout: float = 30.0):
        self.core = core
        self.runtime = runtime
        self.queue = queue or PrefillQueue(runtime)
        self.send_timeout = send_timeout
        self._task: Optional[asyncio.Task] = None
        self._inflight: set = set()
        self._stopping = False
        self.prefills_done = 0
        self.prefills_failed = 0
        self.device_handoffs = 0    # handoffs that rode the ICI bulk plane
        self.stream_handoffs = 0    # handoffs sent as per-layer streams
        self.stream_fallbacks = 0   # streams degraded to monolithic mid-way

    async def start(self) -> "PrefillWorker":
        self._stopping = False
        self._task = asyncio.get_running_loop().create_task(
            self._loop(), name="prefill-worker")
        return self

    async def _loop(self) -> None:
        backoff = 0.5
        while not self._stopping:
            try:
                item = await self.queue.dequeue(timeout=0.5)
                backoff = 0.5
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — transient bus errors
                logger.warning("prefill dequeue failed (%s); retrying in "
                               "%.1fs", e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
                continue
            if item is None:
                continue
            t = asyncio.get_running_loop().create_task(
                self._handle(item), name=f"prefill-item-{item.id}")
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    async def _handle(self, item) -> None:
        try:
            rpr = RemotePrefillRequest.from_json(item.payload)
        except Exception:
            logger.exception("undecodable prefill work item %d", item.id)
            await self.queue.ack(item.id)
            return
        from ..runtime.tracing import Trace, use_trace
        # open a CHILD trace of the decode-side request (wire-propagated
        # context on the queue item) so the disagg handoff appears inside
        # the one fleet tree the collector assembles
        with use_trace(Trace.from_wire(rpr.trace, rpr.request_id,
                                       role="prefill")) as ptrace:
            await self._run_prefill(item, rpr, ptrace)

    async def _run_prefill(self, item, rpr: RemotePrefillRequest,
                           ptrace) -> None:
        conn = ConnectionInfo.from_dict(rpr.connection_info)
        try:
            with ptrace.span("dial_back"):
                sender = await open_stream_sender(conn, timeout=5.0)
        except Exception:
            # decode worker unreachable — retry a bounded number of times
            # (it may be us who's partitioned), then drop: the decode side
            # falls back to local prefill on its own timeout.
            ptrace.set_error("decode worker sink unreachable")
            if item.deliveries >= self.MAX_DELIVERIES:
                logger.warning("dropping prefill item %d after %d deliveries",
                               item.id, item.deliveries)
                await self.queue.ack(item.id)
            else:
                await self.queue.nack(item.id)
            return

        sent = asyncio.get_running_loop().create_future()
        # the failure path may abandon `sent` mid-flight — consume any late
        # exception so asyncio never logs "exception was never retrieved"
        sent.add_done_callback(
            lambda f: None if f.cancelled() else f.exception())

        from .kv_transport import PROC_TOKEN, DeviceKvPayload, bridge
        use_device = rpr.device_bridge == PROC_TOKEN

        async def handoff_wire(tok, logprob, values, seq_hashes) -> None:
            try:
                if isinstance(values, LayeredHarvest):
                    # streaming layer-wise handoff: one frame per layer,
                    # next layer's gather overlapped with this frame's
                    # send; degrades to the monolithic payload on this
                    # same stream if a frame tears (llm/kv/stream.py)
                    res = await send_layer_stream(
                        sender, rpr.request_id, tok, logprob, seq_hashes,
                        values)
                    self.stream_handoffs += 1
                    if res["fallback"]:
                        self.stream_fallbacks += 1
                else:
                    payload = KvPayload(
                        request_id=rpr.request_id, first_token=tok,
                        first_logprob=logprob, seq_hashes=seq_hashes,
                        values=values)
                    await send_monolithic_payload(sender, payload)
                    await sender.finish()
                if not sent.done():
                    sent.set_result(True)
            except Exception as e:  # noqa: BLE001
                if not sent.done():
                    sent.set_exception(e)

        async def handoff_device(tok, logprob, dev, seq_hashes) -> None:
            # deposit the device arrays in the in-process bridge, then tell
            # the decode side over the response plane (tiny control frame);
            # if the sink is gone (decode timed out), fall back to wire so
            # the blocks still arrive
            try:
                payload = DeviceKvPayload(
                    request_id=rpr.request_id, first_token=tok,
                    first_logprob=logprob, seq_hashes=seq_hashes,
                    stacked=dev["stacked"], n_blocks=dev["n_blocks"],
                    block_size=self.core.cfg.kv_block_size)
                if bridge().deposit(rpr.request_id, payload):
                    self.device_handoffs += 1
                    header = json.dumps(
                        {"device_deposit": True,
                         "request_id": rpr.request_id}).encode()
                    await sender.send(b"", header=header)
                    await sender.finish()
                    if not sent.done():
                        sent.set_result(True)
                    return
                from ..engine.block_copy import fetch_wire
                values = await asyncio.to_thread(
                    fetch_wire, dev["stacked"], dev["n_blocks"],
                    self.core.wire_kv_heads)
                # wire fallback needs host scalars (device mode skipped the
                # prefill-side fetch)
                await handoff_wire(int(tok), float(logprob), values,
                                   seq_hashes)
            except Exception as e:  # noqa: BLE001
                if not sent.done():
                    sent.set_exception(e)

        from ..engine.sampling import SlotSampling
        from ..runtime.engine import EngineContext
        # re-anchor the decode side's remaining budget on OUR clock; a
        # job whose budget is already gone is dropped unstarted (the
        # decode worker cancelled/fell back long ago)
        ctx = EngineContext(rpr.request_id, deadline_ms=rpr.deadline_ms)
        if ctx.deadline_exceeded:
            ptrace.set_error("deadline exceeded before prefill started")
            await sender.finish(error="deadline exceeded")
            await self.queue.ack(item.id)
            return
        req = EngineRequest(
            rid=rpr.request_id, prompt=list(rpr.token_ids),
            sampling=SlotSampling(**rpr.sampling), max_new_tokens=1,
            eos_ids=frozenset(), ctx=ctx,
            handoff=handoff_device if use_device else handoff_wire,
            handoff_device=use_device,
            handoff_layered=(rpr.layer_stream and not use_device))
        await self.core.submit(req)
        try:
            # drain the engine's (token, finish) signals, then await the send
            with ptrace.span("prefill.engine", tokens=len(rpr.token_ids)):
                while True:
                    out, _ = await asyncio.wait_for(req.out_queue.get(),
                                                    self.send_timeout)
                    if out is FINISH_SENTINEL:
                        break
            with ptrace.span("prefill.handoff"):
                await asyncio.wait_for(sent, self.send_timeout)
            await self.queue.ack(item.id)
            self.prefills_done += 1
        except Exception as e:  # noqa: BLE001
            ptrace.set_error(str(e))
            self.prefills_failed += 1
            logger.warning("prefill handoff failed for %s (%s)",
                           rpr.request_id, e)
            # if the request is still queued/admitted in the engine, cancel
            # it — its sink stream is gone, so its prefill would be wasted
            ctx.stop_generating()
            try:
                await sender.finish(error=str(e))
            except Exception:  # noqa: BLE001
                pass
            # the KV was computed but not delivered; decode falls back —
            # ack (a re-run would hit the prefill worker's prefix cache
            # anyway, but the sink stream is gone)
            await self.queue.ack(item.id)

    def stats(self) -> dict:
        return {"prefills_done": self.prefills_done,
                "prefills_failed": self.prefills_failed,
                "device_handoffs": self.device_handoffs,
                "stream_handoffs": self.stream_handoffs,
                "stream_fallbacks": self.stream_fallbacks,
                "inflight": len(self._inflight)}

    async def drain(self) -> None:
        """Planner drain: stop pulling NEW queue items, let every in-flight
        prefill finish its handoff (zero dropped work; the queue's other
        consumers — or the decode side's local fallback — absorb the rest)."""
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._inflight:
            await asyncio.gather(*list(self._inflight),
                                 return_exceptions=True)

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for t in list(self._inflight):
            t.cancel()
