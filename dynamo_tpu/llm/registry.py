"""Model registry: deployment cards on the kvstore, watched live.

The multi-model serving plane's source of truth (PAPER.md layers 2/4 —
the reference's ``ModelDeploymentCard`` travelling through etcd so
frontends can serve models they never loaded). A :class:`RegistryCard`
names everything a frontend/processor needs to multiplex the OpenAI
``model`` field onto a worker fleet:

- the served ``name`` and its ``endpoint`` (dyn://ns/comp/ep),
- the ``model_path``/tokenizer ref the preprocessor loads,
- the serving ``geometry`` (tp/pp/quant/spec/ragged/... — whatever the
  fleet was launched with), and
- the derived ``program_set_key`` — a stable digest of the geometry
  features that select a compiled program set. Two fleets with the same
  key serve bit-compatible programs; this is the seam the
  composition-closure refactor (ROADMAP) plugs its unified program-set
  builder into: one key → one builder invocation.

Cards live under ``modelreg/cards/{name}``; self-registering workers
attach their primary lease (the card dies with the fleet's last
worker... actually with the registering process — llmctl-managed cards
persist). :class:`RegistryWatcher` keeps any consumer in sync — the
processor builds/tears down per-model pipelines from it, each with its
own per-model KvIndexer/KvScheduler (llm/engines/kv_routed.py), so one
frontend serves N models with N independent routing planes.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import logging
from typing import Dict, Optional

logger = logging.getLogger("dynamo_tpu.llm.registry")

__all__ = ["REGISTRY_PREFIX", "RegistryCard", "card_key",
           "program_set_key", "register_card", "remove_card", "get_card",
           "list_cards", "RegistryWatcher"]

REGISTRY_PREFIX = "modelreg/cards/"

# geometry keys that select a compiled program set, in canonical order;
# anything else in the geometry dict is descriptive only
_PROGRAM_KEYS = ("tp", "pp", "sp", "quantization", "kv_quantization",
                 "mla", "spec_k", "sliding_window", "ragged",
                 "kv_block_size", "max_seq_len")


def card_key(name: str) -> str:
    return f"{REGISTRY_PREFIX}{name}"


def program_set_key(geometry: Dict[str, object]) -> str:
    """Stable digest of the program-selecting geometry features. The
    canonical key order (not dict order) and JSON scalar encoding make
    the key reproducible across processes — the composition-closure
    builder's future cache key."""
    sel = {k: geometry.get(k) for k in _PROGRAM_KEYS
           if geometry.get(k) not in (None, 0, False, "")}
    blob = json.dumps(sel, sort_keys=True).encode()
    return hashlib.blake2s(blob, digest_size=8).hexdigest()


@dataclasses.dataclass
class RegistryCard:
    """One served model's deployment card (the registry record)."""

    name: str
    endpoint: str                     # dyn://ns/comp/ep or ns.comp.ep
    model_path: Optional[str] = None  # tokenizer/config ref (HF-style dir)
    model_type: str = "chat+completion"   # chat | completion | chat+completion
    kv_block_size: int = 16
    geometry: Dict[str, object] = dataclasses.field(default_factory=dict)
    program_set: str = ""             # derived when empty (see __post_init__)
    revision: int = 0
    mdcsum: Optional[str] = None      # preprocessing checksum when known

    def __post_init__(self):
        if not self.program_set:
            geo = dict(self.geometry)
            geo.setdefault("kv_block_size", self.kv_block_size)
            self.program_set = program_set_key(geo)

    def types(self) -> tuple:
        return tuple(t for t in self.model_type.split("+")
                     if t in ("chat", "completion")) or ("chat",)

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self), sort_keys=True).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "RegistryCard":
        d = json.loads(raw)
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


async def register_card(runtime, card: RegistryCard,
                        lease_id: int = 0) -> None:
    """Write (or bump) a card. Self-registering workers pass their
    primary lease so the card dies with the fleet's registering process;
    llmctl-managed cards persist until removed."""
    existing = await get_card(runtime, card.name)
    if existing is not None:
        card.revision = existing.revision + 1
    await runtime.store.kv_put(card_key(card.name), card.to_json(),
                               lease_id=lease_id)


async def remove_card(runtime, name: str) -> bool:
    return await runtime.store.kv_delete(card_key(name))


async def get_card(runtime, name: str) -> Optional[RegistryCard]:
    entry = await runtime.store.kv_get(card_key(name))
    if entry is None:
        return None
    try:
        return RegistryCard.from_json(entry.value)
    except (ValueError, TypeError):
        logger.warning("malformed registry card at %s", card_key(name))
        return None


async def list_cards(runtime) -> Dict[str, RegistryCard]:
    out: Dict[str, RegistryCard] = {}
    for e in await runtime.store.kv_get_prefix(REGISTRY_PREFIX):
        try:
            card = RegistryCard.from_json(e.value)
        except (ValueError, TypeError):
            logger.warning("malformed registry card at %s", e.key)
            continue
        out[card.name] = card
    return out


class RegistryWatcher:
    """Watches ``modelreg/cards/`` and drives async add/remove
    callbacks: ``on_card(card)`` on PUT (adds AND revisions),
    ``on_removed(name)`` on DELETE. Consumers (the processor's
    multiplexer, test harnesses) own whatever state the callbacks
    build; the watcher only sequences kvstore events."""

    def __init__(self, runtime, on_card, on_removed):
        self.runtime = runtime
        self.on_card = on_card
        self.on_removed = on_removed
        self._watcher = None
        self._task: Optional[asyncio.Task] = None
        self.cards: Dict[str, RegistryCard] = {}

    async def start(self) -> "RegistryWatcher":
        from ..runtime.tracing import detach_trace
        # replay current cards before watching so a late-started
        # frontend converges to the registry's present state
        self._watcher = await self.runtime.store.watch_prefix(
            REGISTRY_PREFIX)
        for name, card in sorted((await list_cards(self.runtime)).items()):
            self.cards[name] = card
            await self.on_card(card)

        async def loop():
            detach_trace()
            from ..runtime.kvstore import WatchEventType
            async for ev in self._watcher:
                name = ev.entry.key[len(REGISTRY_PREFIX):]
                try:
                    if ev.type == WatchEventType.PUT:
                        card = RegistryCard.from_json(ev.entry.value)
                        prev = self.cards.get(name)
                        if (prev is not None
                                and prev.to_json() == card.to_json()):
                            continue      # startup-replay echo
                        self.cards[name] = card
                        await self.on_card(card)
                    else:
                        self.cards.pop(name, None)
                        await self.on_removed(name)
                except Exception:  # noqa: BLE001 — one bad card must not
                    logger.exception("registry watch event failed for %s",
                                     name)

        self._task = asyncio.get_running_loop().create_task(
            loop(), name="model-registry-watch")
        return self

    async def stop(self) -> None:
        # claim before the await (DL008)
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._watcher is not None:
            self._watcher.close()
