"""RemoteEngine: an AsyncEngine that routes requests to dyn:// worker
endpoints over the distributed runtime (the frontend half of the reference's
``EngineConfig::Dynamic`` path, launch/dynamo-run/src/input/common.rs:35-92 +
component/client.rs routing).

The wire payload is whatever the worker's pipeline speaks — for full-pipeline
workers that is the OpenAI request/chunk JSON dicts, so the frontend stays
model-agnostic. Routing modes: random (reference default), round_robin, or
direct via `instance_id`."""

from __future__ import annotations

from typing import Optional

from ...runtime.distributed import Client, Endpoint
from ...runtime.engine import AsyncEngine, ManyOut, SingleIn

__all__ = ["RemoteEngine"]


class RemoteEngine(AsyncEngine):
    def __init__(self, client: Client, router_mode: str = "random"):
        self.client = client
        self.router_mode = router_mode

    @classmethod
    async def start(cls, endpoint: Endpoint, router_mode: str = "random",
                    wait: bool = False, timeout: float = 30.0
                    ) -> "RemoteEngine":
        from ..protocols.annotated import decode_annotated_json
        client = endpoint.client(decode_resp=decode_annotated_json)
        await client.start()
        if wait:
            await client.wait_for_instances(timeout)
        return cls(client, router_mode)

    async def generate(self, request: SingleIn,
                       instance_id: Optional[int] = None) -> ManyOut:
        if instance_id is not None:
            return await self.client.direct(request, instance_id)
        if self.router_mode == "round_robin":
            return await self.client.round_robin(request)
        return await self.client.random(request)

    async def close(self) -> None:
        await self.client.close()
