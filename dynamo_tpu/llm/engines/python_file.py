"""User python-file engines: ``pystr:<file.py>`` / ``pytok:<file.py>``.

Reference: lib/llm/src/engines/python.rs:57-354 — `dynamo-run out=pystr:f.py`
loads a user file exposing ``async def generate(request)`` and adapts its
async generator to the engine stream. `pystr` speaks strings at the OpenAI
level (each yield is a text delta); `pytok` speaks the engine-internal token
protocol (each yield is token ids), sitting behind the preprocessor/
detokenizer link like any core engine.

The user file may optionally expose ``async def init(engine_args: dict)``,
called once before the first request (the reference passes model metadata to
the loaded module the same way).

Example pystr file::

    async def generate(request):
        prompt = request["messages"][-1]["content"]
        for word in prompt.split():
            yield word + " "

Example pytok file::

    async def generate(request):
        for tid in request["token_ids"]:
            yield {"token_ids": [tid]}
"""

from __future__ import annotations

import asyncio
import dataclasses
import importlib.util
import inspect
import os
from typing import Any, AsyncIterator, Optional

from ...runtime.engine import AsyncEngine, ManyOut, ResponseStream, SingleIn
from ..protocols.annotated import Annotated
from ..protocols.common import (BackendOutput, FinishReason,
                                PreprocessedRequest)
from ..protocols.openai import ChatDeltaGenerator, CompletionDeltaGenerator

__all__ = ["load_user_generate", "PythonFileEngineFull",
           "PythonFileEngineCore"]


def load_user_generate(path: str) -> tuple:
    """Import ``path`` as a module; returns (generate, init|None)."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise FileNotFoundError(f"python engine file not found: {path}")
    name = f"_dyn_user_engine_{abs(hash(path)) & 0xFFFFFF:x}"
    spec = importlib.util.spec_from_file_location(name, path)
    assert spec is not None and spec.loader is not None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    gen = getattr(mod, "generate", None)
    if gen is None or not (inspect.isasyncgenfunction(gen)
                           or inspect.iscoroutinefunction(gen)):
        raise TypeError(
            f"{path} must define `async def generate(request)` "
            "(async generator)")
    return gen, getattr(mod, "init", None)


class _PythonFileEngineBase(AsyncEngine):
    def __init__(self, path: str, engine_args: Optional[dict] = None):
        self.path = path
        self.engine_args = engine_args or {}
        self._generate, self._init = load_user_generate(path)
        self._initialized = self._init is None
        self._init_lock: Optional[asyncio.Lock] = None

    async def _ensure_init(self) -> None:
        if self._initialized:
            return
        if self._init_lock is None:
            self._init_lock = asyncio.Lock()
        async with self._init_lock:
            if not self._initialized:
                await self._init(dict(self.engine_args))
                self._initialized = True  # only a successful init latches

    def _user_stream(self, request: Any) -> AsyncIterator[Any]:
        out = self._generate(request)
        if inspect.isasyncgen(out):
            return out

        async def once():  # plain coroutine returning one item
            yield await out
        return once()


class PythonFileEngineFull(_PythonFileEngineBase):
    """`pystr:` — user yields add to the response text; request arrives as
    the raw OpenAI dict (chat or completion)."""

    async def generate(self, request: SingleIn) -> ManyOut:
        await self._ensure_init()
        req = request.data
        if not isinstance(req, dict):
            req = req.model_dump(exclude_none=True)
        ctx = request.ctx
        is_chat = "messages" in req
        gen_cls = ChatDeltaGenerator if is_chat else CompletionDeltaGenerator
        prefix = "chatcmpl" if is_chat else "cmpl"
        delta_gen = gen_cls(req.get("model", "python"),
                            request_id=f"{prefix}-{request.id}")
        user = self._user_stream(req)

        async def stream() -> AsyncIterator[Annotated[dict]]:
            async for item in user:
                if ctx.is_stopped:
                    await user.aclose()
                    break
                yield Annotated.from_data(delta_gen.text_chunk(str(item)))
            yield Annotated.from_data(delta_gen.finish_chunk(FinishReason.STOP))

        return ResponseStream(stream(), ctx)


class PythonFileEngineCore(_PythonFileEngineBase):
    """`pytok:` — token-in/token-out. The user sees the PreprocessedRequest
    as a dict; each yield is `{"token_ids": [...], ...}` or a bare list of
    token ids. Honors max_tokens like a real engine would."""

    async def generate(self, request: SingleIn) -> ManyOut:
        await self._ensure_init()
        pre: PreprocessedRequest = request.data
        req_dict = dataclasses.asdict(pre)
        ctx = request.ctx
        max_tokens = pre.stop_conditions.max_tokens
        user = self._user_stream(req_dict)

        async def stream() -> AsyncIterator[Annotated[BackendOutput]]:
            emitted = 0
            finish = FinishReason.STOP
            async for item in user:
                if ctx.is_stopped:
                    await user.aclose()
                    finish = None
                    break
                if isinstance(item, dict):
                    out = BackendOutput.from_dict(item)
                else:
                    toks = item if isinstance(item, (list, tuple)) else [item]
                    out = BackendOutput(token_ids=[int(t) for t in toks])
                if max_tokens is not None \
                        and emitted + len(out.token_ids) > max_tokens:
                    out.token_ids = out.token_ids[:max_tokens - emitted]
                emitted += len(out.token_ids)
                yield Annotated.from_data(out)
                if out.finish_reason is not None:
                    finish = None  # user already closed the stream
                    break
                if max_tokens is not None and emitted >= max_tokens:
                    await user.aclose()
                    finish = FinishReason.LENGTH  # cap cut the stream
                    break
            if finish is not None:
                yield Annotated.from_data(BackendOutput.final(finish))

        return ResponseStream(stream(), ctx)
