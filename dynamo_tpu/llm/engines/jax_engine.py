"""JAX engine adapter: EngineCore → AsyncEngine[PreprocessedRequest, ...].

The reference's engines translate BackendInput into vLLM/SGLang/TRT-LLM wire
protocols (lib/llm/src/engines/*); here the "engine" is in-process JAX, so
this adapter only maps the request, streams sampled tokens out of the slot
queue, and honors step-granular cancellation.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from ...engine.config import EngineConfig, ModelConfig
from ...engine.core import FINISH_SENTINEL, EngineCore, EngineRequest
from ...engine.sampling import SlotSampling
from ...runtime.engine import AsyncEngine, ManyOut, ResponseStream, SingleIn
from ..protocols.annotated import Annotated
from ..protocols.common import BackendOutput, FinishReason, PreprocessedRequest


class JaxEngine(AsyncEngine):
    """Serves the engine-internal token protocol from an EngineCore."""

    def __init__(self, core: EngineCore):
        self.core = core

    @classmethod
    def from_model_dir(cls, model_dir: str,
                       engine_cfg: Optional[EngineConfig] = None,
                       load_weights: bool = True, **core_kwargs) -> "JaxEngine":
        model_cfg = ModelConfig.from_model_dir(model_dir)
        engine_cfg = engine_cfg or EngineConfig()
        params = None
        if load_weights:
            import jax.numpy as jnp

            # load_params_auto streams each device's shard straight from
            # disk when a mesh is given (host peak = one shard — the
            # 70B-scale path)
            from ...engine.weights import load_params_auto
            params = load_params_auto(
                model_dir, model_cfg, mesh=core_kwargs.get("mesh"),
                dtype=core_kwargs.get("param_dtype", jnp.bfloat16))
        return cls(EngineCore(model_cfg, engine_cfg, params=params,
                              **core_kwargs))

    def build_request(self, request: SingleIn) -> EngineRequest:
        pre: PreprocessedRequest = request.data
        sc = pre.stop_conditions
        # speculation knob: None = engine live default (spec_k = -1);
        # explicit values clamp to the compiled verify width at dispatch
        spec = getattr(pre, "speculation", None)
        return EngineRequest(
            rid=request.id,
            prompt=list(pre.token_ids),
            sampling=SlotSampling.from_options(pre.sampling_options),
            max_new_tokens=sc.max_tokens or 16384,
            eos_ids=frozenset(() if sc.ignore_eos else
                              (sc.stop_token_ids_hidden or pre.eos_token_ids)),
            ctx=request.ctx,
            spec_k=-1 if spec is None else max(0, int(spec)),
            # multi-tenant identity (llm/tenancy.py): payload fields
            # win, the wire-propagated context identity backs them up —
            # the KV tiers' per-tenant quota accounting keys on this
            tenant=(getattr(pre, "tenant_id", None)
                    or getattr(request.ctx, "tenant", None) or ""),
            session=getattr(pre, "session_id", None) or "",
        )

    async def generate(self, request: SingleIn) -> ManyOut:
        req = self.build_request(request)
        await self.core.submit(req)
        return self.stream_response(req, request)

    def stream_response(self, req: EngineRequest,
                        request: SingleIn) -> ManyOut:
        from ...runtime.tracing import current_trace
        trace = current_trace()

        async def stream() -> AsyncIterator[Annotated[BackendOutput]]:
            import asyncio

            first = True
            emitted = 0
            while True:
                # bounded receive (DL007): the engine contract is that
                # every request ends in a FINISH sentinel (even loop
                # death routes through _fail_pending) — but a hung loop
                # must not hang this stream forever. Each timeout polls
                # the request's cancellation; a killed client's stream
                # ends instead of waiting on an engine that stopped
                # answering. The get_nowait fast path keeps the token
                # hot path free of wait_for's per-item task overhead.
                try:
                    item, payload = req.out_queue.get_nowait()
                except asyncio.QueueEmpty:
                    try:
                        item, payload = await asyncio.wait_for(
                            req.out_queue.get(), timeout=30.0)
                    except asyncio.TimeoutError:
                        if req.ctx is not None and req.ctx.is_killed:
                            return
                        continue
                if item is FINISH_SENTINEL:
                    reason: FinishReason = payload
                    if trace is not None:
                        # isl/osl + tenant/session ride the finish
                        # marker so collected traces are exportable as a
                        # replayable workload PRESERVING tenant and
                        # prefix-reuse structure (tools/fleetsim.py
                        # export-trace; ROADMAP sim item (d))
                        trace.event("engine.finish", reason=str(reason),
                                    isl=len(req.prompt), osl=emitted,
                                    tenant=req.tenant or None,
                                    session=req.session or None)
                    yield Annotated.from_data(BackendOutput.final(reason))
                    return
                token, logprob = item, payload
                emitted += 1
                if first:
                    first = False
                    if trace is not None:   # TTFT marker on the trace
                        trace.event("engine.first_token")
                yield Annotated.from_data(BackendOutput(
                    token_ids=[token], log_probs=[logprob],
                    cum_log_probs=None))

        return ResponseStream(stream(), request.ctx)
