from .echo import EchoEngineCore, EchoEngineFull

__all__ = ["EchoEngineCore", "EchoEngineFull"]
