"""KV-aware routed engine: the processor-side client that picks the worker
whose KV cache best overlaps the request's prompt.

Reference: the Router component + KvRouter service (SURVEY.md §3.4,
examples/llm/components/kv_router.py:66-238, lib/llm/src/kv_router/
kv_router.rs:44-140): subscribe the component's ``kv_events`` subject into a
radix-tree indexer, scrape per-instance ForwardPassMetrics, and per request
combine prefix-overlap with load cost to choose an instance — then dispatch
with ``client.direct``. Speaks the token protocol (PreprocessedRequest →
Annotated[BackendOutput]) so it slots into the standard pipeline where a
local engine would sit."""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Set

from ...runtime.distributed import Client, Endpoint
from ...runtime.engine import AsyncEngine, ManyOut, SingleIn
from ..kv_router.protocols import (KV_EVENTS_SUBJECT, KV_HIT_RATE_SUBJECT,
                                   RouterEvent)
from ..kv_router.router import KvRouter
from ..protocols.annotated import decode_annotated_json
from ..protocols.common import BackendOutput

logger = logging.getLogger("dynamo_tpu.llm.kv_routed")

__all__ = ["KvRoutedEngine"]


def _decode_backend_annotated(raw: bytes):
    ann = decode_annotated_json(raw)
    if isinstance(ann.data, dict):
        ann = ann.map_data(BackendOutput.from_dict)
    return ann


class KvRoutedEngine(AsyncEngine):
    def __init__(self, client: Client, router: KvRouter,
                 scrape_interval: float = 1.0):
        self.client = client
        self.router = router
        self.scrape_interval = scrape_interval
        self._tasks: list = []
        self._sub = None
        self._known_workers: Set[int] = set()
        self._hit_component = None
        self._pub_tasks: Set[asyncio.Task] = set()
        # tenant fair-share admission (llm/tenancy.py): a tenant whose
        # in-flight dispatches exceed its fair share of fleet slots
        # WAITS here in WDRR order (QoS classes drain first) instead of
        # starving the fleet — the flooding-tenant throttle. Capacity
        # tracks the scheduler's scraped slot totals live.
        from ..tenancy import FairShareAdmission
        self.admission = FairShareAdmission(
            router.scheduler.fleet_total_slots)
        # observability
        self.kv_hits = 0
        self.kv_routed = 0
        self.fallback_routed = 0

    @classmethod
    async def start(cls, endpoint: Endpoint, block_size: int = 16,
                    scrape_interval: float = 1.0) -> "KvRoutedEngine":
        client = endpoint.client(decode_resp=_decode_backend_annotated)
        router = KvRouter(block_size)
        self = cls(client, router, scrape_interval)
        # per-decision KVHitRateEvents go out on the component's hit-rate
        # subject for the metrics aggregation service (reference
        # scheduler.rs:28-33 → components/metrics subscriber)
        self._hit_component = endpoint.parent_component()
        router.scheduler.on_hit_rate = self._publish_hit_rate
        # attach the membership callback BEFORE the watch starts so no
        # join/leave can slip between discovery replay and the hook
        client.on_instances_changed = self._instances_changed
        await client.start()
        self._known_workers |= set(client.instance_ids())
        self._sub = await self._hit_component.subscribe_event(
            KV_EVENTS_SUBJECT)
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._event_loop(self._sub), name="kvr-events"),
            loop.create_task(self._scrape_loop(), name="kvr-scrape"),
        ]
        return self

    def _publish_hit_rate(self, ev) -> None:
        # keep a strong ref so the loop can't GC the task mid-flight
        # (same discipline as EndpointServer._inflight)
        task = asyncio.get_running_loop().create_task(
            self._hit_component.publish_event(KV_HIT_RATE_SUBJECT,
                                              ev.__dict__),
            name="kvr-hit-rate-pub")
        self._pub_tasks.add(task)
        task.add_done_callback(self._pub_tasks.discard)

    # ---------------------------------------------------------------- feeds
    async def _event_loop(self, sub) -> None:
        async for msg in sub:
            try:
                self.router.on_kv_event(
                    RouterEvent.from_dict(json.loads(msg.payload)))
            except Exception:  # noqa: BLE001
                logger.exception("bad kv event dropped")

    async def _scrape_loop(self) -> None:
        # long-lived task: detach the spawning context's ambient trace
        # (runtime/tracing.py detach_trace contract)
        from ...runtime.tracing import detach_trace
        detach_trace()
        while True:
            try:
                stats = await self.client.collect_stats()
                if stats:
                    self.router.on_metrics(stats)
            except Exception:  # noqa: BLE001
                logger.exception("metrics scrape failed")
            await asyncio.sleep(self.scrape_interval)

    def _instances_changed(self, present: Set[int]) -> None:
        for gone in self._known_workers - present:
            self.router.on_worker_gone(gone)
        self._known_workers = set(present)

    # ------------------------------------------------------------- dispatch
    async def generate(self, request: SingleIn) -> ManyOut:
        tokens = list(request.data.token_ids)
        # tenant identity: the preprocessed payload's fields win, the
        # context's (wire-propagated) identity backs them up
        tenant = (getattr(request.data, "tenant_id", None)
                  or request.ctx.tenant)
        qos = getattr(request.data, "qos", None) or request.ctx.qos
        # fair-share admission BEFORE placement: under contention an
        # over-share tenant queues here in WDRR order; the stream's end
        # releases the slot (tenant-blind placement keeps cache
        # affinity; fairness is a question of WHEN, not WHERE)
        t = await self.admission.acquire(tenant, qos)
        released = False

        def release_once():
            nonlocal released
            if not released:
                released = True
                self.admission.release(t)

        try:
            stream = await self._dispatch(request, tokens, tenant)
        except BaseException:
            release_once()
            raise

        async def tracked():
            try:
                async for item in stream:
                    yield item
            finally:
                release_once()

        from ...runtime.engine import ResponseStream
        return ResponseStream(tracked(), request.ctx)

    async def _dispatch(self, request: SingleIn, tokens,
                        tenant) -> ManyOut:
        # draining instances take no new admissions (docs/planner.md);
        # client.random below applies the same exclusion on fallback
        draining = set(self.client.draining_ids())
        pick = self.router.schedule(tokens, exclude=draining or None,
                                    tenant=tenant)
        if pick is None:
            self.fallback_routed += 1
            return await self.client.random(request)
        worker_id, overlap_blocks = pick
        request.data.estimated_prefix_hit_blocks = overlap_blocks
        request.data.prefix_hit_len = overlap_blocks * self.router.block_size
        if overlap_blocks:
            self.kv_hits += 1
        self.kv_routed += 1
        try:
            return await self.client.direct(request, worker_id)
        except Exception:  # noqa: BLE001 — instance raced away; fall back
            logger.warning("direct dispatch to %x failed; falling back",
                           worker_id)
            # the hints described the failed worker's cache, not the
            # fallback target's — reset so its disagg/prefill planning
            # doesn't skip work it actually has to do
            request.data.estimated_prefix_hit_blocks = 0
            request.data.prefix_hit_len = 0
            self.fallback_routed += 1
            return await self.client.random(request)

    def stats(self) -> dict:
        return {"kv_routed": self.kv_routed, "kv_hits": self.kv_hits,
                "fallback_routed": self.fallback_routed,
                "known_workers": sorted(self._known_workers),
                "tenants": self.admission.counters()}

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.close()
        if self._pub_tasks:  # flush in-flight hit-rate publishes
            await asyncio.gather(*self._pub_tasks, return_exceptions=True)
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        await self.client.close()
