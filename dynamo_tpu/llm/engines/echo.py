"""Echo engines — the no-hardware test engines.

Reference: launch/dynamo-run/src/output/echo_{full,core}.rs and
docs/guides/dynamo_run.md:388-415. `EchoEngineCore` speaks the engine-internal
token protocol (sits behind preprocessor+backend); `EchoEngineFull` speaks
OpenAI directly. Token pacing via DYN_TOKEN_ECHO_DELAY_MS, matching the
reference's env knob.
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator

from ...runtime.engine import AsyncEngine, ManyOut, ResponseStream, SingleIn
from ..protocols.annotated import Annotated
from ..protocols.common import BackendOutput, FinishReason, PreprocessedRequest
from ..protocols.openai import (ChatCompletionRequest, ChatDeltaGenerator,
                                CompletionDeltaGenerator, CompletionRequest,
                                usage_dict)


def _delay_s() -> float:
    return float(os.environ.get("DYN_TOKEN_ECHO_DELAY_MS", "0")) / 1000.0


class EchoEngineCore(AsyncEngine):
    """Echo the prompt's token ids back, one per step."""

    async def generate(self, request: SingleIn) -> ManyOut:
        pre: PreprocessedRequest = request.data
        ctx = request.ctx
        delay = _delay_s()
        max_tokens = pre.stop_conditions.max_tokens

        async def stream() -> AsyncIterator[Annotated[BackendOutput]]:
            emitted = 0
            for tid in pre.token_ids:
                if ctx.is_stopped:
                    break
                if max_tokens is not None and emitted >= max_tokens:
                    break
                if delay:
                    await asyncio.sleep(delay)
                emitted += 1
                yield Annotated.from_data(BackendOutput(token_ids=[tid]))
            if not ctx.is_stopped:
                yield Annotated.from_data(BackendOutput.final(FinishReason.STOP))

        return ResponseStream(stream(), ctx)


class EchoEngineFull(AsyncEngine):
    """Echo the raw prompt text as OpenAI chunks (no tokenizer involved)."""

    async def generate(self, request: SingleIn) -> ManyOut:
        req = request.data
        if isinstance(req, dict):
            req = (ChatCompletionRequest.model_validate(req)
                   if "messages" in req else CompletionRequest.model_validate(req))
        ctx = request.ctx
        delay = _delay_s()
        if isinstance(req, ChatCompletionRequest):
            text = req.messages[-1].text() if req.messages else ""
            gen = ChatDeltaGenerator(req.model, request_id=f"chatcmpl-{request.id}")
        else:
            text = req.prompt if isinstance(req.prompt, str) else ""
            gen = CompletionDeltaGenerator(req.model, request_id=f"cmpl-{request.id}")

        async def stream() -> AsyncIterator[Annotated[dict]]:
            words = text.split(" ")
            emitted = 0
            for word in words:
                if ctx.is_stopped:
                    break
                if delay:
                    await asyncio.sleep(delay)
                yield Annotated.from_data(gen.text_chunk(word + " "))
                emitted += 1
            # word counts stand in for token counts (echo has no tokenizer)
            if isinstance(gen, ChatDeltaGenerator):
                yield Annotated.from_data(gen.finish_chunk(FinishReason.STOP))
                yield Annotated.from_data(gen.usage_chunk(len(words),
                                                          emitted))
            else:
                yield Annotated.from_data(gen.finish_chunk(
                    FinishReason.STOP,
                    usage=usage_dict(len(words), emitted)))

        return ResponseStream(stream(), ctx)
