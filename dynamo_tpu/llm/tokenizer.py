"""Tokenizer wrapper + incremental detokenization.

Reference: lib/llm/src/tokenizers.rs (570 LoC) and tokenizers/hf.rs — a thin
facade over HF `tokenizers` exposing `encode`, `decode`, and a stateful
`DecodeStream` that emits UTF-8-safe text increments token by token. The
incremental decoder mirrors the reference's prefix-offset algorithm: decode a
sliding window, only surface text once it no longer ends in a replacement
character (incomplete UTF-8 / byte-fallback sequence).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Sequence

try:
    from tokenizers import Tokenizer as _HFTokenizer
except ImportError:  # pragma: no cover
    _HFTokenizer = None

_REPLACEMENT = "�"


@dataclasses.dataclass
class Encoding:
    """Reference `Encoding` (tokenizers.rs): ids + offsets view."""

    ids: List[int]
    tokens: Optional[List[str]] = None

    def __len__(self) -> int:
        return len(self.ids)


class HuggingFaceTokenizer:
    """Wraps a `tokenizer.json` (HF tokenizers). Reference tokenizers/hf.rs."""

    def __init__(self, tokenizer: "_HFTokenizer"):
        self._tk = tokenizer

    @classmethod
    def from_file(cls, path: str) -> "HuggingFaceTokenizer":
        if _HFTokenizer is None:
            raise RuntimeError("tokenizers package not available")
        return cls(_HFTokenizer.from_file(path))

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "HuggingFaceTokenizer":
        path = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(path):
            return cls.from_file(path)
        raise FileNotFoundError(f"no tokenizer.json under {model_dir}")

    def encode(self, text: str, add_special_tokens: bool = False) -> Encoding:
        enc = self._tk.encode(text, add_special_tokens=add_special_tokens)
        return Encoding(ids=list(enc.ids), tokens=list(enc.tokens))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tk.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def id_to_token(self, token_id: int) -> Optional[str]:
        return self._tk.id_to_token(token_id)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tk.token_to_id(token)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


class DecodeStream:
    """Stateful incremental detokenizer.

    Reference `DecodeStream` (tokenizers.rs): feed one token id at a time,
    receive the new UTF-8-complete text (or None if the token only partially
    completes a multi-byte character, e.g. byte-fallback tokens).
    """

    def __init__(self, tokenizer, skip_special_tokens: bool = True):
        self._tk = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: List[int] = []
        self._prefix_offset = 0  # start of the context window
        self._read_offset = 0    # everything before this has been emitted

    def step(self, token_id: int) -> Optional[str]:
        self._ids.append(token_id)
        prefix_text = self._tk.decode(
            self._ids[self._prefix_offset:self._read_offset],
            skip_special_tokens=self._skip_special)
        new_text = self._tk.decode(
            self._ids[self._prefix_offset:],
            skip_special_tokens=self._skip_special)
        if new_text.endswith(_REPLACEMENT):
            # Incomplete UTF-8 sequence — hold until more tokens arrive.
            return None
        if len(new_text) <= len(prefix_text):
            # Special token skipped or no visible text yet.
            self._read_offset = len(self._ids)
            return None
        delta = new_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta


class SentencePieceTokenizer:
    """SentencePiece-model tokenizer behind the same interface as
    HuggingFaceTokenizer (reference lib/llm/src/tokenizers/sp.rs — the
    second tokenizer kind the model card can declare). Uses the
    `sentencepiece` package when importable; otherwise the native
    unigram engine (llm/sp_model.py) loads the same .model file, so the
    tokenizer kind works — and is tested — in every image."""

    def __init__(self, processor):
        self._sp = processor

    @classmethod
    def from_file(cls, path: str) -> "SentencePieceTokenizer":
        try:
            import sentencepiece as spm
        except ImportError:
            from .sp_model import NativeSentencePiece
            return cls(NativeSentencePiece.load(path))
        sp = spm.SentencePieceProcessor()
        sp.Load(path)
        return cls(sp)

    def encode(self, text: str, add_special_tokens: bool = False) -> Encoding:
        ids = self._sp.EncodeAsIds(text)
        if add_special_tokens and self._sp.bos_id() >= 0:
            ids = [self._sp.bos_id()] + ids
        return Encoding(ids=list(ids))

    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str:
        if skip_special_tokens:
            control = {i for i in (self._sp.bos_id(), self._sp.eos_id(),
                                   self._sp.pad_id()) if i >= 0}
            ids = [i for i in ids if i not in control]
        return self._sp.DecodeIds(list(ids))

    def id_to_token(self, token_id: int) -> Optional[str]:
        try:
            return self._sp.IdToPiece(int(token_id))
        except Exception:  # noqa: BLE001 — out-of-range ids
            return None

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._sp.PieceToId(token)
        return tid if tid >= 0 else None

    @property
    def vocab_size(self) -> int:
        return int(self._sp.GetPieceSize())

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens=skip_special_tokens)


def load_tokenizer(model_dir_or_file: str):
    """Load from a tokenizer.json / .model path or an HF-style model
    directory; HF tokenizer.json is preferred, sentencepiece
    tokenizer.model is the fallback kind (reference model_card tokenizer
    detection, model_card/create.rs)."""
    if os.path.isdir(model_dir_or_file):
        sp_path = os.path.join(model_dir_or_file, "tokenizer.model")
        if (not os.path.exists(os.path.join(model_dir_or_file,
                                            "tokenizer.json"))
                and os.path.exists(sp_path)):
            return SentencePieceTokenizer.from_file(sp_path)
        return HuggingFaceTokenizer.from_pretrained_dir(model_dir_or_file)
    if model_dir_or_file.endswith(".model"):
        return SentencePieceTokenizer.from_file(model_dir_or_file)
    return HuggingFaceTokenizer.from_file(model_dir_or_file)


def read_special_token_ids(model_dir: str, tokenizer: HuggingFaceTokenizer) -> dict:
    """Pull eos/bos ids out of HF config files (reference model_card/create.rs
    extracts the same from config.json / generation_config.json /
    tokenizer_config.json)."""
    out: dict = {"eos_token_ids": [], "bos_token_id": None}

    def _as_list(v) -> List[int]:
        if v is None:
            return []
        return list(v) if isinstance(v, list) else [v]

    for name in ("generation_config.json", "config.json"):
        path = os.path.join(model_dir, name)
        if os.path.exists(path):
            with open(path) as f:
                cfg = json.load(f)
            eos = _as_list(cfg.get("eos_token_id"))
            if eos and not out["eos_token_ids"]:
                out["eos_token_ids"] = eos
            if out["bos_token_id"] is None and cfg.get("bos_token_id") is not None:
                out["bos_token_id"] = cfg["bos_token_id"]
    tk_cfg = os.path.join(model_dir, "tokenizer_config.json")
    if not out["eos_token_ids"] and os.path.exists(tk_cfg):
        with open(tk_cfg) as f:
            cfg = json.load(f)
        tok = cfg.get("eos_token")
        if isinstance(tok, dict):
            tok = tok.get("content")
        if tok is not None:
            tid = tokenizer.token_to_id(tok)
            if tid is not None:
                out["eos_token_ids"] = [tid]
    return out
