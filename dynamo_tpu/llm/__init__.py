"""LLM library layer (reference: lib/llm, the dynamo-llm crate)."""
