"""Service-level objectives for the dynamic planner.

Reference: the Planner pillar (README "dynamic GPU scheduling") that the
reference ships as ``deploy/sdk/.../planner`` — declared latency/load
targets drive replica counts of the disaggregated prefill/decode fleet.
FlowKV/NetKV (PAPERS.md) motivate the signal set: decode-side queue depth
and KV-pool pressure are the leading indicators; TTFT/ITL percentiles are
the lagging, user-visible truth.

This module is the PURE half of the planner: the SLO schema, the KV-store
key layout (SLO / control / status / scale intents), the fleet-signal
snapshot, and the ``evaluate`` function mapping (signals, slo) → verdict.
The standing control loop with hysteresis/cooldown and the actuators live
in :mod:`dynamo_tpu.components.planner`.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional

__all__ = [
    "ServiceLevelObjective", "FleetSignals", "SloVerdict", "evaluate",
    "percentile", "latency_percentiles_from_traces",
    "latency_percentiles",
    "slo_key", "control_key", "status_key", "scale_key", "PLANNER_PREFIX",
]

PLANNER_PREFIX = "planner/"


def slo_key(namespace: str) -> str:
    """Declared SLOs; watched live by the planner (llmctl set-slo)."""
    return f"{PLANNER_PREFIX}slo/{namespace}"


def control_key(namespace: str) -> str:
    """Admin control record ({"paused": bool}; llmctl planner pause)."""
    return f"{PLANNER_PREFIX}control/{namespace}"


def status_key(namespace: str) -> str:
    """The planner's periodically-published status snapshot (llmctl
    planner status and the metrics service's /planner endpoint read it)."""
    return f"{PLANNER_PREFIX}status/{namespace}"


def scale_key(service: str) -> str:
    """Desired-replica intents the sdk/serve.py supervisor watches."""
    return f"{PLANNER_PREFIX}scale/{service}"


@dataclasses.dataclass
class ServiceLevelObjective:
    """Declared targets + scaling bounds. All latencies are milliseconds.

    The utilization watermarks are deliberately far apart (0.85 up /
    0.25 down): together with the planner's breach-cycle hysteresis and
    post-action cooldown they keep the loop from flapping under
    oscillating load."""

    ttft_p90_ms: float = 2000.0
    itl_p90_ms: float = 200.0
    # mean waiting requests per NON-draining decode worker
    max_queue_depth: float = 4.0
    slot_util_high: float = 0.85
    slot_util_low: float = 0.25
    kv_util_high: float = 0.90
    min_decode_workers: int = 1
    max_decode_workers: int = 8
    min_prefill_workers: int = 0
    max_prefill_workers: int = 4
    # baseline disagg threshold the retune actuator works around
    max_local_prefill_length: int = 512

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ServiceLevelObjective":
        d = json.loads(raw)
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass
class FleetSignals:
    """One evaluation's view of the live fleet (draining workers are
    excluded from capacity math — they take no new admissions, so counting
    them would mask pressure during a drain)."""

    n_decode: int = 0                 # non-draining decode workers
    n_draining: int = 0
    queue_depth: float = 0.0          # mean num_requests_waiting per worker
    slot_util: float = 0.0            # mean active/total slots
    kv_util: float = 0.0              # mean gpu_cache_usage_perc
    ttft_p90_ms: Optional[float] = None
    itl_p90_ms: Optional[float] = None
    prefill_queue_depth: int = 0

    @classmethod
    def from_worker_metrics(cls, metrics: Dict[int, dict],
                            draining: Optional[set] = None,
                            ttft_p90_ms: Optional[float] = None,
                            itl_p90_ms: Optional[float] = None,
                            prefill_queue_depth: int = 0) -> "FleetSignals":
        """Aggregate scraped ForwardPassMetrics dicts (worker_id → dict)."""
        draining = draining or set()
        live = {w: m for w, m in metrics.items() if w not in draining}
        n = len(live)
        if n == 0:
            return cls(n_decode=0, n_draining=len(draining),
                       ttft_p90_ms=ttft_p90_ms, itl_p90_ms=itl_p90_ms,
                       prefill_queue_depth=prefill_queue_depth)
        q = su = kv = 0.0
        for m in live.values():
            q += float(m.get("num_requests_waiting", 0))
            total = float(m.get("request_total_slots", 0)) or 1.0
            su += float(m.get("request_active_slots", 0)) / total
            kv += float(m.get("gpu_cache_usage_perc", 0.0))
        return cls(n_decode=n, n_draining=len(draining),
                   queue_depth=q / n, slot_util=su / n, kv_util=kv / n,
                   ttft_p90_ms=ttft_p90_ms, itl_p90_ms=itl_p90_ms,
                   prefill_queue_depth=prefill_queue_depth)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SloVerdict:
    """Outcome of one evaluation. ``action`` is the RAW per-cycle verdict;
    the planner applies hysteresis (consecutive breach cycles) and
    cooldown before actuating."""

    action: str                        # "scale_up" | "scale_down" | "hold"
    breaches: List[str] = dataclasses.field(default_factory=list)
    reason: str = ""


def evaluate(signals: FleetSignals,
             slo: ServiceLevelObjective) -> SloVerdict:
    """Pure decision function: compare one signal snapshot against the SLO.

    Scale-up triggers on ANY pressure breach (queue, slots, KV pool, TTFT,
    ITL) while below max replicas. Scale-down requires EVERY pressure
    signal comfortably idle and replicas above min. Anything else holds."""
    b: List[str] = []
    if signals.n_decode == 0:
        return SloVerdict("scale_up", ["no_workers"],
                          "no live decode workers")
    if signals.queue_depth > slo.max_queue_depth:
        b.append(f"queue_depth {signals.queue_depth:.1f} > "
                 f"{slo.max_queue_depth:g}")
    if signals.slot_util > slo.slot_util_high:
        b.append(f"slot_util {signals.slot_util:.2f} > "
                 f"{slo.slot_util_high:g}")
    if signals.kv_util > slo.kv_util_high:
        b.append(f"kv_util {signals.kv_util:.2f} > {slo.kv_util_high:g}")
    if signals.ttft_p90_ms is not None \
            and signals.ttft_p90_ms > slo.ttft_p90_ms:
        b.append(f"ttft_p90 {signals.ttft_p90_ms:.0f}ms > "
                 f"{slo.ttft_p90_ms:g}ms")
    if signals.itl_p90_ms is not None \
            and signals.itl_p90_ms > slo.itl_p90_ms:
        b.append(f"itl_p90 {signals.itl_p90_ms:.0f}ms > "
                 f"{slo.itl_p90_ms:g}ms")
    if b:
        if signals.n_decode >= slo.max_decode_workers:
            return SloVerdict("hold", b,
                              "pressure but already at max_decode_workers")
        return SloVerdict("scale_up", b, "; ".join(b))
    idle = (signals.queue_depth == 0
            and signals.slot_util < slo.slot_util_low
            and (signals.ttft_p90_ms is None
                 or signals.ttft_p90_ms < 0.5 * slo.ttft_p90_ms))
    if idle and signals.n_decode > slo.min_decode_workers:
        return SloVerdict(
            "scale_down", [],
            f"idle: slot_util {signals.slot_util:.2f} < "
            f"{slo.slot_util_low:g}, empty queue")
    return SloVerdict("hold", [], "within SLO")


# --------------------------------------------------------------- latencies
def percentile(values: List[float], p: float) -> Optional[float]:
    """Nearest-rank percentile (p in [0, 100]); None on empty input."""
    if not values:
        return None
    xs = sorted(values)
    rank = max(int(math.ceil(p / 100.0 * len(xs))) - 1, 0)
    return xs[min(rank, len(xs) - 1)]


def latency_percentiles_from_traces(traces: List[dict], p: float = 90.0
                                    ) -> Dict[str, Optional[float]]:
    """TTFT/ITL percentiles (ms) out of tracer ring-buffer dicts
    (runtime/tracing.py). TTFT is the ``first_response`` event offset on
    worker-role traces; ITL is the remaining stream time spread over the
    ``respond`` span after first response (an upper bound when the token
    count is unknown — traces don't carry it, so we approximate with the
    respond span's shape: (respond_end - first_response))."""
    ttfts: List[float] = []
    itls: List[float] = []
    for t in traces:
        spans = {s["name"]: s for s in t.get("spans", ())}
        first = spans.get("first_response")
        if first is None:
            continue
        ttfts.append(first["at_ms"])
        respond = spans.get("respond")
        if respond is not None:
            tail = respond["at_ms"] + respond["ms"] - first["at_ms"]
            if tail >= 0:
                itls.append(tail)
    return {"ttft_p_ms": percentile(ttfts, p),
            "itl_p_ms": percentile(itls, p),
            "n_traces": float(len(ttfts))}


def latency_percentiles(p: float = 90.0, collector=None,
                        traces: Optional[List[dict]] = None
                        ) -> Dict[str, Optional[float]]:
    """FLEET-wide latency percentiles with local fallback: prefer the
    trace collector's window (components/trace_collector.py — fed by
    every worker's published traces, so the planner scales on what the
    whole fleet experienced), fall back to the frontend-local tracer
    ring when no collector is wired or it hasn't seen traffic yet.
    The SLO inputs degrade gracefully instead of flipping to None."""
    if collector is not None:
        try:
            d = collector.latency_percentiles(p)
        except Exception:  # noqa: BLE001 — observability never breaks SLOs
            d = None
        if d and d.get("n_traces"):
            return d
    return latency_percentiles_from_traces(traces or [], p)
