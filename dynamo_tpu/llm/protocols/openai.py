"""OpenAI wire protocol: requests, responses, streaming deltas, aggregation.

Reference: lib/llm/src/protocols/openai/{chat_completions,completions}.rs with
their delta generators and SSE aggregators (delta.rs, aggregator.rs:32-113 test
semantics) and nvext.rs:28-193. Pydantic models give request validation at the
HTTP edge; everything internal stays dataclass/dict.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict

from .annotated import Annotated
from .common import FinishReason

# ---------------------------------------------------------------------------
# nvext — framework extension fields (reference nvext.rs:28-193)
# ---------------------------------------------------------------------------


class NvExt(BaseModel):
    model_config = ConfigDict(extra="allow")

    ignore_eos: Optional[bool] = None
    use_raw_prompt: Optional[bool] = None
    annotations: Optional[List[str]] = None
    greed_sampling: Optional[bool] = None
    top_k: Optional[int] = None
    repetition_penalty: Optional[float] = None
    # speculative decoding: max draft tokens verified per step (None =
    # engine default, 0 = off; clamped to the worker's compiled maximum)
    speculation: Optional[int] = None
    # multi-tenant serving plane (llm/tenancy.py; docs/multi_tenant.md):
    # tenant id + QoS class ride the wire into the router's fair-share
    # admission and the tiers' per-tenant quota accounting. priority is
    # one of "interactive" | "standard" | "batch" (unknown values fall
    # back to the tenant's default class). session_id groups requests
    # for prefix-reuse structure (fleetsim export-trace preserves it).
    tenant: Optional[str] = None
    priority: Optional[str] = None
    session_id: Optional[str] = None


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")

    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        parts = []
        for part in self.content:
            if part.get("type") == "text":
                parts.append(part.get("text", ""))
        return "".join(parts)


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    """`POST /v1/chat/completions` body (reference
    NvCreateChatCompletionRequest: async-openai CreateChatCompletionRequest +
    nvext)."""

    model_config = ConfigDict(extra="allow")

    model: str
    messages: List[ChatMessage]
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: Optional[int] = 1
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    stop: Optional[Union[str, List[str]]] = None
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    logit_bias: Optional[Dict[str, float]] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    seed: Optional[int] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    parallel_tool_calls: Optional[bool] = None
    response_format: Optional[Dict[str, Any]] = None
    nvext: Optional[NvExt] = None

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self) -> Optional[int]:
        if self.max_completion_tokens is not None:
            return self.max_completion_tokens
        return self.max_tokens


class CompletionRequest(BaseModel):
    """`POST /v1/completions` body."""

    model_config = ConfigDict(extra="allow")

    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    suffix: Optional[str] = None
    max_tokens: Optional[int] = 16
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: Optional[int] = 1
    stream: Optional[bool] = False
    stream_options: Optional[StreamOptions] = None
    logprobs: Optional[int] = None
    echo: Optional[bool] = False
    stop: Optional[Union[str, List[str]]] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    best_of: Optional[int] = None
    user: Optional[str] = None
    seed: Optional[int] = None
    nvext: Optional[NvExt] = None

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


# ---------------------------------------------------------------------------
# Responses (plain dict builders — hot path, no pydantic validation cost)
# ---------------------------------------------------------------------------


def _now() -> int:
    return int(time.time())


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


class ChatDeltaGenerator:
    """Builds `chat.completion.chunk` dicts from engine text deltas.

    Reference: the chat delta generator (protocols/openai/chat_completions/delta.rs).
    One generator per request; emits the role-bearing first chunk lazily.
    """

    def __init__(self, model: str, request_id: Optional[str] = None,
                 n_choices: int = 1):
        self.id = request_id or f"chatcmpl-{uuid.uuid4().hex}"
        self.model = model
        self.created = _now()
        self.n_choices = n_choices
        self._sent_role = [False] * n_choices
        self.object = "chat.completion.chunk"

    def _chunk(self, choices: List[dict], usage: Optional[dict] = None) -> dict:
        out = {
            "id": self.id,
            "object": self.object,
            "created": self.created,
            "model": self.model,
            "choices": choices,
        }
        if usage is not None:
            out["usage"] = usage
        return out

    def role_chunk(self, index: int = 0) -> dict:
        self._sent_role[index] = True
        return self._chunk([{
            "index": index,
            "delta": {"role": "assistant", "content": ""},
            "finish_reason": None,
        }])

    def text_chunk(self, text: str, index: int = 0,
                   logprobs: Optional[dict] = None) -> dict:
        delta: dict = {"content": text}
        if not self._sent_role[index]:
            delta["role"] = "assistant"
            self._sent_role[index] = True
        choice: dict = {"index": index, "delta": delta, "finish_reason": None}
        if logprobs is not None:
            choice["logprobs"] = logprobs
        return self._chunk([choice])

    def tool_calls_chunk(self, calls: List[dict], index: int = 0) -> dict:
        """One delta carrying the parsed tool calls, followed (by the
        caller) by a finish chunk with reason "tool_calls"."""
        delta: dict = {"tool_calls": [
            {**call, "index": i} for i, call in enumerate(calls)]}
        if not self._sent_role[index]:
            delta["role"] = "assistant"
            self._sent_role[index] = True
        return self._chunk([{"index": index, "delta": delta,
                             "finish_reason": None}])

    def finish_chunk(self, reason: FinishReason, index: int = 0) -> dict:
        return self._chunk([{
            "index": index,
            "delta": {},
            "finish_reason": reason.to_openai(),
        }])

    def usage_chunk(self, prompt_tokens: int, completion_tokens: int) -> dict:
        return self._chunk([], usage=usage_dict(prompt_tokens, completion_tokens))


class CompletionDeltaGenerator:
    """Builds `text_completion` streaming chunks."""

    def __init__(self, model: str, request_id: Optional[str] = None):
        self.id = request_id or f"cmpl-{uuid.uuid4().hex}"
        self.model = model
        self.created = _now()
        self.object = "text_completion"

    def text_chunk(self, text: str, index: int = 0,
                   logprobs: Optional[dict] = None) -> dict:
        choice: dict = {"index": index, "text": text, "finish_reason": None}
        if logprobs is not None:
            choice["logprobs"] = logprobs
        return {
            "id": self.id, "object": self.object, "created": self.created,
            "model": self.model, "choices": [choice],
        }

    def finish_chunk(self, reason: FinishReason, index: int = 0,
                     usage: Optional[dict] = None) -> dict:
        out = {
            "id": self.id, "object": self.object, "created": self.created,
            "model": self.model,
            "choices": [{"index": index, "text": "", "finish_reason": reason.to_openai()}],
        }
        if usage is not None:
            out["usage"] = usage
        return out


# ---------------------------------------------------------------------------
# Aggregators: fold a stream of chunks back into a unary response
# (reference protocols/openai/*/aggregator.rs; conformance: tests/aggregators.rs)
# ---------------------------------------------------------------------------


async def aggregate_chat_stream(stream) -> dict:
    """Fold `Annotated[chunk-dict]` into one `chat.completion` response."""
    base: Optional[dict] = None
    texts: Dict[int, List[str]] = {}
    roles: Dict[int, str] = {}
    finish: Dict[int, Optional[str]] = {}
    tool_calls: Dict[int, list] = {}
    logprobs: Dict[int, list] = {}
    usage: Optional[dict] = None
    async for ann in stream:
        if isinstance(ann, Annotated):
            if ann.is_error:
                raise RuntimeError(ann.error_message())
            chunk = ann.data
        else:
            chunk = ann
        if chunk is None:
            continue
        if base is None:
            base = {k: chunk.get(k) for k in ("id", "created", "model")}
        if chunk.get("usage"):
            usage = chunk["usage"]
        for choice in chunk.get("choices", []):
            idx = choice.get("index", 0)
            delta = choice.get("delta", {})
            if delta.get("role"):
                roles[idx] = delta["role"]
            if delta.get("content"):
                texts.setdefault(idx, []).append(delta["content"])
            if delta.get("tool_calls"):
                tool_calls.setdefault(idx, []).extend(delta["tool_calls"])
            if (choice.get("logprobs") or {}).get("content"):
                logprobs.setdefault(idx, []).extend(
                    choice["logprobs"]["content"])
            if choice.get("finish_reason"):
                finish[idx] = choice["finish_reason"]
    if base is None:
        raise RuntimeError("empty response stream")
    indices = sorted(set(texts) | set(finish) | set(roles) | {0})
    choices = []
    for idx in indices:
        message: dict = {
            "role": roles.get(idx, "assistant"),
            "content": "".join(texts.get(idx, [])),
        }
        if tool_calls.get(idx):
            message["tool_calls"] = tool_calls[idx]
        choice = {
            "index": idx,
            "message": message,
            "finish_reason": finish.get(idx, "stop"),
        }
        if logprobs.get(idx):
            choice["logprobs"] = {"content": logprobs[idx]}
        choices.append(choice)
    out = {
        "id": base["id"], "object": "chat.completion",
        "created": base["created"], "model": base["model"],
        "choices": choices,
    }
    if usage is not None:
        out["usage"] = usage
    return out


async def aggregate_completion_stream(stream) -> dict:
    base: Optional[dict] = None
    texts: Dict[int, List[str]] = {}
    finish: Dict[int, Optional[str]] = {}
    lp_tokens: Dict[int, list] = {}
    lp_values: Dict[int, list] = {}
    usage: Optional[dict] = None
    async for ann in stream:
        if isinstance(ann, Annotated):
            if ann.is_error:
                raise RuntimeError(ann.error_message())
            chunk = ann.data
        else:
            chunk = ann
        if chunk is None:
            continue
        if base is None:
            base = {k: chunk.get(k) for k in ("id", "created", "model")}
        if chunk.get("usage"):
            usage = chunk["usage"]
        for choice in chunk.get("choices", []):
            idx = choice.get("index", 0)
            if choice.get("text"):
                texts.setdefault(idx, []).append(choice["text"])
            lp = choice.get("logprobs") or {}
            if lp.get("token_logprobs"):
                lp_values.setdefault(idx, []).extend(lp["token_logprobs"])
                lp_tokens.setdefault(idx, []).extend(lp.get("tokens", []))
            if choice.get("finish_reason"):
                finish[idx] = choice["finish_reason"]
    if base is None:
        raise RuntimeError("empty response stream")
    indices = sorted(set(texts) | set(finish) | {0})
    choices = []
    for idx in indices:
        choice = {
            "index": idx,
            "text": "".join(texts.get(idx, [])),
            "finish_reason": finish.get(idx, "stop"),
        }
        if lp_values.get(idx):
            choice["logprobs"] = {"token_logprobs": lp_values[idx],
                                  "tokens": lp_tokens.get(idx, [])}
        choices.append(choice)
    out = {
        "id": base["id"], "object": "text_completion",
        "created": base["created"], "model": base["model"],
        "choices": choices,
    }
    if usage is not None:
        out["usage"] = usage
    return out
