"""Server-Sent Events codec with the edge-case semantics the reference's
conformance tests pin down (lib/llm/src/protocols/codec.rs:52-754,
lib/llm/tests/aggregators.rs:32-113): multi-line `data:` fields are joined
with newlines, comment lines (leading `:`) are preserved out-of-band,
`[DONE]` terminates, and invalid JSON in a data field surfaces as an error
event rather than a crash.
"""

from __future__ import annotations

import dataclasses
import json
from typing import AsyncIterator, Iterator, List, Optional

from .annotated import Annotated

DONE_SENTINEL = "[DONE]"


@dataclasses.dataclass
class SseEvent:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: List[str] = dataclasses.field(default_factory=list)

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE_SENTINEL


def encode_event(data: Optional[str] = None, event: Optional[str] = None,
                 id: Optional[str] = None, comments: Optional[List[str]] = None) -> str:
    """Encode one SSE event block (trailing blank line included)."""
    lines: List[str] = []
    for c in comments or []:
        for part in c.split("\n"):
            lines.append(f": {part}")
    if event is not None:
        lines.append(f"event: {event}")
    if id is not None:
        lines.append(f"id: {id}")
    if data is not None:
        for part in data.split("\n"):
            lines.append(f"data: {part}")
    return "\n".join(lines) + "\n\n"


def encode_annotated(ann: Annotated, data_encoder=json.dumps) -> str:
    data = None if ann.data is None else data_encoder(ann.data)
    return encode_event(data=data, event=ann.event, id=ann.id, comments=ann.comment)


def encode_done() -> str:
    return encode_event(data=DONE_SENTINEL)


class SseParser:
    """Incremental line-oriented SSE parser (push text in, pull events out)."""

    def __init__(self) -> None:
        self._buf = ""
        self._cur = SseEvent()
        self._data_lines: List[str] = []

    def push(self, text: str) -> Iterator[SseEvent]:
        self._buf += text
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            ev = self._push_line(line.rstrip("\r"))
            if ev is not None:
                yield ev

    def _push_line(self, line: str) -> Optional[SseEvent]:
        if line == "":
            if (self._data_lines or self._cur.event or self._cur.id
                    or self._cur.comments):
                ev = self._cur
                ev.data = "\n".join(self._data_lines) if self._data_lines else None
                self._cur = SseEvent()
                self._data_lines = []
                return ev
            return None
        if line.startswith(":"):
            self._cur.comments.append(line[1:].lstrip(" "))
            return None
        if ":" in line:
            field, value = line.split(":", 1)
            value = value.lstrip(" ")
        else:
            field, value = line, ""
        if field == "data":
            self._data_lines.append(value)
        elif field == "event":
            self._cur.event = value
        elif field == "id":
            self._cur.id = value
        # unknown fields are ignored per the SSE spec
        return None

    def finish(self) -> Optional[SseEvent]:
        """Flush a trailing event not terminated by a blank line (the final
        line itself may also lack its newline, so push two)."""
        for ev in self.push("\n\n"):
            return ev
        return None


def event_to_annotated(ev: SseEvent) -> Annotated[dict]:
    """Decode a parsed SSE event into Annotated[dict]; malformed JSON becomes
    an error element (reference codec behavior, not an exception)."""
    if ev.is_done:
        return Annotated(event="done")
    ann: Annotated[dict] = Annotated(id=ev.id, event=ev.event,
                                     comment=ev.comments or None)
    if ev.data is not None:
        try:
            ann.data = json.loads(ev.data)
        except json.JSONDecodeError as e:
            return Annotated.from_error(f"invalid JSON in SSE data: {e}")
    return ann


async def parse_sse_stream(chunks: AsyncIterator[bytes]) -> AsyncIterator[Annotated[dict]]:
    """Parse an async byte stream into Annotated dicts; stops at [DONE].
    UTF-8 is decoded incrementally so multi-byte characters split across
    network chunks survive."""
    import codecs
    decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
    parser = SseParser()
    async for chunk in chunks:
        for ev in parser.push(decoder.decode(chunk)):
            if ev.is_done:
                return
            yield event_to_annotated(ev)
    tail_text = decoder.decode(b"", final=True)
    if tail_text:
        for ev in parser.push(tail_text):
            if ev.is_done:
                return
            yield event_to_annotated(ev)
    tail = parser.finish()
    if tail is not None and not tail.is_done:
        yield event_to_annotated(tail)
