"""SSE-shaped stream element carrying data, errors and annotations out-of-band.

Reference: ``Annotated<R>`` (lib/runtime/src/protocols/annotated.rs:32-150).
Every response stream in the framework is a stream of ``Annotated`` items so
that errors and metadata (e.g. the preprocessor's ``token_ids`` annotation)
ride the same channel as data without corrupting the payload type.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Generic, List, Optional, TypeVar

R = TypeVar("R")

ERROR_EVENT = "error"


@dataclasses.dataclass
class Annotated(Generic[R]):
    data: Optional[R] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[List[str]] = None

    @classmethod
    def from_data(cls, data: R) -> "Annotated[R]":
        return cls(data=data)

    @classmethod
    def from_error(cls, message: str) -> "Annotated[R]":
        return cls(event=ERROR_EVENT, comment=[message])

    @classmethod
    def from_annotation(cls, event: str, value: Any) -> "Annotated[R]":
        return cls(event=event, comment=[json.dumps(value)])

    @property
    def is_error(self) -> bool:
        return self.event == ERROR_EVENT

    def error_message(self) -> Optional[str]:
        if not self.is_error:
            return None
        return "; ".join(self.comment or ["unknown error"])

    def map_data(self, fn) -> "Annotated":
        if self.data is None:
            return Annotated(None, self.id, self.event, self.comment)
        return Annotated(fn(self.data), self.id, self.event, self.comment)

    def to_json_dict(self, data_encoder=None) -> dict:
        out: dict = {}
        if self.data is not None:
            out["data"] = data_encoder(self.data) if data_encoder else self.data
        if self.id is not None:
            out["id"] = self.id
        if self.event is not None:
            out["event"] = self.event
        if self.comment:
            out["comment"] = self.comment
        return out


# Wire serde for the distributed response plane: workers stream
# Annotated[dict] items; the frontend client reconstructs them so errors
# and annotations survive the hop (the reference streams the same
# Annotated JSON over its TCP response plane).

def encode_annotated_json(item) -> bytes:
    if not isinstance(item, Annotated):
        item = Annotated.from_data(item)
    enc = (dataclasses.asdict
           if dataclasses.is_dataclass(item.data) else None)
    return json.dumps(item.to_json_dict(data_encoder=enc)).encode()


def decode_annotated_json(raw: bytes) -> "Annotated":
    d = json.loads(raw)
    return Annotated(data=d.get("data"), id=d.get("id"),
                     event=d.get("event"), comment=d.get("comment"))
