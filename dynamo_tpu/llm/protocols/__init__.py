from .annotated import Annotated
from .common import (BackendInput, BackendOutput, FinishReason,
                     LLMEngineOutput, OutputOptions, PreprocessedRequest,
                     SamplingOptions, StopConditions)

__all__ = [
    "Annotated", "BackendInput", "BackendOutput", "FinishReason",
    "LLMEngineOutput", "OutputOptions", "PreprocessedRequest",
    "SamplingOptions", "StopConditions",
]
