"""Engine-internal request/response protocol.

Reference: lib/llm/src/protocols/common.rs:43-650 (StopConditions,
SamplingOptions, OutputOptions, FinishReason) and common/llm_backend.rs:20-127
(BackendInput/BackendOutput/LLMEngineOutput). These are the types that flow
between the OpenAI preprocessor, the detokenizing Backend operator, and the
model engine — token ids in, token ids (+ optional text) out.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional


class FinishReason(str, enum.Enum):
    """Reference FinishReason (protocols/common.rs): why a stream ended."""

    EOS = "eos"
    LENGTH = "length"
    STOP = "stop"
    ERROR = "error"
    CANCELLED = "cancelled"
    TOOL_CALLS = "tool_calls"

    def to_openai(self) -> str:
        if self in (FinishReason.EOS, FinishReason.STOP):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        if self is FinishReason.TOOL_CALLS:
            return "tool_calls"
        return "error" if self is FinishReason.ERROR else "stop"


@dataclasses.dataclass
class StopConditions:
    """Reference StopConditions (protocols/common.rs:43+)."""

    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    stop: Optional[List[str]] = None
    stop_token_ids_hidden: Optional[List[int]] = None
    ignore_eos: bool = False

    def apply_ignore_eos(self) -> None:
        """ignore_eos means the hidden EOS stop-ids must not fire
        (reference common.rs `apply_ignore_eos`)."""
        if self.ignore_eos:
            self.stop_token_ids_hidden = []


@dataclasses.dataclass
class SamplingOptions:
    """Reference SamplingOptions (protocols/common.rs)."""

    n: int = 1
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    greedy: bool = False


@dataclasses.dataclass
class OutputOptions:
    """Reference OutputOptions: what the engine should attach per token."""

    logprobs: Optional[int] = None
    prompt_logprobs: Optional[int] = None
    echo: bool = False
    skip_special_tokens: bool = True


@dataclasses.dataclass
class PreprocessedRequest:
    """The canonical engine input (reference ``PreprocessedRequest`` =
    ``BackendInput``, lib/llm/src/protocols/common/preprocessor.rs:25)."""

    token_ids: List[int]
    stop_conditions: StopConditions = dataclasses.field(default_factory=StopConditions)
    sampling_options: SamplingOptions = dataclasses.field(default_factory=SamplingOptions)
    output_options: OutputOptions = dataclasses.field(default_factory=OutputOptions)
    eos_token_ids: List[int] = dataclasses.field(default_factory=list)
    mdc_sum: Optional[str] = None
    annotations: List[str] = dataclasses.field(default_factory=list)
    # Disaggregation extensions (ours; reference carries these in nvext /
    # RemotePrefillParams, container/deps/vllm patch:3584-3645):
    prefix_hit_len: int = 0
    estimated_prefix_hit_blocks: int = 0
    # Speculative decoding (engine/spec/, docs/speculative.md): max
    # draft tokens verified per step for this request. None = the
    # engine's live default (llmctl spec set-k); 0 = explicitly off;
    # n > 0 clamps to the engine's compiled maximum (EngineConfig
    # spec_k). Surfaces as nvext.speculation on the OpenAI edge.
    speculation: Optional[int] = None
    # Multi-tenant serving plane (llm/tenancy.py, appended — DL004
    # append-only evolution): tenant id + QoS class ("interactive" |
    # "standard" | "batch") from nvext.tenant/nvext.priority — the
    # router's fair-share admission and the KV tiers' per-tenant quota
    # accounting key on these; session_id (nvext.session_id) groups
    # requests so exported traces preserve prefix-reuse structure
    # (tools/fleetsim.py export-trace). None = the implicit single
    # tenant (old senders decode unchanged).
    tenant_id: Optional[str] = None
    qos: Optional[str] = None
    session_id: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict) -> "PreprocessedRequest":
        """Wire decode for the token protocol (processor → worker hop)."""
        d = dict(d)
        if isinstance(d.get("stop_conditions"), dict):
            d["stop_conditions"] = StopConditions(**d["stop_conditions"])
        if isinstance(d.get("sampling_options"), dict):
            d["sampling_options"] = SamplingOptions(**d["sampling_options"])
        if isinstance(d.get("output_options"), dict):
            d["output_options"] = OutputOptions(**d["output_options"])
        return cls(**d)


BackendInput = PreprocessedRequest


@dataclasses.dataclass
class BackendOutput:
    """One step of engine output (reference ``BackendOutput`` /
    ``LLMEngineOutput``, common/llm_backend.rs:20-127)."""

    token_ids: List[int] = dataclasses.field(default_factory=list)
    tokens: Optional[List[str]] = None
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    log_probs: Optional[List[float]] = None
    top_logprobs: Optional[List[Dict[int, float]]] = None
    finish_reason: Optional[FinishReason] = None
    # serving metrics piggybacked on the final chunk
    kv_transfer_us: Optional[int] = None

    @classmethod
    def final(cls, reason: FinishReason) -> "BackendOutput":
        return cls(finish_reason=reason)

    @classmethod
    def from_dict(cls, d: dict) -> "BackendOutput":
        d = dict(d)
        if d.get("finish_reason") is not None:
            d["finish_reason"] = FinishReason(d["finish_reason"])
        return cls(**d)


LLMEngineOutput = BackendOutput


@dataclasses.dataclass
class ParsedChatMessage:
    role: str
    content: str
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
