"""Disaggregation protocols: remote-prefill requests and the KV handoff
wire format.

Reference: the vLLM patch's ``RemotePrefillRequest{request_id,
prompt_token_ids, sampling_params, block_ids, engine_id}`` and
``RemotePrefillParams`` (container/deps/vllm patch:3584-3645), plus the NATS
JetStream prefill queue (examples/llm/utils/prefill_queue.py:24-56).

TPU-native redesign of the KV *transfer* itself: the reference moves blocks
with NIXL RDMA writes into the decode engine's VRAM (patch nixl.py). Here the
prefill worker dials the decode worker's TCP stream server (the same response
plane every request already uses, runtime/tcp.py) and streams the gathered
block values; the decode side scatters them into its paged HBM pool. Within
a slice this is ICI-adjacent host staging; across slices it is DCN — both
ride TPU-VM DRAM, which is the pinned tier (SURVEY.md §5.8). TP-reshard on
handoff is free: the payload is the *unsharded* logical block array, and the
decode engine's scatter re-shards it under its own mesh (the analog of
``permute_scatter_memcpy``, block_copy.cu:558-728, done by XLA instead).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

import numpy as np

__all__ = ["RemotePrefillRequest", "PrefillPublishRequest", "KvPayload",
           "KV_CHUNK_BYTES", "encode_kv_payload", "decode_kv_payload"]

# One KV handoff can be GBs for long prompts (a Llama-8B-class model is
# ~128 KB of K+V per token); split it across frames so no single frame
# approaches the codec's MAX_FRAME bound (runtime/codec.py). The first
# frame carries the metadata header; the rest are continuation chunks, and
# the stream's SENTINEL marks completion.
KV_CHUNK_BYTES = 64 * 1024 * 1024


@dataclasses.dataclass
class RemotePrefillRequest:
    """One unit of work on the prefill queue."""

    request_id: str
    token_ids: List[int]
    sampling: Dict                 # SlotSampling fields
    connection_info: Dict          # decode worker's KV-sink stream (addr+id)
    engine_id: str = ""            # decode worker identity (diagnostics)
    prefix_hit_tokens: int = 0     # decode-side estimate (router metric)
    # decode process's kv_transport.PROC_TOKEN: a prefill worker in the
    # SAME process takes the device-to-device bulk plane (ICI) and sends
    # only a control frame over TCP; others stream the wire payload
    device_bridge: str = ""
    # distributed-tracing propagation (runtime/tracing.py TraceContext):
    # the prefill worker opens its trace as a CHILD of the decode-side
    # request trace, so the disagg handoff appears inside the one fleet
    # tree instead of as a disjoint prefill-side trace. None on old
    # senders; ignored by old receivers (from_json passes it through).
    trace: Optional[Dict] = None
    # end-to-end deadline (docs/chaos.md): the REMAINING budget in ms at
    # enqueue time — the prefill worker re-anchors it to its own clock
    # and drops the job unstarted when the budget is already gone (the
    # decode side has long since cancelled). None on old senders.
    deadline_ms: Optional[float] = None
    # streaming layer-wise KV handoff (llm/kv/stream.py): the decode
    # side can consume per-layer DATA frames (manifest + one frame per
    # layer) on the wire plane — the prefill worker streams each layer
    # as it fetches instead of one monolithic payload. False on old
    # senders (and ignored on the device plane, whose ICI bulk deposit
    # never serializes at all); the producer may still degrade to the
    # monolithic payload mid-stream (torn frame), byte-identically.
    layer_stream: bool = False

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "RemotePrefillRequest":
        return cls(**json.loads(raw))


@dataclasses.dataclass
class PrefillPublishRequest:
    """One unit of work on the prefill-PUBLISH queue (components/
    prefill_service.py): run prefill and publish the prompt's prefix KV
    to the shared object tier — no per-request decode sink, no handoff
    stream. Decode fleets anywhere admit the published prefix through
    the remote (G4) cascade, priced by their own AdmissionGate
    crossover."""

    request_id: str
    token_ids: List[int]
    # SlotSampling fields for the single sampled token (the publish
    # worker samples one token like any prefill; callers usually leave
    # the default greedy)
    sampling: Dict = dataclasses.field(default_factory=dict)
    # distributed-tracing propagation (see RemotePrefillRequest.trace)
    trace: Optional[Dict] = None

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "PrefillPublishRequest":
        return cls(**json.loads(raw))


@dataclasses.dataclass
class KvPayload:
    """Decoded KV handoff: first sampled token + stacked block values."""

    request_id: str
    first_token: int
    first_logprob: float
    seq_hashes: List[int]          # chained hashes of the FULL blocks
    values: Dict[str, np.ndarray]  # {"k": [L, H_kv, n, bs, D], "v": ...}


def _dtype_of(arr: np.ndarray) -> str:
    return arr.dtype.name  # "bfloat16" round-trips via ml_dtypes


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_kv_payload(payload: KvPayload) -> tuple:
    """→ (header bytes, data bytes) for one TCP DATA frame. The header
    names the pool's key set (sorted) so llama {"k","v"} and MLA latent
    {"kv"} pools share one wire format; all keys share one shape/dtype
    (k/v are twins, MLA has one array)."""
    keys = sorted(payload.values)
    first = payload.values[keys[0]]
    header = json.dumps({
        "request_id": payload.request_id,
        "first_token": payload.first_token,
        "first_logprob": payload.first_logprob,
        "seq_hashes": payload.seq_hashes,
        "shape": list(first.shape),
        "dtype": _dtype_of(first),
        "keys": keys,
    }).encode()
    return header, b"".join(payload.values[k].tobytes() for k in keys)


def decode_kv_payload(header: bytes, data: bytes) -> KvPayload:
    h = json.loads(header)
    shape = tuple(h["shape"])
    dt = _np_dtype(h["dtype"])
    keys = h.get("keys", ["k", "v"])   # absent: pre-"keys" wire frames
    nbytes = int(np.prod(shape)) * dt.itemsize
    values = {key: np.frombuffer(
        data[i * nbytes:(i + 1) * nbytes], dtype=dt).reshape(shape)
        for i, key in enumerate(keys)}
    return KvPayload(
        request_id=h["request_id"], first_token=int(h["first_token"]),
        first_logprob=float(h["first_logprob"]),
        seq_hashes=[int(x) for x in h["seq_hashes"]],
        values=values)
